"""ProcReplicaPool: the serving fleet as real processes, not threads.

serve/pool.py's replicas share one interpreter — a "crash" there is a
simulated state flip. This module lifts the same supervision story onto
spawned PROCESSES (one warmed Engine per process, forced single-device
CPU worlds in the smokes; per-device on a real mesh), so process death
is an actual SIGKILL and the recovery claims are load-bearing:

- each replica child runs `_replica_main`: build the engine from a
  picklable builder, warm through core/excache (a warm cache means
  ZERO backend compiles — the respawn rebirth is a disk read), start a
  `serve.Server` + its own `serve/transport.py` HTTP endpoint on
  127.0.0.1:0, and join the serving generation via
  `resilience/rendezvous.py` (member lease + heartbeat; the first
  cohort assembles the generation with `join`, a respawn re-enters it
  with `attach`);
- the parent routes requests to replicas over real sockets
  (`submit(model, image, deadline_ms=) -> Future`, same contract as
  ReplicaPool, so one Transport fronts either), with admission control
  at the parent edge and the W3C traceparent riding every proxied hop;
- death is detected TWICE: connection loss at request time (the dead
  process's in-flight requests — and only those — fail with a typed,
  retryable `ReplicaLost`) and lease expiry in the monitor thread (a
  hung process stops heartbeating and is declared dead without a
  request having to die first). Both paths journal `replica_lost`,
  respawn a fresh process (same rid, attempt+1), and journal
  `replica_recovered` with the child's warmup stats — the smoke
  asserts `backend_compiles == 0` on the rebirth;
- `SwapController` drives a canary across PROCESSES unchanged: the
  parent holds a warmed template engine (`primary_engine()`), the
  shadow's weights ship to a spawned canary process via a pickle under
  the run dir, `promote_variables` POSTs `/control/promote` to every
  base replica (each hot-swaps via `Engine.set_variables`, zero
  recompiles), and `remove_canary` tears the canary process down.

The parent's ledger holds `accepted == completed + errors + cancelled`
with sheds and refusals counted beside it (`ledger()`), and each child
holds the same invariant at its own edge — the fleetnet smoke
crosschecks client, parent, children, and journal.
"""
from __future__ import annotations

import json
import http.client
import os
import pickle
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

from deep_vision_tpu.obs import locksmith, propagate
from deep_vision_tpu.serve.admission import ShedError
from deep_vision_tpu.serve.engine import Engine, ServeError
from deep_vision_tpu.serve.pool import ReplicaLost
from deep_vision_tpu.serve.queue import DeadlineExceeded
from deep_vision_tpu.serve.slo import SLOTracker

READY_SUFFIX = ".ready.json"

#: a replica process's lifecycle states (the thread pool's vocabulary,
#: minus "warming" being observable only through the ready-file wait)
PROC_STATES = ("spawning", "serving", "draining", "dead")


def _atomic_json(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


# -- the child process ---------------------------------------------------------

def _replica_main(spec: dict) -> None:
    """Entry point of one replica process (multiprocessing spawn target;
    everything it needs rides the picklable `spec` dict). The child is
    a complete single-device serving node: engine + router + HTTP
    transport + membership lease, draining cleanly on SIGTERM."""
    rid = spec["rid"]
    run_dir = spec["run_dir"]
    # membership FIRST (stdlib-only, no jax import yet): the lease must
    # exist while the child pays its jax import + warmup, or the parent
    # would read a slow warmup as a corpse
    from deep_vision_tpu.resilience.rendezvous import Rendezvous

    rdzv = Rendezvous(spec["rdzv_root"], host=rid,
                      heartbeat_s=spec.get("heartbeat_s", 0.5))
    generation = spec.get("generation")
    try:
        if generation is None:
            view = rdzv.join(expect_hosts=spec["expect_hosts"],
                             timeout_s=spec.get("join_timeout_s", 60.0))
        else:
            view = rdzv.attach(generation=generation,
                               timeout_s=spec.get("join_timeout_s", 60.0))
    except Exception:
        rdzv.leave()
        raise
    from deep_vision_tpu.obs.journal import RunJournal
    from deep_vision_tpu.obs.registry import Registry
    from deep_vision_tpu.resilience import faults
    from deep_vision_tpu.serve.router import Server
    from deep_vision_tpu.serve.transport import Transport

    registry = Registry()
    journal = RunJournal(os.path.join(
        run_dir, f"replica-{rid}-a{spec['attempt']}.jsonl"), kind="serve")
    excache = None
    if spec.get("excache_dir"):
        from deep_vision_tpu.core.excache import ExecutableCache

        excache = ExecutableCache(spec["excache_dir"], journal=journal,
                                  registry=registry)
    builder = spec["builder"]
    engine = builder(journal=journal, registry=registry, excache=excache,
                     **(spec.get("builder_kwargs") or {}))
    stats = engine.warmup()
    overlay = spec.get("variables_path")
    if overlay:
        # a canary child (or a respawn after a promote) serves the
        # shipped weights, not the builder's: same aval-validated
        # hot-swap path a live promote uses
        with open(overlay, "rb") as f:
            variables_by_model = pickle.load(f)
        for name, variables in variables_by_model.items():
            if name in engine.models:
                engine.set_variables(name, variables)
    server = Server(engine, journal=journal, registry=registry,
                    max_wait_ms=spec.get("max_wait_ms", 2.0),
                    slo_ms=spec.get("slo_ms"),
                    health_policy=spec.get("health_policy", "warn"),
                    tags={"replica": rid}).start()
    backend = _ChildBackend(server)
    transport = Transport(backend, port=0, journal=journal,
                          registry=registry,
                          controls={"promote": backend.promote}).start()
    _atomic_json(os.path.join(run_dir, f"replica-{rid}{READY_SUFFIX}"), {
        "rid": rid, "attempt": spec["attempt"], "pid": os.getpid(),
        "port": transport.port, "generation": view.generation,
        "warmup": {k: stats[k] for k in
                   ("models", "pairs", "backend_compiles", "cache_hits")},
        "ts": time.time(),
    })
    server.install_sigterm()
    server.wait_for_stop()
    # SIGTERM (or a parent-driven drain): flush in-flight, drop the
    # lease cleanly so the monitor sees a departure, not a corpse
    transport.close()
    server.drain("sigterm")
    rdzv.leave()
    journal.close()
    # faults kept imported so the env-inherited spec (DVT_FAULT_SPEC)
    # is armed in this process from the first request on
    del faults


class _ChildBackend:
    """The replica child's view of its own Server: fires the
    `serve.replica` fault at the request boundary (the `crash` kind now
    kills a REAL process) and hosts the promote control verb."""

    def __init__(self, server):
        self.server = server
        self.engine = server.engine

    def submit(self, model, image, deadline_ms=None):
        from deep_vision_tpu.resilience import faults

        faults.fire("serve.replica")
        return self.server.submit(model, image, deadline_ms=deadline_ms)

    def healthz(self):
        return self.server.healthz()

    def queue_depth(self, model):
        return self.server.queue_depth(model)

    def counts(self):
        return self.server.counts()

    def telemetry_status(self):
        return self.server.telemetry_status()

    def promote(self, payload: dict) -> dict:
        """POST /control/promote {"path": <pickle>}: hot-swap the
        shipped weights into this process's engine (aval-validated,
        zero recompiles — Engine.set_variables)."""
        with open(payload["path"], "rb") as f:
            variables_by_model = pickle.load(f)
        swapped = []
        for name, variables in variables_by_model.items():
            if name in self.engine.models:
                self.engine.set_variables(name, variables)
                swapped.append(name)
        return {"models": sorted(swapped)}


# -- the parent-side pool ------------------------------------------------------

class _ProcSlot:
    """Parent-side record of one replica process."""

    __slots__ = ("rid", "proc", "port", "attempt", "state", "warmup",
                 "canary", "completed", "errors", "latencies_by_model",
                 "generation")

    def __init__(self, rid: str, canary: bool = False):
        self.rid = rid
        self.proc = None
        self.port: Optional[int] = None
        self.attempt = 0
        self.state = "spawning"
        self.warmup: Optional[dict] = None
        self.canary = canary
        self.completed = 0
        self.errors = 0
        self.latencies_by_model: Dict[str, List[float]] = {}
        self.generation: Optional[int] = None


class ProcReplicaPool:
    """N replica PROCESSES behind one submit() — the ReplicaPool
    contract over real sockets.

    Wire-up (what tools/fleetnet_smoke.py does)::

        pool = ProcReplicaPool(builder, replicas=3, run_dir=run_dir,
                               excache_dir=cache_dir, journal=journal,
                               admission=AdmissionController(...))
        pool.start()                      # spawn + wait ready
        fut = pool.submit("toy", image)   # proxied over HTTP
        ...
        pool.drain("close")               # SIGTERM children, fold ledgers

    `builder(journal=, registry=, excache=, **kwargs) -> Engine` must be
    a MODULE-LEVEL callable (spawn pickles it by reference); the parent
    calls it too, for the warmed template engine that seeds the
    executable cache (children then warm at zero backend compiles) and
    gives SwapController its `primary_engine()`.
    """

    def __init__(self, builder: Callable, replicas: int = 2,
                 run_dir: str = ".", excache_dir: Optional[str] = None,
                 journal=None, registry=None, admission=None,
                 builder_kwargs: Optional[dict] = None,
                 slo_ms: Optional[float] = None,
                 max_wait_ms: float = 2.0,
                 heartbeat_s: float = 0.5,
                 ready_timeout_s: float = 90.0,
                 max_respawns: int = 2,
                 monitor_poll_s: float = 0.25,
                 request_timeout_s: float = 30.0,
                 max_inflight: int = 64):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.builder = builder
        self.builder_kwargs = dict(builder_kwargs or {})
        self.n_replicas = int(replicas)
        self.run_dir = run_dir
        self.rdzv_root = os.path.join(run_dir, "rdzv")
        self.excache_dir = excache_dir
        self.journal = journal
        self.admission = admission
        self.slo_ms = slo_ms
        self.max_wait_ms = float(max_wait_ms)
        self.heartbeat_s = float(heartbeat_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.max_respawns = int(max_respawns)
        self.monitor_poll_s = float(monitor_poll_s)
        self.request_timeout_s = float(request_timeout_s)
        if registry is None:
            from deep_vision_tpu.obs.registry import get_registry

            registry = get_registry()
        self.registry = registry
        self.slo = SLOTracker(registry=registry, slo_ms=slo_ms)
        self._lock = locksmith.lock("serve.procpool")
        self._slots: Dict[str, _ProcSlot] = {}
        self._canary: Optional[_ProcSlot] = None
        self._canary_pct = 0
        self._rr = 0
        self._seq = 0
        self.accepted = 0
        self.completed = 0
        self.errors = 0
        self.cancelled = 0
        self.sheds = 0
        self.refused = 0
        self._started = False
        self._draining = False
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._pool = ThreadPoolExecutor(
            max_workers=int(max_inflight), thread_name_prefix="procpool")
        self._template: Optional[Engine] = None
        self._promoted_path: Optional[str] = None
        # a read-only rendezvous handle: the parent never writes a
        # member lease, it only reads the children's
        from deep_vision_tpu.resilience.rendezvous import Rendezvous

        self._rdzv = Rendezvous(self.rdzv_root, host="fleet-parent",
                                heartbeat_s=self.heartbeat_s)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ProcReplicaPool":
        if self._started:
            return self
        os.makedirs(self.rdzv_root, exist_ok=True)
        # the template engine warms FIRST: with an excache attached it
        # populates the cache, so every child (and every respawn) warms
        # at zero backend compiles — the parent pays the one compile
        excache = None
        if self.excache_dir:
            from deep_vision_tpu.core.excache import ExecutableCache

            excache = ExecutableCache(self.excache_dir,
                                      journal=self.journal,
                                      registry=self.registry)
        self._template = self.builder(journal=self.journal,
                                      registry=self.registry,
                                      excache=excache,
                                      **self.builder_kwargs)
        self.template_warmup = self._template.warmup()
        for i in range(self.n_replicas):
            rid = f"p{i}"
            slot = _ProcSlot(rid)
            self._slots[rid] = slot
            self._spawn(slot, generation=None)
        deadline = time.monotonic() + self.ready_timeout_s
        for slot in self._slots.values():
            self._wait_ready(slot, deadline)
        self._started = True
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="procpool-monitor",
                                         daemon=True)
        self._monitor.start()
        return self

    def _spawn(self, slot: _ProcSlot, generation: Optional[int]) -> None:
        import multiprocessing as mp

        slot.attempt += 1
        slot.state = "spawning"
        slot.port = None
        # a stale ready file from the previous incarnation must never
        # be mistaken for the new one's
        try:
            os.remove(self._ready_path(slot.rid))
        except OSError:
            pass
        spec = {
            "rid": slot.rid, "attempt": slot.attempt,
            "run_dir": self.run_dir, "rdzv_root": self.rdzv_root,
            "excache_dir": self.excache_dir, "builder": self.builder,
            "builder_kwargs": self.builder_kwargs,
            "heartbeat_s": self.heartbeat_s,
            "expect_hosts": self.n_replicas,
            "generation": generation,
            "slo_ms": self.slo_ms, "max_wait_ms": self.max_wait_ms,
            "variables_path": self._promoted_path,
        }
        if slot.canary:
            # a canary never joins the base generation — it forms a
            # one-member world under its OWN rendezvous root (joining
            # the shared root would leave it waiting to be adopted by a
            # resize the base fleet never runs)
            spec["generation"] = None
            spec["expect_hosts"] = 1
            spec["rdzv_root"] = self.rdzv_root + "-canary"
            os.makedirs(spec["rdzv_root"], exist_ok=True)
        ctx = mp.get_context("spawn")
        slot.proc = ctx.Process(target=_replica_main, args=(spec,),
                                name=f"replica-{slot.rid}", daemon=True)
        slot.proc.start()

    def _ready_path(self, rid: str) -> str:
        return os.path.join(self.run_dir, f"replica-{rid}{READY_SUFFIX}")

    def _wait_ready(self, slot: _ProcSlot, deadline: float) -> None:
        path = self._ready_path(slot.rid)
        while time.monotonic() < deadline:
            rec = None
            try:
                with open(path) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                rec = None
            if rec and rec.get("attempt") == slot.attempt:
                slot.port = int(rec["port"])
                slot.warmup = rec.get("warmup")
                slot.generation = rec.get("generation")
                slot.state = "serving"
                return
            if slot.proc is not None and not slot.proc.is_alive():
                raise ServeError(
                    f"replica {slot.rid} died during warmup "
                    f"(exitcode={slot.proc.exitcode})")
            time.sleep(0.05)
        raise ServeError(
            f"replica {slot.rid} not ready within "
            f"{self.ready_timeout_s:.0f}s")

    # -- request path ------------------------------------------------------

    def submit(self, model: str, image,
               deadline_ms: Optional[float] = None) -> Future:
        """Admit at the parent edge, pick a replica, proxy over its
        socket. ShedError is synchronous (no Future on shed, the
        ReplicaPool contract); everything request-scoped — including a
        SIGKILLed replica mid-request — comes back on the Future."""
        if not self._started:
            raise ServeError("submit() before start(): no replicas are up")
        self.slo.offered(model)
        with self._lock:
            if self._draining:
                reason: Optional[str] = "draining"
            elif self.admission is not None:
                reason = self.admission.admit(model, self._pool._work_queue
                                              .qsize())
            else:
                reason = None
            slot = None if reason is not None else self._route()
            if reason is None and slot is None:
                self.refused += 1
            if reason is None and slot is not None:
                self.accepted += 1
        if reason is not None:
            self.sheds += 1
            self.slo.shed(model, reason)
            if self.journal is not None:
                self.journal.write("serve_shed", model=model, reason=reason)
            raise ShedError(model, reason)
        if slot is None:
            self.slo.refused(model)
            raise ServeError(
                f"no serving replicas for {model!r} "
                f"({self.replica_states()})")
        ctx = propagate.current()
        fut: Future = Future()
        self._pool.submit(self._proxy_call, slot, model, image,
                          deadline_ms, ctx, fut,
                          time.perf_counter())
        return fut

    def _route(self) -> Optional[_ProcSlot]:
        """Round-robin over serving base replicas; the canary takes its
        diverted percentage first (deterministic modulo — the verdict
        sample accrues at the configured rate, not by luck)."""
        self._seq += 1
        # (seq*pct) % 100 < pct spreads the diverted requests EVENLY
        # through the stream (pct=50 -> every other request) instead of
        # taking the first pct of every hundred as one burst
        if (self._canary is not None and self._canary.state == "serving"
                and self._canary_pct > 0
                and (self._seq * self._canary_pct) % 100 < self._canary_pct):
            return self._canary
        serving = [s for s in self._slots.values()
                   if s.state == "serving" and not s.canary]
        if not serving:
            return None
        self._rr = (self._rr + 1) % len(serving)
        return serving[self._rr]

    def _proxy_call(self, slot: _ProcSlot, model: str, image,
                    deadline_ms: Optional[float], ctx, fut: Future,
                    t0: float) -> None:
        """One proxied request on a worker thread; resolves `fut` with
        the child's answer or the typed failure. Runs the whole
        status-code contract in reverse: the child's HTTP verdict maps
        back onto the exceptions in-process callers already handle."""
        if not fut.set_running_or_notify_cancel():
            self._account(slot, model, "cancelled", t0)
            return
        try:
            row = self._http_infer(slot, model, image, deadline_ms, ctx)
        except Exception as e:
            self._account(slot, model, "error", t0)
            fut.set_exception(e)
            if isinstance(e, ReplicaLost):
                self._suspect(slot)
            return
        self._account(slot, model, "ok", t0)
        fut.set_result(row)

    def _account(self, slot: _ProcSlot, model: str, outcome: str,
                 t0: float) -> None:
        latency_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            if outcome == "ok":
                self.completed += 1
                slot.completed += 1
                slot.latencies_by_model.setdefault(model, []).append(
                    latency_ms)
            elif outcome == "cancelled":
                self.cancelled += 1
            else:
                self.errors += 1
                slot.errors += 1
        self.slo.request_done(model, latency_ms, outcome)

    def _http_infer(self, slot: _ProcSlot, model: str, image,
                    deadline_ms: Optional[float], ctx) -> dict:
        body = json.dumps(
            {"image": image.tolist() if hasattr(image, "tolist")
             else image}).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        if deadline_ms is not None:
            headers["X-DVT-Deadline-Ms"] = f"{deadline_ms:.3f}"
        if ctx is not None:
            headers["traceparent"] = ctx.to_traceparent()
        conn = http.client.HTTPConnection(
            "127.0.0.1", slot.port, timeout=self.request_timeout_s)
        try:
            try:
                conn.request("POST", f"/v1/{model}", body=body,
                             headers=headers)
                resp = conn.getresponse()
                payload = json.loads(resp.read().decode("utf-8"))
            except (OSError, http.client.HTTPException, ValueError) as e:
                # connection loss IS the death signal for in-flight
                # requests: typed, retryable, scoped to this request
                raise ReplicaLost(
                    f"replica {slot.rid} connection lost mid-request "
                    f"({type(e).__name__}: {e})")
            if resp.status == 200:
                return payload.get("outputs", payload)
            reason = payload.get("reason")
            if resp.status in (429, 503) and reason:
                raise ShedError(model, reason)
            if resp.status == 504:
                raise DeadlineExceeded(
                    f"deadline shed at {payload.get('stage', '?')} on "
                    f"replica {slot.rid}")
            raise ServeError(
                f"replica {slot.rid} answered {resp.status}: "
                f"{payload.get('detail', payload)}")
        finally:
            conn.close()

    # -- death detection + respawn ----------------------------------------

    def _suspect(self, slot: _ProcSlot) -> None:
        """Request-path death report (connection loss): flip the slot
        out of the routing set NOW; the monitor confirms and respawns."""
        with self._lock:
            if slot.state == "serving":
                slot.state = "dead"

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.monitor_poll_s):
            for slot in list(self._slots.values()):
                if slot.state not in ("serving", "dead"):
                    continue
                dead = slot.state == "dead"
                if not dead and slot.proc is not None \
                        and not slot.proc.is_alive():
                    dead = True  # the waitpid truth: connection loss's
                    # parent-side twin
                if not dead:
                    gap = self._rdzv.lease_gap(slot.rid)
                    if gap is not None and gap > self._rdzv.lease_s:
                        dead = True  # lease expiry: a HUNG process
                        # stops heartbeating long before it stops
                        # holding its socket open
                if not dead:
                    continue
                with self._lock:
                    slot.state = "dead"
                self._handle_lost(slot)
            if self._draining:
                return

    def _handle_lost(self, slot: _ProcSlot) -> None:
        if self.journal is not None:
            self.journal.write("replica_lost", replica=slot.rid,
                              attempt=slot.attempt)
        self.registry.counter("serve_replica_lost_total",
                              "replica processes lost",
                              labels={"replica": slot.rid}).inc()
        if slot.canary or self._draining \
                or slot.attempt > self.max_respawns:
            return
        try:
            self._spawn(slot, generation=slot.generation)
            self._wait_ready(slot,
                             time.monotonic() + self.ready_timeout_s)
        except Exception as e:
            with self._lock:
                slot.state = "dead"
            if self.journal is not None:
                self.journal.write("note", note="respawn_failed",
                                  replica=slot.rid,
                                  error=f"{type(e).__name__}: {e}"[:200])
            return
        if self.journal is not None:
            self.journal.write("replica_recovered", replica=slot.rid,
                              attempt=slot.attempt, **(slot.warmup or {}))

    # -- fleet introspection ----------------------------------------------

    def primary_engine(self) -> Engine:
        """The parent's warmed template engine — SwapController's
        reference for aval validation, shadow cloning, and probes."""
        if self._template is None:
            raise ServeError("primary_engine() before start()")
        return self._template

    def replica_states(self) -> Dict[str, str]:
        with self._lock:
            out = {rid: s.state for rid, s in self._slots.items()}
            if self._canary is not None:
                out[self._canary.rid] = self._canary.state
            return out

    def warmup_stats(self) -> Dict[str, dict]:
        """Per-replica warmup reports from the ready files (the
        zero-compile respawn assertion reads backend_compiles here)."""
        with self._lock:
            return {rid: dict(s.warmup or {})
                    for rid, s in self._slots.items()}

    def healthz(self):
        states = self.replica_states()
        serving = sum(1 for s in states.values() if s == "serving")
        ok = self._started and not self._draining and serving > 0
        return ok, {"replicas": states, "serving": serving,
                    "draining": self._draining}

    def telemetry_status(self) -> dict:
        out = dict(self.counts())
        out["sheds"] = self.sheds
        out["refused"] = self.refused
        out["replicas"] = self.replica_states()
        try:
            out["slo"] = self.slo.report()
        except Exception:
            pass
        return out

    def counts(self) -> dict:
        with self._lock:
            return {"accepted": self.accepted, "completed": self.completed,
                    "errors": self.errors, "cancelled": self.cancelled}

    def ledger(self) -> dict:
        """The fleet ledger + its invariant: every offered request is
        accepted, shed, or refused, and every accepted one lands in
        exactly one of completed/errors/cancelled."""
        with self._lock:
            counts = {"accepted": self.accepted,
                      "completed": self.completed, "errors": self.errors,
                      "cancelled": self.cancelled, "shed": self.sheds,
                      "refused": self.refused}
        counts["pending"] = (counts["accepted"] - counts["completed"]
                             - counts["errors"] - counts["cancelled"])
        counts["balanced"] = counts["pending"] >= 0
        return counts

    def queue_depth(self, model: str) -> int:
        """Admission input when a Transport fronts this pool directly:
        parent-side in-flight dispatch backlog."""
        return self._pool._work_queue.qsize()

    # -- canary swap across processes (SwapController's surface) -----------

    def add_canary(self, engine: Engine, pct: int) -> str:
        """Mount a canary PROCESS serving `engine`'s weights for `pct`%
        of traffic. The engine is the SwapController's shadow (parent-
        side); its variables ship to the spawned child via a pickle
        under the run dir and load through the same aval-validated
        set_variables path a promote uses."""
        if not 0 < pct <= 100:
            raise ValueError(f"canary pct must be in (0, 100], got {pct}")
        with self._lock:
            if self._canary is not None:
                raise ServeError("a canary is already mounted")
        path = os.path.join(self.run_dir, "canary-variables.pkl")
        variables_by_model = {name: engine.entry(name).variables
                              for name in engine.models}
        with open(path, "wb") as f:
            pickle.dump(variables_by_model, f)
        slot = _ProcSlot("canary", canary=True)
        prev_promoted = self._promoted_path
        self._promoted_path = path
        try:
            self._spawn(slot, generation=None)
            self._wait_ready(slot,
                             time.monotonic() + self.ready_timeout_s)
        finally:
            self._promoted_path = prev_promoted
        with self._lock:
            self._canary = slot
            self._canary_pct = int(pct)
        return slot.rid

    def canary_status(self) -> Optional[dict]:
        with self._lock:
            slot = self._canary
        if slot is None:
            return None
        state = slot.state
        if slot.proc is not None and not slot.proc.is_alive():
            state = "dead"
        with self._lock:
            lat = {m: sorted(v)
                   for m, v in slot.latencies_by_model.items()}
            out = {"replica": slot.rid, "state": state,
                   "accepted": slot.completed + slot.errors,
                   "completed": slot.completed, "errors": slot.errors,
                   "cancelled": 0}
        out["slo"] = {
            m: {"p99_ms": v[min(len(v) - 1, int(0.99 * len(v)))]}
            for m, v in lat.items() if v}
        return out

    def remove_canary(self) -> Optional[dict]:
        with self._lock:
            slot, self._canary = self._canary, None
            self._canary_pct = 0
        if slot is None:
            return None
        slot.state = "draining"
        summary = self._terminate(slot)
        slot.state = "dead"
        return summary

    def promote_variables(self, variables_by_model: dict) -> None:
        """Ship the new weights to every base replica process (POST
        /control/promote -> Engine.set_variables: zero recompiles) and
        to the parent template; a replica respawned later loads the
        same pickle, so the promoted weights survive process death."""
        path = os.path.join(self.run_dir, "promoted-variables.pkl")
        with open(path, "wb") as f:
            pickle.dump(variables_by_model, f)
        self._promoted_path = path
        for name, variables in variables_by_model.items():
            self._template.set_variables(name, variables)
        failures = []
        with self._lock:
            slots = [s for s in self._slots.values()
                     if s.state == "serving"]
        for slot in slots:
            try:
                self._control(slot, "promote", {"path": path})
            except Exception as e:
                failures.append(f"{slot.rid}: {type(e).__name__}: {e}")
        if failures:
            raise ServeError(
                f"promote failed on {len(failures)} replica(s): "
                + "; ".join(failures))

    def _control(self, slot: _ProcSlot, verb: str, payload: dict) -> dict:
        conn = http.client.HTTPConnection(
            "127.0.0.1", slot.port, timeout=self.request_timeout_s)
        try:
            conn.request("POST", f"/control/{verb}",
                         body=json.dumps(payload).encode("utf-8"),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            out = json.loads(resp.read().decode("utf-8"))
            if resp.status != 200 or not out.get("ok"):
                raise ServeError(
                    f"control {verb} on {slot.rid} answered "
                    f"{resp.status}: {out}")
            return out
        finally:
            conn.close()

    # -- drain / shutdown --------------------------------------------------

    def _terminate(self, slot: _ProcSlot,
                   timeout_s: float = 15.0) -> Optional[dict]:
        """SIGTERM one child (its Server drains in-process), reap it,
        return its final edge ledger when reachable."""
        summary = None
        try:
            summary = self._ledgerz(slot)
        except Exception:
            pass
        proc = slot.proc
        if proc is not None and proc.is_alive():
            proc.terminate()
            proc.join(timeout=timeout_s)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=5.0)
        return summary

    def _ledgerz(self, slot: _ProcSlot) -> dict:
        conn = http.client.HTTPConnection("127.0.0.1", slot.port,
                                          timeout=5.0)
        try:
            conn.request("GET", "/ledgerz")
            return json.loads(conn.getresponse().read().decode("utf-8"))
        finally:
            conn.close()

    def child_ledgers(self) -> Dict[str, dict]:
        """Each live child's transport ledger (the smoke's cross-process
        crosscheck input)."""
        out = {}
        with self._lock:
            slots = [s for s in self._slots.values()
                     if s.state == "serving"]
        for slot in slots:
            try:
                out[slot.rid] = self._ledgerz(slot)
            except Exception:
                pass
        return out

    def drain(self, reason: str = "close") -> dict:
        """Stop admitting, drain every child (SIGTERM -> in-process
        flush), fold the fleet ledger into one journaled summary."""
        with self._lock:
            if self._draining:
                return getattr(self, "_drain_summary", {})
            self._draining = True
        t0 = time.monotonic()
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        if self._canary is not None:
            self.remove_canary()
        for slot in self._slots.values():
            if slot.state == "serving":
                slot.state = "draining"
            self._terminate(slot)
            slot.state = "dead"
        self._pool.shutdown(wait=True)
        counts = self.counts()
        pending = (counts["accepted"] - counts["completed"]
                   - counts["errors"] - counts["cancelled"])
        # drain_s feeds the goodput plane's drain bucket: offline
        # attribution (obs/goodput.py) carves exactly this much of the
        # gap before the serve_drain row out of overhead
        summary = {"reason": reason,
                   "outcome": "flushed" if pending == 0 else "timeout",
                   **counts, "pending": max(0, pending),
                   "shed": self.sheds, "refused": self.refused,
                   "replicas": len(self._slots),
                   "drain_s": round(time.monotonic() - t0, 3)}
        if self.journal is not None:
            self.journal.write("serve_drain", scope="pool", **summary)
        self._drain_summary = summary
        return summary

    def close(self) -> dict:
        return self.drain("close")
