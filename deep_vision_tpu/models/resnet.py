"""ResNet-34/50/152 (He 2015) and ResNet-50 V2 (pre-activation, He 2016).

Parity targets: ResNet/pytorch/models/resnet50.py (BottleneckBlock +
projection shortcut, Kaiming init at resnet50.py:84-93), resnet34.py (basic
blocks), resnet152.py (3/8/36/3), and the pre-activation
ResNet/tensorflow/models/resnet50v2.py:11-12. NHWC, he_normal init, BN with
global-batch statistics under pjit (synced BN by construction).

The flagship model of the framework: `resnet50` is the benchmark target
(BASELINE.json: top-1 >= 75.3% on v5e-8 at >= 0.9x A100x8 images/sec).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import ConvBN, FusedBatchNorm, global_avg_pool


class BasicBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = ConvBN(self.features, (3, 3), strides=self.strides, dtype=self.dtype)(x, train)
        # tail: BN + skip-add + ReLU fold into one pass (nn/layers.py
        # ConvBN residual arg -> ops/pallas/bn_act.py on TPU). Constructed
        # before the projection so flax auto-names (ConvBN_1 here, ConvBN_2
        # for the projection) — and with them every checkpoint — keep the
        # exact pre-fusion variable-tree paths.
        tail = ConvBN(self.features, (3, 3), act=nn.relu, dtype=self.dtype)
        if x.shape[-1] != self.features or self.strides != (1, 1):
            residual = ConvBN(
                self.features, (1, 1), strides=self.strides, act=None, dtype=self.dtype
            )(x, train)
        return tail(y, train, residual=residual)


class BottleneckBlock(nn.Module):
    features: int  # bottleneck width; output is 4x
    strides: Tuple[int, int] = (1, 1)
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = ConvBN(self.features, (1, 1), dtype=self.dtype)(x, train)
        y = ConvBN(self.features, (3, 3), strides=self.strides, dtype=self.dtype)(y, train)
        # zero-init the last BN scale so each block starts as identity
        # (standard TPU ResNet recipe; improves large-batch training)
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        # the block tail — BN apply + skip-add + ReLU — is ONE fused pass on
        # TPU (ops/pallas/bn_act.py; act/residual args on nn/layers.py
        # BatchNorm). Constructed before the projection ConvBN so flax
        # auto-names (BatchNorm_0, ConvBN_2) keep the pre-fusion
        # variable-tree paths and checkpoints stay interchangeable.
        bn = FusedBatchNorm(
            use_running_average=not train,
            momentum=0.9,
            scale_init=nn.initializers.zeros_init(),
            act="relu",
        )
        if x.shape[-1] != self.features * 4 or self.strides != (1, 1):
            residual = ConvBN(
                self.features * 4, (1, 1), strides=self.strides, act=None, dtype=self.dtype
            )(x, train)
        return bn(y, residual=residual)


class PreActBottleneckBlock(nn.Module):
    """ResNet V2: BN-ReLU-Conv ordering (resnet50v2.py cites He 2016)."""

    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        pre = FusedBatchNorm(use_running_average=not train, momentum=0.9, dtype=self.dtype)(x)
        pre = nn.relu(pre)
        needs_proj = x.shape[-1] != self.features * 4 or self.strides != (1, 1)
        residual = (
            nn.Conv(self.features * 4, (1, 1), strides=self.strides, use_bias=False,
                    dtype=self.dtype)(pre)
            if needs_proj
            else x
        )
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(pre)
        y = ConvBN(self.features, (3, 3), strides=self.strides, dtype=self.dtype)(y, train)
        y = FusedBatchNorm(use_running_average=not train, momentum=0.9, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        return y + residual


class SpaceToDepthStem(nn.Module):
    """The 7x7/s2 stem conv on space-to-depth input: MXU-efficient, math-equal.

    A 7x7 stride-2 conv on a 3-channel image is the least efficient conv on a
    TPU: the 3-channel input wastes the 128-wide lane tiling and the profiler
    shows it HBM-bound well below peak bandwidth. The MLPerf-ResNet trick:
    the host pipeline lays the image out as (H/2, W/2, 12) (space_to_depth,
    see data/transforms.py SpaceToDepth), and the stem becomes a 4x4 stride-1
    conv over 12 channels — *mathematically identical* to the 7x7/s2 conv
    because the 7x7 kernel zero-pads to 8x8 and reshuffles into (4, 4, 12).
    The parameter keeps the canonical (7, 7, 3, 64) shape: the kernel values
    are interchangeable with a conv7 stem's, though the variable-tree paths
    differ (SpaceToDepthStem_0/kernel vs ConvBN_0/Conv_0/kernel), so moving a
    checkpoint between stems requires remapping those two paths.
    """

    features: int = 64
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        c_in = x.shape[-1] // 4  # input is (H/2, W/2, 4*C)
        w = self.param(
            "kernel", nn.initializers.he_normal(), (7, 7, c_in, self.features),
            jnp.float32,
        )
        # pad 7x7 -> 8x8 at the top-left: kernel tap k maps to original
        # offset k-1, with k=0 the zero row (see derivation: original row
        # index = 2(i - 2) + k  vs  2i - 4 + k for the 7x7/s2 at pad 3)
        k8 = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
        w2 = (
            k8.reshape(4, 2, 4, 2, c_in, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 4 * c_in, self.features)
        )
        dt = self.dtype or x.dtype
        return jax.lax.conv_general_dilated(
            x.astype(dt),
            w2.astype(dt),
            window_strides=(1, 1),
            padding=((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: type = BottleneckBlock
    num_classes: int = 1000
    width: int = 64
    preact: bool = False
    stem: str = "conv7"  # "conv7" (B,H,W,3) | "s2d" (B,H/2,W/2,12) input
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.stem == "s2d":
            x = SpaceToDepthStem(64, dtype=self.dtype)(x)
            if not self.preact:
                x = nn.relu(
                    FusedBatchNorm(use_running_average=not train, momentum=0.9)(x)
                )
        elif self.preact:
            x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=self.dtype)(x)
        else:
            x = ConvBN(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                       dtype=self.dtype)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, n_blocks in enumerate(self.stage_sizes):
            features = self.width * (2**i)
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(features, strides=strides, dtype=self.dtype)(x, train)
        if self.preact:
            x = nn.relu(FusedBatchNorm(use_running_average=not train, momentum=0.9,
                                     dtype=self.dtype)(x))
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


@register_model("resnet34")
def resnet34(num_classes: int = 1000, dtype=None, stem: str = "conv7", **_):
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BasicBlock,
                  num_classes=num_classes, stem=stem, dtype=dtype)


@register_model("resnet50")
def resnet50(num_classes: int = 1000, dtype=None, stem: str = "conv7", **_):
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BottleneckBlock,
                  num_classes=num_classes, stem=stem, dtype=dtype)


@register_model("resnet152")
def resnet152(num_classes: int = 1000, dtype=None, stem: str = "conv7", **_):
    return ResNet(stage_sizes=(3, 8, 36, 3), block=BottleneckBlock,
                  num_classes=num_classes, stem=stem, dtype=dtype)


@register_model("resnet50v2")
def resnet50v2(num_classes: int = 1000, dtype=None, stem: str = "conv7", **_):
    return ResNet(stage_sizes=(3, 4, 6, 3), block=PreActBottleneckBlock,
                  num_classes=num_classes, preact=True, stem=stem, dtype=dtype)
