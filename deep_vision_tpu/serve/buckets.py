"""Batch-shape bucketing: the anti-recompile contract of the server.

XLA compiles one executable per input shape. A server that batches
"however many requests happened to be waiting" presents a new batch
dimension every few milliseconds and spends its life in the compiler —
the ORCA/Clipper-era fix is a small fixed menu of batch sizes: coalesced
requests round UP to the smallest warmed bucket, the tail rows are
zero-padded, and the padded rows are sliced off before anyone sees them.
Every predictor in this repo is batch-independent (per-example decode /
NMS / argmax), so padding rows cannot perturb real rows; tests prove the
padded result equals the unpadded reference bitwise.

Host-side and jax-free at import (like obs/registry.py): padding is
numpy on the request thread, device work stays in serve/engine.py.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

#: default batch-size menu; powers of two keep the warmup cost log(max)
DEFAULT_BUCKETS = (1, 2, 4, 8)


def normalize_buckets(buckets: Sequence[int]) -> Tuple[int, ...]:
    """Sorted unique positive bucket sizes; rejects an empty/invalid menu
    loudly — a typo'd bucket list must not become a server that can
    never warm anything."""
    out = sorted({int(b) for b in buckets})
    if not out or out[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets!r}")
    return tuple(out)


def bucket_for(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket >= n, or None when n exceeds the largest bucket
    (the queue caps batches at max(buckets), so a live server never sees
    None — it exists for callers probing the menu)."""
    for b in buckets:
        if b >= n:
            return int(b)
    return None


def pad_batch(images: List[np.ndarray], bucket: int,
              dtype=np.float32) -> np.ndarray:
    """Stack per-request images into a (bucket, *image_shape) array,
    zero-padding rows [len(images), bucket). All images must share one
    shape — spatial bucketing is the model's fixed input_shape contract,
    enforced at submit time (serve/router.py), not here."""
    if not images:
        raise ValueError("pad_batch on an empty request list")
    if len(images) > bucket:
        raise ValueError(f"{len(images)} requests do not fit bucket {bucket}")
    shape = images[0].shape
    for im in images[1:]:
        if im.shape != shape:
            raise ValueError(
                f"mixed image shapes in one batch: {im.shape} vs {shape}")
    out = np.zeros((bucket,) + tuple(shape), dtype=dtype)
    for i, im in enumerate(images):
        out[i] = im
    return out


def split_rows(tree, n: int) -> List[dict]:
    """Batched output pytree (dict of (bucket, ...) host arrays) -> one
    dict per real request, padded rows discarded. Row i keeps no batch
    dim: a client asked about one image and gets one answer."""
    keys = list(tree)
    return [{k: tree[k][i] for k in keys} for i in range(n)]
