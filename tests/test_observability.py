"""SummaryWriter event-file format + MetricLogger integration + profiler hook."""
import os

import numpy as np
import pytest

from deep_vision_tpu.core.metrics import MetricLogger
from deep_vision_tpu.core.tensorboard import SummaryWriter

try:
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader,
    )

    HAS_TB = True
except Exception:
    HAS_TB = False


def test_summary_writer_records_parse(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.scalar("train/loss", 1.5, 10)
    w.scalar("val/top1", 0.75, 20)
    w.close()
    from deep_vision_tpu.data.records import read_records

    events = list(read_records(w.path))
    assert len(events) == 3  # file_version + 2 scalars
    assert b"brain.Event:2" in events[0]
    assert b"train/loss" in events[1]


@pytest.mark.skipif(not HAS_TB, reason="tensorboard package unavailable")
def test_summary_writer_tensorboard_cross_parity(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.scalar("loss", 2.25, 7)
    w.close()
    events = [e for e in EventFileLoader(w.path).Load()]
    scalar_events = [e for e in events if e.summary.value]
    assert len(scalar_events) == 1
    (e,) = scalar_events
    assert e.step == 7
    v = e.summary.value[0]
    assert v.tag == "loss"
    # the loader's data_compat pass migrates simple_value -> tensor.float_val
    got = v.simple_value or v.tensor.float_val[0]
    assert got == pytest.approx(2.25)


def test_metric_logger_writes_tb(tmp_path):
    w = SummaryWriter(str(tmp_path))
    lg = MetricLogger(tb_writer=w, name="train", print_every=0)
    lg.start_epoch()
    lg.log_step(1, {"loss": 3.0}, batch_size=4, epoch=0)
    summary = lg.end_epoch(0)
    w.close()
    assert summary["loss"] == pytest.approx(3.0)
    from deep_vision_tpu.data.records import read_records

    payload = b"".join(read_records(w.path))
    assert b"train/batch_loss" in payload
    assert b"train/epoch_loss" in payload


def test_trainer_profiler_hook(tmp_path, mesh8):
    import jax.numpy as jnp

    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    trainer = Trainer(
        get_model("lenet5", num_classes=4),
        build_optimizer("adam", 1e-3),
        classification_loss_fn,
        jnp.ones((2, 32, 32, 1)),
        mesh=mesh8,
        profile_dir=str(tmp_path / "trace"),
        profile_steps=(1, 3),
    )
    rng = np.random.RandomState(0)
    batch = {"image": rng.rand(8, 32, 32, 1).astype(np.float32),
             "label": rng.randint(0, 4, (8,)).astype(np.int32)}
    for _ in range(5):
        trainer.train_step(batch)
    assert not trainer._profiling
    # a trace directory with at least one .pb/.json artifact was produced
    found = []
    for root, _, files in os.walk(tmp_path / "trace"):
        found += files
    assert found, "profiler produced no trace files"


def test_model_summary_counts():
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.core.summary import count_params, model_summary
    from deep_vision_tpu.models import get_model

    model = get_model("lenet5", num_classes=10)
    text = model_summary(model, jnp.ones((1, 32, 32, 1)))
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
        jnp.ones((1, 32, 32, 1)), train=False,
    )
    n = count_params(variables["params"])
    assert f"trainable params: {n:,}" in text
    # table lists every kernel with its shape
    assert "(5, 5, 1, 6)" in text  # LeNet-5 C1 conv kernel


def test_model_summary_resnet_is_abstract_and_fast():
    import jax.numpy as jnp

    from deep_vision_tpu.core.summary import model_summary
    from deep_vision_tpu.models import get_model

    # eval_shape: no real compute, so a 224x224 ResNet-50 summary is instant
    text = model_summary(
        get_model("resnet50", num_classes=1000), jnp.ones((2, 224, 224, 3)),
        max_rows=5,
    )
    assert "trainable params: 25,5" in text  # ~25.5M
    assert "... " in text  # truncation marker
