"""Compiled-artifact performance attribution: typed perf events + the
live perf status source.

PR 16's telemetry plane answers *is it healthy*; this module answers
*is it fast, and why not*. Wherever the repo already holds a compiled
executable — `Engine.warmup`'s bucket menu, the Trainer's excache/AOT
path, an explicit `Trainer.profile_step` — `profile_compiled` distills
it through obs/costmodel into two typed journal events:

  perf_profile     one per (name) jit pair: XLA cost analysis (flops,
                   bytes accessed, buffer budget) + the collective
                   roll-up (op count, total per-device payload bytes)
  perf_collective  one per (kind, dtype) aggregate: op count, summed
                   bytes, group size — the partitioner's comm bill,
                   itemized

Both are additive observation: every extraction failure degrades to
None/absence, never to a raised exception, so a backend that hides HLO
text costs fields, not warmups.

The module also keeps the process-wide "last known perf state" the
telemetry /statusz page serves (`telemetry_status`): rolling step-time
p50/p95 fed by the Trainer's StepClock histogram, the process recompile
count, the last profile, and the last perf-gate verdict / trace digest
(`note_gate` / `note_digest`, set by tools/perf_gate.py and
tools/trace_digest.py when they run in-process). A live watcher sees
perf drift without waiting for the postmortem report.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional

from deep_vision_tpu.obs import costmodel

__all__ = [
    "profile_compiled",
    "telemetry_status",
    "note_gate",
    "note_digest",
    "set_quantile_source",
]

# last-known perf state for /statusz; one lock, plain dicts only (the
# scraper thread must never touch the device)
from deep_vision_tpu.obs import locksmith

_state_lock = locksmith.lock("obs.perfwatch")
_LAST = {
    "profile": None,   # {"name", "flops", "collective_bytes", ...}
    "gate": None,      # {"verdict", "metric", ...} from tools/perf_gate
    "digest": None,    # {"top_op", "collective_frac", ...} from trace_digest
}
_QUANTILES: Optional[Callable[[], dict]] = None


def profile_compiled(name: str, compiled, journal=None, registry=None,
                     extra: Optional[dict] = None) -> Optional[dict]:
    """Extract + journal the perf profile of one compiled executable.

    Returns {"name", "cost": {...}, "collectives": [op dicts],
    "collective_bytes", "allreduce_bytes"}, or None when nothing could
    be extracted. Never raises.
    """
    try:
        cost = costmodel.cost_summary(compiled)
        hlo = costmodel.hlo_text(compiled)
        inventory = costmodel.collective_inventory(hlo) if hlo else []
        total_bytes = costmodel.predicted_collective_bytes(inventory)
        ar_bytes = costmodel.predicted_collective_bytes(inventory,
                                                        "all-reduce")
        profile = {
            "name": name,
            "cost": cost,
            "collectives": inventory,
            "collective_bytes": int(total_bytes),
            "allreduce_bytes": int(ar_bytes),
        }
        if journal is not None:
            fields = {
                "name": name,
                "flops": cost["flops"],
                "bytes_accessed": cost["bytes_accessed"],
                "argument_bytes": cost["argument_bytes"],
                "output_bytes": cost["output_bytes"],
                "temp_bytes": cost["temp_bytes"],
                "collective_count": len(inventory),
                "collective_bytes": int(total_bytes),
            }
            if extra:
                fields.update(extra)
            journal.write("perf_profile", **fields)
            for agg in _aggregate(inventory):
                journal.write("perf_collective", name=name, **agg)
        if registry is not None:
            try:
                registry.gauge("perfwatch_collective_bytes",
                               "per-device collective payload bytes of the "
                               "last profiled executable",
                               labels={"name": name}).set(total_bytes)
                if cost["flops"] is not None:
                    registry.gauge("perfwatch_flops",
                                   "XLA-estimated flops of the last "
                                   "profiled executable",
                                   labels={"name": name}).set(cost["flops"])
                registry.counter("perfwatch_profiles_total",
                                 "compiled executables profiled").inc()
            except Exception:
                pass
        with _state_lock:
            _LAST["profile"] = {
                "name": name,
                "flops": cost["flops"],
                "collective_count": len(inventory),
                "collective_bytes": int(total_bytes),
            }
        return profile
    except Exception:
        return None


def _aggregate(inventory: List[dict]) -> List[dict]:
    """Per-(kind, dtype) roll-up of an op-level inventory — the
    perf_collective event payloads."""
    by_key: dict = {}
    for c in inventory:
        key = (c["kind"], c.get("dtype") or "unknown")
        agg = by_key.setdefault(key, {
            "kind": c["kind"], "dtype": key[1], "ops": 0, "bytes": 0,
            "group_size": c.get("group_size"),
        })
        agg["ops"] += 1
        agg["bytes"] += int(c["bytes"])
        if agg["group_size"] is None:
            agg["group_size"] = c.get("group_size")
    return [by_key[k] for k in sorted(by_key)]


# -- /statusz state ----------------------------------------------------------


def note_gate(verdict: dict) -> None:
    """Record the latest perf-gate verdict for /statusz (called by
    tools/perf_gate.py after every gate decision)."""
    with _state_lock:
        _LAST["gate"] = dict(verdict)


def note_digest(summary: dict) -> None:
    """Record the latest trace-digest summary for /statusz (called by
    tools/trace_digest.py when it runs in-process)."""
    with _state_lock:
        _LAST["digest"] = dict(summary)


def set_quantile_source(fn: Optional[Callable[[], dict]]) -> None:
    """Install the rolling step-time quantile provider (the Trainer wires
    its StepClock histogram here; the scraper thread then reads plain
    host-side numbers)."""
    global _QUANTILES
    _QUANTILES = fn


def telemetry_status() -> dict:
    """The /statusz "perf" status source: rolling step-time p50/p95,
    process recompile count, last profile / gate verdict / digest."""
    out: dict = {}
    fn = _QUANTILES
    if fn is not None:
        try:
            out.update(fn())
        except Exception:
            pass
    try:
        from deep_vision_tpu.obs.stepclock import recompile_count

        out["recompiles"] = recompile_count()
    except Exception:
        pass
    with _state_lock:
        if _LAST["profile"] is not None:
            out["last_profile"] = dict(_LAST["profile"])
        if _LAST["gate"] is not None:
            out["gate"] = dict(_LAST["gate"])
        if _LAST["digest"] is not None:
            out["digest"] = dict(_LAST["digest"])
    return out


def _reset_for_tests() -> None:
    with _state_lock:
        _LAST["profile"] = _LAST["gate"] = _LAST["digest"] = None
    set_quantile_source(None)
