"""Flight recorder: always-on bounded-memory postmortem capture.

The journal explains a run that finished; this module explains a run
that *died*. The steady-state obs/ stack (journal, spans, health) leaves
only a crash marker at the moment that matters most — production TPU
stacks treat the anomaly itself as the trigger for deep data collection,
and when host 7 of 32 dies at 3am the bundle that explains it must
already exist on disk.

A `FlightRecorder` keeps ring buffers (bounded memory, O(1) per event)
of the recent past:

  steps          the last N per-step journal records (timing + metrics)
  health         recent health events (non_finite, spikes, hang dumps)
  journal tail   the last N journal lines of ANY type, in order
  notes          breadcrumbs from layers without a journal handle
                 (data-pipeline worker restarts, bench backend recovery)
  span tail      snapshotted from the active Tracer at dump time

and dumps them as an atomic, crc-checked bundle directory

  <flight_dir>/<run_id>-<reason>/
      MANIFEST.json     run identity + reason + per-file size/crc32
      journal_tail.jsonl  steps.jsonl  health.jsonl  notes.jsonl
      spans.json        Chrome-trace tail (loads in Perfetto)
      stacks.json       every Python thread's stack at dump time
      metrics.prom      the metrics registry, Prometheus text format

on any of the ways a run dies:

  crash         process exits without a clean close (atexit, armed)
  hang          the health watchdog fired (observed via the journal tap)
  health_abort  the HealthMonitor abort policy tripped
  preempt       SIGTERM / preemption (multihost.PreemptionGuard hook)
  injected_crash[_after_write]  resilience fault injection, dumped in
                the instants before its SIGKILL (faults.fire hook)

Atomicity: the bundle is written into `<final>.tmp-<pid>` with per-file
fsync, then renamed — a reader never sees a half-written bundle, and a
SIGKILL that lands mid-dump leaves only a `.tmp-` directory that
`validate_bundle` ignores. Each file's crc32 is recorded in the
manifest so storage rot is detectable (`validate_bundle`).

Cost when idle: `observe` is one dict lookup + deque append per journal
event; layers without a recorder installed pay one module-global
None-check in `note()`. The chaos smoke probes this against a 2%
step-time budget.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional

from deep_vision_tpu.obs import locksmith
from deep_vision_tpu.obs.journal import _jsonable
from deep_vision_tpu.obs.registry import process_suffix

#: the dump reasons check_journal validates; dump() accepts any string
#: (forward compat) but everything the repo emits is one of these
REASONS = (
    "crash",
    "hang",
    "health_abort",
    "preempt",
    "injected_crash",
    "injected_crash_after_write",
    "manual",
)

#: bundle payload files, in write order (MANIFEST.json is written last,
#: after every payload crc is known)
_PAYLOAD_FILES = (
    "journal_tail.jsonl",
    "steps.jsonl",
    "health.jsonl",
    "notes.jsonl",
    "spans.json",
    "stacks.json",
    "metrics.prom",
)


class FlightRecorder:
    """Bounded-memory black box for one run.

    Wire-up (what train_cli does):

        flight = FlightRecorder(flight_dir, run_id=journal.run_id)
        set_flight(flight)              # layers without a journal handle
        journal.add_tap(flight.observe) # feed the ring buffers
        ...
        flight.close()                  # clean exit: disarm, no dump

    Anything that dies in between leaves a bundle: the atexit hook dumps
    `crash` while armed, the journal tap dumps on hang/abort health
    events, and the preemption/fault hooks call `emergency_dump`.
    """

    def __init__(self, flight_dir: str, run_id: Optional[str] = None,
                 max_steps: int = 512, max_health: int = 256,
                 max_tail: int = 1024, max_notes: int = 256,
                 span_tail: int = 512, registry=None):
        self.flight_dir = flight_dir
        self.run_id = run_id or f"flight-{os.getpid()}-{int(time.time())}"
        self.span_tail = int(span_tail)
        self.registry = registry
        self.journal = None  # attach() wires the flight_dump event emitter
        self._steps: deque = deque(maxlen=int(max_steps))
        self._health: deque = deque(maxlen=int(max_health))
        self._tail: deque = deque(maxlen=int(max_tail))
        self._notes: deque = deque(maxlen=int(max_notes))
        self._lock = locksmith.lock("obs.flight")
        self._dumped: Dict[str, str] = {}  # reason -> bundle dir (latch)
        self._dumping = False
        self._armed = True
        self._closed = False
        atexit.register(self._atexit)

    # -- feeding the buffers ----------------------------------------------

    def attach(self, journal) -> None:
        """Tap `journal` and remember it for typed `flight_dump` events."""
        self.journal = journal
        journal.add_tap(self.observe)

    def observe(self, row: dict) -> None:
        """Journal tap: route one event row into the ring buffers, and
        trigger a dump when the row itself is the emergency (a watchdog
        hang dump, a health-abort verdict). A serve-plane abort
        (monitor="serve": the router fails one batch's requests and
        keeps answering — a canary rejecting poisoned weights is the
        designed outcome, not a death) is request-scoped by contract
        and must NOT leave a crash-grade postmortem."""
        ev = row.get("event")
        with self._lock:
            self._tail.append(row)
            if ev == "step":
                self._steps.append(row)
            elif ev == "health":
                self._health.append(row)
        if ev == "health" and not self._dumping:
            if row.get("kind") == "hang":
                self.dump("hang")
            elif row.get("action") == "abort" \
                    and row.get("monitor") != "serve":
                self.dump("health_abort")

    def note(self, category: str, **fields) -> None:
        """Breadcrumb from a layer without a journal handle (data-pipeline
        worker restarts, bench backend recovery)."""
        row = {"ts": round(time.time(), 3), "category": str(category)}
        row.update({k: _jsonable(v) for k, v in fields.items()})
        with self._lock:
            self._notes.append(row)

    # -- dumping -----------------------------------------------------------

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Write the postmortem bundle for `reason`; returns its path.

        Latched per reason: one stall produces one `hang` bundle, and the
        crash that may follow still gets its own `crash` bundle. A second
        dump for an already-dumped reason returns the existing path.
        """
        with self._lock:
            if reason in self._dumped:
                return self._dumped[reason]
            if self._dumping:
                return None  # a dump triggered from inside a dump
            self._dumping = True
            steps = list(self._steps)
            health = list(self._health)
            tail = list(self._tail)
            notes = list(self._notes)
        try:
            path = self._write_bundle(reason, steps, health, tail, notes)
            with self._lock:
                self._dumped[reason] = path
            self._journal_event(reason, path, outcome="written")
            return path
        except Exception as e:
            # the recorder must never turn a dying run into a different
            # death; the failed dump is itself journaled when possible
            self._journal_event(reason, self.flight_dir, outcome="failed",
                                error=f"{type(e).__name__}: {e}")
            return None
        finally:
            with self._lock:
                self._dumping = False

    def _journal_event(self, reason: str, path: str, outcome: str,
                       **extra) -> None:
        if self.journal is not None:
            try:
                self.journal.write("flight_dump", reason=reason, dir=path,
                                   outcome=outcome, **extra)
            except Exception:
                pass

    def _write_bundle(self, reason: str, steps, health, tail,
                      notes) -> str:
        # multi-process runs suffix the bundle name with '.pN' (the
        # journal/trace per-host contract): identically-launched hosts can
        # share run_id (pid + launch second), and a pod-wide preemption
        # dumping onto one shared flight dir must not race two hosts'
        # renames onto the same final path — the loser's bundle is exactly
        # the postmortem this module exists to keep
        base = f"{self.run_id}-{reason}{process_suffix()}"
        final = os.path.join(self.flight_dir, base)
        n = 2
        while os.path.exists(final):  # a prior run's bundle: never clobber
            final = os.path.join(self.flight_dir, f"{base}-{n}")
            n += 1
        tmp = f"{final}.tmp-{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)

        spans = self._span_tail()
        stacks = _all_stacks()
        metrics = self._metrics_text()
        payloads = {
            "journal_tail.jsonl": _jsonl(tail),
            "steps.jsonl": _jsonl(steps),
            "health.jsonl": _jsonl(health),
            "notes.jsonl": _jsonl(notes),
            "spans.json": json.dumps({"traceEvents": spans,
                                      "metadata": {"run_id": self.run_id}}),
            "stacks.json": json.dumps(stacks, indent=1),
            "metrics.prom": metrics,
        }
        files: Dict[str, dict] = {}
        for name in _PAYLOAD_FILES:
            data = payloads[name].encode()
            files[name] = {"bytes": len(data), "crc32": zlib.crc32(data)}
            _write_fsync(os.path.join(tmp, name), data)
        manifest = {
            "run_id": self.run_id,
            "reason": reason,
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "process_index": _proc_index(),
            "files": files,
        }
        _write_fsync(os.path.join(tmp, "MANIFEST.json"),
                     json.dumps(manifest, indent=1).encode())
        os.rename(tmp, final)
        _fsync_dir(self.flight_dir)
        return final

    def _span_tail(self) -> List[dict]:
        try:
            from deep_vision_tpu.obs.trace import get_tracer

            t = get_tracer()
            return t.tail(self.span_tail) if t is not None else []
        except Exception:
            return []

    def _metrics_text(self) -> str:
        try:
            reg = self.registry
            if reg is None:
                from deep_vision_tpu.obs.registry import get_registry

                reg = get_registry()
            return reg.to_prometheus()
        except Exception:
            return ""

    def tail(self, n: int = 32) -> List[dict]:
        """The last `n` journal rows from the ring — the /statusz
        `recent_events` feed (obs/telemetry.py). Copy-under-lock, so a
        scraper thread never walks the deque while a tap appends."""
        with self._lock:
            rows = list(self._tail)
        return rows[-max(0, int(n)):]

    # -- lifecycle ---------------------------------------------------------

    @property
    def dumped(self) -> Dict[str, str]:
        """reason -> bundle path for every dump this run produced."""
        with self._lock:
            return dict(self._dumped)

    def disarm(self) -> None:
        """A clean exit is not an emergency: no crash bundle at atexit."""
        self._armed = False

    def close(self) -> None:
        """Clean-exit epilogue: disarm and detach (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self.disarm()
        atexit.unregister(self._atexit)
        if get_flight() is self:
            set_flight(None)

    def _atexit(self) -> None:
        if self._armed:
            self.dump("crash")


# -- bundle validation --------------------------------------------------------

def validate_bundle(path: str) -> List[str]:
    """Structural + crc validation of one bundle dir; empty list = valid.

    The CI teeth behind the dump format (chaos-smoke, tests): the
    manifest must parse and carry the envelope, and every listed file
    must exist with the recorded size and crc32 — a torn or rotted
    bundle fails loudly instead of lying quietly at 3am.
    """
    errors: List[str] = []
    man_path = os.path.join(path, "MANIFEST.json")
    try:
        with open(man_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{man_path}: unreadable manifest: {e}"]
    for k in ("run_id", "reason", "ts", "files"):
        if k not in manifest:
            errors.append(f"{man_path}: missing field {k!r}")
    for name, meta in (manifest.get("files") or {}).items():
        fpath = os.path.join(path, name)
        try:
            with open(fpath, "rb") as f:
                data = f.read()
        except OSError as e:
            errors.append(f"{fpath}: listed in manifest but unreadable: {e}")
            continue
        if len(data) != meta.get("bytes"):
            errors.append(f"{fpath}: size {len(data)} != manifest "
                          f"{meta.get('bytes')}")
        if zlib.crc32(data) != meta.get("crc32"):
            errors.append(f"{fpath}: crc32 mismatch (bundle rotted or torn)")
    return errors


def find_bundles(flight_dir: str) -> List[str]:
    """Complete bundle dirs under `flight_dir` (in-flight `.tmp-` dirs and
    stray files are excluded), sorted by name."""
    try:
        entries = sorted(os.listdir(flight_dir))
    except OSError:
        return []
    out = []
    for e in entries:
        full = os.path.join(flight_dir, e)
        if os.path.isdir(full) and ".tmp-" not in e:
            out.append(full)
    return out


# -- preemption escalation: checkpoint-now-and-requeue ------------------------

#: exit code a preempted run returns after its checkpoint landed:
#: EX_TEMPFAIL, the conventional "transient failure — requeue me" code
#: (sendmail, SLURM requeue policies). Distinct from 0 (done, do not
#: reschedule) and 1 (bug, do not reschedule), so the scheduler that
#: SIGTERMed the VM can resubmit the job to resume from the preempt
#: checkpoint.
REQUEUE_EXIT_CODE = 75

_requeue_requested = False


def request_requeue() -> None:
    """Mark this run preempted-with-checkpoint: the CLI exits with
    `REQUEUE_EXIT_CODE` so the scheduler requeues instead of declaring the
    job finished or failed. Called by the Trainer's SIGTERM escalation
    after the preempt checkpoint is on disk (the flight `preempt` bundle
    was already dumped from the signal hook)."""
    global _requeue_requested
    _requeue_requested = True


def requeue_requested() -> bool:
    return _requeue_requested


def clear_requeue() -> None:
    """Reset the latch (CLI entry, tests): the flag is process-wide and a
    long-lived process may host several runs."""
    global _requeue_requested
    _requeue_requested = False


# -- process-wide active recorder ---------------------------------------------

_active: Optional[FlightRecorder] = None


def set_flight(recorder: Optional[FlightRecorder]) -> None:
    """Install (or clear, with None) the process-wide recorder that the
    module-level `note`/`emergency_dump` report to."""
    global _active
    _active = recorder


def get_flight() -> Optional[FlightRecorder]:
    return _active


def note(category: str, **fields) -> None:
    """Breadcrumb on the active recorder; one global load + None check
    when no recorder is installed (same contract as trace.span)."""
    fr = _active
    if fr is not None:
        fr.note(category, **fields)


def emergency_dump(reason: str) -> Optional[str]:
    """Dump the active recorder's bundle NOW (fault injection's pre-SIGKILL
    hook, the preemption guard's SIGTERM hook); no-op without a recorder."""
    fr = _active
    if fr is not None:
        return fr.dump(reason)
    return None


# -- small helpers ------------------------------------------------------------

def _jsonl(rows: List[dict]) -> str:
    return "".join(json.dumps(r) + "\n" for r in rows)


def _proc_index() -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def _all_stacks() -> dict:
    try:
        from deep_vision_tpu.obs.health import dump_all_stacks

        return dump_all_stacks()
    except Exception:
        return {}


def _write_fsync(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    """Durability for the rename itself (the SIGKILL may be microseconds
    away on the injected-crash path)."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass
