"""The front-door smoke: a process fleet behind real sockets survives
SIGKILL, sheds by policy with real 429s, and swaps weights live.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/fleetnet_smoke.py \
        [--workdir artifacts/fleetnet_smoke] [--replicas 3] [--rps 120]

The CI teeth behind serve/transport.py + serve/procpool.py
(`make fleetnet-smoke`, a `make verify` prerequisite after
fleet-smoke). Where fleet-smoke exercises the THREAD fleet in one
interpreter, this drives N spawned replica PROCESSES — each with its
own engine, HTTP endpoint, and rendezvous membership lease — through
the parent's socket front door, with every request a real HTTP
round trip:

  1. warmup     parent template compiles once and seeds the executable
                cache; every replica process warms at ZERO backend
                compiles (cache_hits == pairs, read from ready files).
  2. death      sustained seeded RPS over the socket; one replica gets
                a REAL SIGKILL mid-traffic. Exactly the dead process's
                in-flight requests fail — typed (ReplicaLost behind a
                retryable 503), bounded, never the stream — the journal
                carries replica_lost/replica_recovered, the respawn
                warms from the cache at zero compiles, and a follow-up
                run's p99 proves the fleet recovered.
  3. promote    SwapController canaries new weights ACROSS PROCESSES
                (a spawned canary process serves the shadow weights for
                half the stream), auto-promotes, and every replica
                process hot-swaps via /control/promote; responses over
                the wire prove the new weights serve.
  4. shed       admission tightened at the front door, then an overload
                blast: excess traffic gets REAL 429s with Retry-After,
                a retrying client paces itself by the header, and
                offered == ok + err + shed holds across the client, the
                transport ledger, AND the journal.
  5. drain      clean close: the fleet ledger balances
                (accepted == completed + errors + cancelled), parent +
                every child journal pass check_journal --strict,
                obs_report renders the fleet-edge section, locksmith
                (armed the whole run) reports zero violations, and the
                flight dir is empty.
  6. goodput    the wall-clock ledger (obs/goodput.py) covers every
                second within 2% with the kill window billed to
                replica_respawn; the error burn-rate alert fired live
                during the kill (visible on /alertz), resolved under
                clean traffic, and an offline replay of the journal
                (obs/alerts.py evaluate_journal) reproduces the exact
                fired/resolved pairs; goodput_frac lands as a MAD-gated
                row in artifacts/perf_ledger.jsonl.

Exit status 0 = every contract held; 1 = something broke.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from typing import List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.loadgen import (  # noqa: E402
    BUCKETS,
    IMG,
    SLO_MS,
    Failures,
    HttpLoadClient,
    LoadGen,
    crosscheck_varz,
    fleet_builder,
    toy_fn,
    toy_variables,
)
from tools.smoke_util import read_jsonl  # noqa: E402


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/fleetnet_smoke")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--rps", type=float, default=120.0)
    p.add_argument("--requests", type=int, default=120,
                   help="requests in the sustained-load episode")
    args = p.parse_args(argv)

    # burn-rate windows at smoke scale: the SIGKILL episode is ~1 s of
    # traffic, so the fast/slow windows must fit inside the smoke's wall
    # clock for the alert to both fire and resolve; the budget drops so
    # even a minimal one-error kill window burns past budget * burn.
    # Set via env (not arguments) so the offline replay at the end reads
    # the SAME knob-tuned rule set the live engine did.
    os.environ["DVT_ALERT_FAST_S"] = "2.0"
    os.environ["DVT_ALERT_SLOW_S"] = "8.0"
    os.environ["DVT_ALERT_ERROR_BUDGET"] = "0.002"

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.checkpoint import CheckpointManager
    from deep_vision_tpu.obs import (
        FlightRecorder,
        RunJournal,
        locksmith,
        propagate,
        set_flight,
    )
    from deep_vision_tpu.obs.alerts import (
        AlertEngine,
        default_serving_rules,
        evaluate_journal,
    )
    from deep_vision_tpu.obs.goodput import GoodputMeter, attribute_journal
    from deep_vision_tpu.obs.registry import Registry
    from deep_vision_tpu.obs.telemetry import TelemetryServer
    from deep_vision_tpu.resilience import RetryPolicy
    from deep_vision_tpu.serve import (
        AdmissionController,
        ProcReplicaPool,
        ReplicaLost,
        ShedError,
        SwapController,
        Transport,
    )

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work)
    f = Failures()
    j_path = os.path.join(work, "journal.jsonl")
    flight_dir = os.path.join(work, "flight")

    journal = RunJournal(j_path, kind="serve")
    journal.manifest(config={"name": "fleetnet_smoke", "task": "serving"})
    flight = FlightRecorder(flight_dir, run_id=journal.run_id)
    flight.attach(journal)
    set_flight(flight)
    locksmith.arm(journal=journal)
    registry = Registry()
    tele = TelemetryServer(port=0, role="serve", registry=registry,
                           journal=journal, flight=flight,
                           discovery_dir=work)
    tele.start()
    # the goodput/alert plane rides the parent journal: the meter taps
    # every row into the wall-clock ledger, the engine evaluates the
    # knob-tuned serving rules at event time, and /alertz serves both
    goodput = GoodputMeter(journal=journal, registry=registry)
    tele.add_status("goodput", goodput.telemetry_status)
    alerts = AlertEngine(default_serving_rules(), journal=journal,
                         registry=registry)
    journal.add_tap(alerts.observe)
    tele.set_alerts(alerts)

    # -- phase 1: process fleet up, zero-compile children ---------------
    print(f"phase 1: {args.replicas} replica PROCESSES warm from the "
          "parent-seeded executable cache")
    pool = ProcReplicaPool(fleet_builder, replicas=args.replicas,
                           run_dir=work,
                           excache_dir=os.path.join(work, "excache"),
                           journal=journal, registry=registry,
                           slo_ms=SLO_MS, heartbeat_s=0.4,
                           ready_timeout_s=180.0)
    pool.start()
    f.check(pool.template_warmup["backend_compiles"] == 2 * len(BUCKETS),
            "parent template paid exactly one compile per unique "
            f"(model, bucket) pair "
            f"({pool.template_warmup['backend_compiles']})")
    ws = pool.warmup_stats()
    f.check(all(w["backend_compiles"] == 0 and w["cache_hits"] == w["pairs"]
                for w in ws.values()),
            f"every replica process warmed at ZERO backend compiles "
            f"({ {r: w['backend_compiles'] for r, w in ws.items()} }, "
            "cache_hits == pairs)")
    tp = Transport(pool, journal=journal, registry=registry)
    tp.start()
    f.check(tp.port > 0, f"front door listening at {tp.address}")
    # one traced request end to end: client socket -> parent -> child
    ctx = propagate.new_trace()
    probe_img = np.random.RandomState(9).rand(*IMG).astype(np.float32)
    with propagate.use(ctx):
        c0 = HttpLoadClient("127.0.0.1", tp.port, registry=registry)
        row = c0.submit("toy", probe_img).result(timeout=60)
    c0.close()
    f.check("scores" in row, "a request crossed both sockets")

    # -- phase 2: sustained RPS + mid-traffic SIGKILL -------------------
    print("phase 2: mid-traffic SIGKILL is request-scoped; respawn is a "
          "disk read")
    # NO retries here: the client must OBSERVE the typed failures the
    # death causes, not paper over them
    noretry = HttpLoadClient(
        "127.0.0.1", tp.port,
        retry=RetryPolicy(name="fleetnet.noretry", max_attempts=1))
    victim = pool._slots["p1"]
    killed_at = {}

    def killer():
        time.sleep(0.3)  # let the stream establish
        killed_at["pid"] = victim.proc.pid
        os.kill(victim.proc.pid, signal.SIGKILL)

    kt = threading.Thread(target=killer, daemon=True)
    kt.start()
    gen = LoadGen(noretry.submit, ["toy", "aux"], rps=args.rps,
                  n_requests=args.requests, seed=42)
    stats = gen.run()
    kt.join()
    noretry.close()
    print(f"  load: {stats}  (SIGKILL pid {killed_at.get('pid')})")
    f.check(stats["ok"] + stats["errors"] + stats["shed"]
            + stats["refused"] == stats["offered"],
            "every offered request accounted over the wire "
            f"(ok={stats['ok']} err={stats['errors']} "
            f"shed={stats['shed']})")
    f.check(1 <= stats["errors"] <= 25,
            f"only the dead process's in-flight window failed "
            f"({stats['errors']} errors; the stream survived)")
    # the failures were TYPED: every error outcome at the edge names
    # ReplicaLost (a retryable 503), never an anonymous 500
    edge_errs = [e for e in read_jsonl(j_path)
                 if e.get("event") == "transport_request"
                 and e.get("outcome") == "error"]
    f.check(bool(edge_errs)
            and all(e.get("status") == 503
                    and "ReplicaLost" in e.get("error", "")
                    for e in edge_errs),
            f"all {len(edge_errs)} edge errors are typed ReplicaLost "
            "behind retryable 503s")
    # the kill window PAGED: the error burn-rate rule fired live, and
    # the /alertz endpoint (what tools/obs_poll.py --strict-alerts
    # polls) shows it active over the wire — event time is frozen at
    # the last row, so the verdict holds until clean traffic ages the
    # errors out of the fast window
    from tools.obs_poll import fetch_json
    az = fetch_json(tele.host, tele.port, "/alertz")
    live_active = [a.get("rule") for a in (az or {}).get("active", [])]
    f.check("serve_error_burn" in live_active,
            f"burn-rate alert fired during the kill window and /alertz "
            f"shows it live ({live_active})")
    deadline = time.time() + 60
    while time.time() < deadline and not all(
            s == "serving" for s in pool.replica_states().values()):
        time.sleep(0.1)
    f.check(all(s == "serving" for s in pool.replica_states().values()),
            f"fleet back to full strength ({pool.replica_states()})")
    recs = [e for e in read_jsonl(j_path)
            if e.get("event") == "replica_recovered"]
    f.check(len(recs) == 1 and recs[0].get("backend_compiles") == 0
            and recs[0].get("cache_hits") == recs[0].get("pairs"),
            "respawned process warmed ENTIRELY from the executable "
            "cache (zero backend compiles, "
            f"{recs[0].get('cache_hits') if recs else '?'}"
            f"/{recs[0].get('pairs') if recs else '?'} pairs cache-hit)")
    # post-respawn health: a second seeded run over the full fleet
    # holds the SLO — the fleet RECOVERED, it did not limp on
    c2 = HttpLoadClient("127.0.0.1", tp.port, registry=registry)
    stats2 = LoadGen(c2.submit, ["toy", "aux"], rps=args.rps,
                     n_requests=60, seed=43).run()
    c2.close()
    print(f"  post-respawn: {stats2}")
    f.check(stats2["errors"] == 0 and stats2["ok"] == stats2["offered"],
            "post-respawn stream is clean (no errors, no sheds)")
    f.check(stats2["p99_ms"] <= SLO_MS,
            f"post-respawn p99 recovered "
            f"({stats2['p99_ms']:.1f}ms <= {SLO_MS:g}ms)")
    xc = crosscheck_varz(stats2, tele.host, tele.port, ["toy", "aux"])
    f.check(len(xc["checked"]) == 2,
            "client p50+p99 cross-checked against /varz over the wire "
            f"({len(xc['skewed'])} skew warning(s))")
    # resolution needs event time to move PAST the kill window: feed
    # clean probe traffic until the errors age out of the fast window
    # and the engine journals alert_resolved (bounded, not forever)
    rc0 = HttpLoadClient("127.0.0.1", tp.port, registry=registry)
    resolve_deadline = time.time() + 30
    while time.time() < resolve_deadline and any(
            a["rule"] == "serve_error_burn" for a in alerts.active()):
        rc0.submit("toy", probe_img).result(timeout=60)
        time.sleep(0.25)
    rc0.close()
    az = fetch_json(tele.host, tele.port, "/alertz")
    f.check(not (az or {}).get("active"),
            "burn-rate alert RESOLVED under clean post-respawn traffic "
            "(/alertz active list empty)")

    # -- phase 3: canary swap across processes --------------------------
    print("phase 3: canary process serves new weights; promote hot-swaps "
          "every replica")
    ckpt_dir = os.path.join(work, "ckpt")
    mgr = CheckpointManager(ckpt_dir, journal=journal)
    new_toy = {"toy": toy_variables(scale=2.0, seed=7)}
    mgr.save_tree(1, new_toy)
    mgr.wait()
    ref = jax.device_get(
        toy_fn(new_toy["toy"], jnp.asarray(probe_img[None])))
    ctraffic = HttpLoadClient("127.0.0.1", tp.port, registry=registry)
    stop = threading.Event()

    def traffic(seed: int):
        rng = np.random.RandomState(seed)
        while not stop.is_set():
            try:
                ctraffic.submit("toy", rng.rand(*IMG).astype(np.float32))
            except Exception:
                pass
            time.sleep(0.004)

    t = threading.Thread(target=traffic, args=(3,), daemon=True)
    t.start()
    swapper = SwapController(pool, journal=journal, canary_pct=50,
                             min_canary_requests=6, slo_ms=SLO_MS,
                             canary_timeout_s=90.0)
    verdict = swapper.swap(mgr, step=1, models=("toy",))
    stop.set()
    t.join(timeout=10)
    ctraffic.close()
    f.check(verdict["outcome"] == "promoted",
            "new weights promoted across the process fleet ("
            + " -> ".join(f"{t_['phase']}:{t_['outcome']}"
                          for t_ in verdict["timeline"]) + ")")
    cp = HttpLoadClient("127.0.0.1", tp.port, registry=registry)
    got = np.asarray(cp.submit("toy", probe_img).result(timeout=60)
                     ["scores"])
    cp.close()
    f.check(bool(np.allclose(got, ref["scores"][0], rtol=1e-4)),
            "responses over the wire serve the PROMOTED weights")

    # -- phase 4: overload sheds with real 429s -------------------------
    print("phase 4: overload gets real 429s; Retry-After paces the client")
    led_before = tp.ledger()
    tp.admission = AdmissionController(max_queue_depth=16,
                                       rate_per_s=0.0, burst=20)
    blast_client = HttpLoadClient(
        "127.0.0.1", tp.port,
        retry=RetryPolicy(name="fleetnet.blast", max_attempts=1))
    blast = LoadGen(blast_client.submit, ["toy"], rps=None,
                    n_requests=100, seed=77)
    bstats = blast.run()
    blast_client.close()
    print(f"  blast: {bstats}")
    f.check(bstats["shed"] >= 70 and bstats["ok"] <= 25,
            f"token budget admitted <= 25 of 100 over the wire, shed "
            f"the rest (shed={bstats['shed']})")
    f.check(bstats["ok"] + bstats["errors"] + bstats["shed"]
            + bstats["refused"] == bstats["offered"],
            "overload accounting balances at the client")
    led = tp.ledger()
    shed_delta = led["shed"] - led_before["shed"]
    ok_delta = led["ok"] - led_before["ok"]
    f.check(shed_delta == bstats["shed"] and ok_delta == bstats["ok"],
            f"client and transport ledgers agree across the wire "
            f"(shed {bstats['shed']}=={shed_delta}, "
            f"ok {bstats['ok']}=={ok_delta})")
    f.check(led["by_status"].get("429", 0) >= 70,
            f"sheds were REAL 429s on the wire "
            f"(429 x{led['by_status'].get('429', 0)})")
    # a retrying client must come back and land: the bucket has no
    # refill, so widen it just enough for the retry to get through
    tp.admission = AdmissionController(max_queue_depth=16,
                                       rate_per_s=50.0, burst=1)
    rc = HttpLoadClient("127.0.0.1", tp.port, registry=registry)
    rows = [rc.submit("toy", probe_img) for _ in range(3)]
    ok_after_retry = sum(1 for r in rows
                         if r.result(timeout=60) is not None)
    f.check(ok_after_retry == 3 and rc.counts["retries"] >= 1
            and rc.counts["retry_after_honored"] >= 1,
            f"retrying client honored Retry-After and recovered "
            f"({rc.counts['retries']} retries, "
            f"{rc.counts['retry_after_honored']} paced by the header)")
    rc.close()
    tp.admission = None

    # -- phase 5: drain + artifacts -------------------------------------
    print("phase 5: clean drain; strict journals everywhere; zero "
          "violations")
    led = tp.ledger()
    f.check(led["balanced"],
            f"transport ledger balances: offered {led['offered']} == "
            "ok + error + shed + deadline + bad_request + torn")
    # journal vs ledger: every wire request journaled exactly one verdict
    jreq = [e for e in read_jsonl(j_path)
            if e.get("event") == "transport_request"]
    f.check(len(jreq) == led["offered"],
            f"journal carries one transport_request per offered request "
            f"({len(jreq)} == {led['offered']})")
    tp.close()
    summary = pool.drain("close")
    f.check(summary["outcome"] == "flushed" and summary["pending"] == 0,
            f"fleet drained everything ({summary})")
    f.check(summary["accepted"] == summary["completed"]
            + summary["errors"] + summary["cancelled"],
            "fleet ledger balances across death, swap, and shed "
            f"(accepted={summary['accepted']})")
    lock_report = locksmith.report()
    f.check(not lock_report["violations"],
            "locksmith: zero lock-order violations across the fleet "
            "lifecycle"
            + ("" if not lock_report["violations"]
               else f" ({lock_report['violations'][0]})"))
    locksmith.disarm()
    mgr.close()
    tele.close()
    flight.close()
    set_flight(None)
    journal.close()
    f.check(not os.listdir(flight_dir) if os.path.isdir(flight_dir)
            else True, "clean run left no flight bundle")

    env = dict(os.environ, PYTHONPATH=ROOT)
    child_journals = sorted(
        os.path.join(work, p) for p in os.listdir(work)
        if p.startswith("replica-") and p.endswith(".jsonl"))
    f.check(len(child_journals) >= args.replicas + 1,
            f"each replica incarnation left a journal "
            f"({len(child_journals)} files: base fleet + respawn + "
            "canary)")
    # the SIGKILLed incarnation's journal is the one file that MUST
    # fail strict — a murdered process never writes its terminal event,
    # and that missing line is the forensic record of the kill
    killed = {f"replica-{e['replica']}-a{e['attempt']}.jsonl"
              for e in read_jsonl(j_path)
              if e.get("event") == "replica_lost"}
    strict_ok, killed_flagged = True, True
    for path in [j_path] + child_journals:
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools",
                                          "check_journal.py"),
             path, "--strict"], cwd=ROOT, env=env)
        if os.path.basename(path) in killed:
            killed_flagged = killed_flagged and r.returncode != 0
        else:
            strict_ok = strict_ok and r.returncode == 0
    f.check(strict_ok, "check_journal --strict accepts the parent AND "
            f"every surviving child journal "
            f"({1 + len(child_journals) - len(killed)} files)")
    f.check(len(killed) == 1 and killed_flagged,
            "strict mode flags exactly the SIGKILLed incarnation's "
            f"journal as terminated without a terminal event ({killed})")
    rep = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_report.py"),
         j_path],
        cwd=ROOT, env=env, stdout=subprocess.PIPE, text=True)
    f.check(rep.returncode == 0 and "fleet edge" in rep.stdout
            and "429x" in rep.stdout,
            "obs_report renders the fleet-edge section (status ledger "
            "with the 429s)")
    f.check("goodput" in rep.stdout and "alerts" in rep.stdout
            and "serve_error_burn" in rep.stdout,
            "obs_report renders the goodput table and the alert "
            "timeline from the same journal")

    # -- phase 6: goodput ledger + live==offline alert agreement --------
    print("phase 6: every second attributed; offline replay reproduces "
          "the live alert pairs")
    events = read_jsonl(j_path)
    f.check(any(e.get("event") == "goodput_summary" for e in events),
            "the live GoodputMeter flushed a terminal goodput_summary "
            "via the journal closer")
    acct = attribute_journal(events)
    imb = acct.imbalance_frac()
    f.check(imb <= 0.02,
            f"goodput buckets sum to wall clock within 2% "
            f"(imbalance {imb * 100:.2f}%)")
    f.check(acct.buckets["replica_respawn"] > 0,
            "the SIGKILL->respawn window is attributed to "
            f"replica_respawn ({acct.buckets['replica_respawn']:.2f} s), "
            "not overhead")
    # live == offline, literally: the engine is a pure state machine
    # over event time, so replaying the journal through a fresh engine
    # with the same knob-tuned rules reproduces the exact transitions
    live_pairs = [(h["rule"], h["fired_ts"], h["resolved_ts"])
                  for h in alerts.pairs()]
    off_pairs = [(h["rule"], h["fired_ts"], h["resolved_ts"])
                 for h in evaluate_journal(
                     events, rules=default_serving_rules()).pairs()]
    f.check(live_pairs == off_pairs,
            f"offline journal replay reproduces the live alert pairs "
            f"exactly ({live_pairs} == {off_pairs})")
    f.check(len(live_pairs) == 1
            and live_pairs[0][0] == "serve_error_burn"
            and live_pairs[0][2] is not None,
            "exactly one alert episode: serve_error_burn fired and "
            "resolved; no spurious rule ever paged")
    from tools.perf_gate import PerfLedger, default_env, gate_result
    gp = acct.goodput_frac()
    verdict = gate_result(
        PerfLedger(os.path.join(ROOT, "artifacts", "perf_ledger.jsonl")),
        "goodput_frac", gp, unit="frac",
        env=dict(default_env(), suite="fleetnet_smoke"),
        direction="higher")
    f.check(verdict["verdict"] in ("pass", "insufficient_history"),
            f"goodput_frac {gp:.3f} passes the MAD gate "
            f"(verdict {verdict['verdict']})")

    if f.errors:
        print(f"\nfleetnet-smoke: {len(f.errors)} contract(s) BROKEN "
              f"(artifacts in {work})")
        return 1
    print(f"\nfleetnet-smoke: all front-door contracts held "
          f"(artifacts in {work})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
