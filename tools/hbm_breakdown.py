"""Per-op HBM traffic breakdown of the flagship train step (round 4).

The bench's aggregate number (77.9 GB/step at batch 256 ~= 92% of v5e HBM
bandwidth) says the step is memory-bound but not WHERE the bytes go. The
tunnel's profiler exposes no per-op compute events, so this derives the
breakdown statically from the compiled executable's post-optimization HLO:
every top-level instruction of the entry computation reads its operands from
HBM and writes its output to HBM (XLA materializes exactly these buffers;
everything else lives inside fusions), so

    bytes(instr) ~= sum(operand buffer sizes) + output buffer size

which is the same accounting XLA's own cost analysis uses for its aggregate
"bytes accessed". The report ranks instructions, groups them into classes
(conv fwd / conv dgrad+wgrad / BN-ish fusions / optimizer / copies ...), and
cross-checks the grand total against `cost_analysis()["bytes accessed"]`.

Writes artifacts/hbm_breakdown_r04.json. Run on the chip (layouts and
fusion decisions are backend-specific).
"""
from __future__ import annotations

import json
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1, "token": 0, "opaque": 0,
}

_SHAPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")


def shape_bytes(shape_str: str) -> int:
    """Bytes of one (possibly tuple) HLO shape string."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in DTYPE_BYTES:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * DTYPE_BYTES[dtype]
    return total


# one instruction line: "  %name = <shape> opcode(...)" or "  name = ..."
_INSTR_RE = re.compile(
    r"^\s+(?:ROOT\s+)?%?([\w.\-]+)\s+=\s+((?:\([^=]*?\))|(?:[\w\[\],:{}()*#\s]+?))\s+"
    r"([\w\-]+)\("
)


def parse_entry(hlo_text: str):
    """Yield (name, shape_str, opcode, operand_names, line) for the entry
    computation's top-level instructions."""
    lines = hlo_text.splitlines()
    in_entry = False
    for ln in lines:
        if ln.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry and ln.startswith("}"):
            break
        if not in_entry:
            continue
        m = _INSTR_RE.match(ln)
        if not m:
            continue
        name, shape_str, opcode = m.group(1), m.group(2), m.group(3)
        # operand names: %foo references after the opcode's open paren
        rest = ln[m.end():]
        # strip nested calls=/to_apply= references and attribute payloads
        args = rest.split("), ")[0]
        operands = re.findall(r"%([\w.\-]+)", args)
        yield name, shape_str, opcode, operands, ln


def classify(opcode: str, line: str) -> str:
    """Bucket an entry instruction for the report."""
    if opcode == "fusion":
        if "conv" in line and "kind=kOutput" in line:
            return "conv+epilogue fusion"
        if "reduce" in line or "kind=kInput" in line:
            return "reduce fusion (BN stats & grads)"
        return "elementwise fusion (BN apply/residual/opt)"
    if opcode == "convolution":
        return "convolution (unfused)"
    if opcode in ("copy", "copy-start", "copy-done", "transpose"):
        return "copy/layout"
    if opcode in ("all-reduce", "all-gather", "reduce-scatter"):
        return "collective"
    if opcode in ("custom-call",):
        return "custom-call"
    if opcode in ("parameter", "constant", "get-tuple-element", "tuple",
                  "bitcast-convert"):
        return "plumbing (no traffic)"
    return opcode


NO_TRAFFIC = {"parameter", "constant", "get-tuple-element", "tuple",
              "bitcast"}


def breakdown(hlo_text: str, top_n: int = 30):
    sizes = {}     # instr name -> output bytes
    rows = []
    for name, shape_str, opcode, operands, ln in parse_entry(hlo_text):
        out_b = shape_bytes(shape_str)
        sizes[name] = out_b
        if opcode in NO_TRAFFIC:
            continue
        in_b = sum(sizes.get(op, 0) for op in operands)
        rows.append({
            "name": name,
            "op": opcode,
            "class": classify(opcode, ln),
            "out_mb": round(out_b / 1e6, 2),
            "in_mb": round(in_b / 1e6, 2),
            "total_mb": round((out_b + in_b) / 1e6, 2),
        })
    rows.sort(key=lambda r: -r["total_mb"])
    by_class = defaultdict(lambda: [0.0, 0])
    for r in rows:
        by_class[r["class"]][0] += r["total_mb"]
        by_class[r["class"]][1] += 1
    total = sum(r["total_mb"] for r in rows)
    classes = sorted(
        ({"class": k, "gb": round(v[0] / 1e3, 2), "n_ops": v[1],
          "pct": round(100 * v[0] / total, 1)}
         for k, v in by_class.items()),
        key=lambda c: -c["gb"],
    )
    return {
        "total_estimated_gb": round(total / 1e3, 2),
        "by_class": classes,
        "top_instructions": rows[:top_n],
        "n_entry_instructions": len(rows),
    }


def main(out_path="artifacts/hbm_breakdown_r04.json",
         batch=256, dump_hlo=None):
    import bench

    print("breakdown: compiling step", file=sys.stderr)
    step, state, b, *_ = bench.build_bench(batch, 1)
    text = step.as_text()
    if dump_hlo:
        with open(dump_hlo, "w") as f:
            f.write(text)
    art = {"what": __doc__.split("\n")[0], "batch_per_chip": batch}
    art.update(breakdown(text))
    try:
        ca = step.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        art["xla_cost_analysis_gb"] = round(
            float(ca["bytes accessed"]) / 1e9, 2
        )
    except Exception as e:
        art["xla_cost_analysis_gb"] = None
        art["cost_analysis_error"] = f"{type(e).__name__}: {e}"
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(art, f, indent=2)
    print(f"breakdown: est {art['total_estimated_gb']} GB vs "
          f"cost_analysis {art['xla_cost_analysis_gb']} GB -> {out_path}",
          file=sys.stderr)
    for c in art["by_class"]:
        print(f"breakdown:   {c['pct']:5.1f}%  {c['gb']:7.2f} GB  "
              f"({c['n_ops']:4d} ops)  {c['class']}", file=sys.stderr)


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--out", default="artifacts/hbm_breakdown_r04.json")
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--dump-hlo", default=None,
                   help="also write the optimized HLO text here")
    a = p.parse_args()
    main(a.out, a.batch, a.dump_hlo)
