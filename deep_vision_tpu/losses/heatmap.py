"""Pose heatmap loss (Stacked Hourglass) and CenterNet losses.

Parity targets:
- Hourglass weighted MSE (Hourglass/tensorflow/train.py:65-76): foreground
  pixels weighted x(81+1), summed over all stacks (intermediate supervision).
- CenterNet focal + L1 losses: the reference left these EMPTY
  (ObjectsAsPoints/tensorflow/train.py:35 `loss_objects = []`, SURVEY.md §2.9);
  implemented here from the ObjectsAsPoints paper (eq. 1: penalty-reduced
  pixel-wise focal loss with alpha=2/beta=4; eq. 3: L1 size loss weighted 0.1;
  offset L1).

CenterNet batch convention (dense, static-shape):
  batch['heatmap'] : (B, H, W, C) gaussian class heatmaps in [0, 1]
  batch['wh']      : (B, H, W, 2) box sizes written at center pixels
  batch['offset']  : (B, H, W, 2) sub-pixel offsets at center pixels
  batch['mask']    : (B, H, W)   1.0 exactly at object centers
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

FOREGROUND_WEIGHT = 81.0  # Hourglass/tensorflow/train.py:69


def hourglass_loss_fn(outputs, batch, fg_threshold: float = 0.0):
    """outputs: list of per-stack (B, H, W, K) heatmaps; batch['heatmap'] GT.

    Any strictly-positive GT pixel is foreground (weight 82), exactly matching
    `cast(labels > 0) * 81 + 1` at Hourglass/tensorflow/train.py:69 — gaussian
    tail pixels count as foreground too.
    """
    gt = batch["heatmap"]
    weights = jnp.where(gt > fg_threshold, 1.0 + FOREGROUND_WEIGHT, 1.0)
    total = 0.0
    for hm in outputs:
        total = total + jnp.mean(jnp.square(hm - gt) * weights)
    metrics = {"loss": total, "last_stack_mse": jnp.mean(jnp.square(outputs[-1] - gt))}
    return total, metrics


def centernet_focal_loss(pred_logits, gt, alpha: float = 2.0, beta: float = 4.0):
    """Penalty-reduced pixel-wise focal loss, normalized by object count."""
    p = jax.nn.sigmoid(pred_logits)
    p = jnp.clip(p, 1e-6, 1.0 - 1e-6)
    pos = jnp.where(gt >= 1.0 - 1e-6, 1.0, 0.0)
    pos_loss = pos * jnp.power(1.0 - p, alpha) * jnp.log(p)
    neg_loss = (
        (1.0 - pos)
        * jnp.power(1.0 - gt, beta)
        * jnp.power(p, alpha)
        * jnp.log(1.0 - p)
    )
    num_pos = jnp.maximum(jnp.sum(pos), 1.0)
    return -(jnp.sum(pos_loss) + jnp.sum(neg_loss)) / num_pos


def _masked_l1(pred, gt, mask):
    num = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(jnp.abs(pred - gt) * mask[..., None]) / num


def centernet_loss_fn(outputs, batch, wh_weight: float = 0.1,
                      offset_weight: float = 1.0):
    """outputs: list of per-stack dicts {'heatmap','wh','offset'} (raw logits)."""
    total = 0.0
    metrics = {}
    for i, head in enumerate(outputs):
        hm_loss = centernet_focal_loss(head["heatmap"], batch["heatmap"])
        wh_loss = _masked_l1(head["wh"], batch["wh"], batch["mask"])
        off_loss = _masked_l1(head["offset"], batch["offset"], batch["mask"])
        total = total + hm_loss + wh_weight * wh_loss + offset_weight * off_loss
        if i == len(outputs) - 1:
            metrics.update(
                {"hm_loss": hm_loss, "wh_loss": wh_loss, "offset_loss": off_loss}
            )
    metrics["loss"] = total
    return total, metrics
