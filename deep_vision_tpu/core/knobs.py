"""Central registry of `DVT_*` environment knobs.

Before this module, 14 knobs were scattered across 12 files, each with
its own parse idiom: DVT_NMS_IMPL raised on a typo (the convention worth
keeping — a triage knob that silently no-ops defeats its purpose),
DVT_LOCKSMITH_HOLD_MS fed `float()` raw (garbage = unhandled
ValueError deep in `arm_from_env`), DVT_TELEMETRY warned, and
DVT_PALLAS_FUSED treated ANY value — including the empty string — as
truthy unless it happened to be "0"/"false"/"off". This module is the
single source of truth the DV203 lint rule enforces: every `DVT_*` read
in the tree must go through a typed helper here, and every name a
helper is given must be declared in `KNOBS`.

Parse contract ("mistype raises", the DVT_NMS_IMPL precedent):

  - unset, or set to whitespace/empty -> the registered default;
  - a value that does not parse as the knob's kind -> `KnobError`
    (a ValueError), never a silent fallback;
  - a helper called with the wrong kind for a knob, or an unregistered
    name -> `KnobError` at the call site, so the registry cannot rot.

Stdlib-only by design: resilience/rendezvous.py and resilience/faults.py
read knobs before (or instead of) paying the jax import.

`python -m deep_vision_tpu.lint --knobs` prints `format_knob_table()`;
the README "Environment knobs" section mirrors it (tests assert the
README lists every registered name).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Knob",
    "KnobError",
    "KNOBS",
    "get_int",
    "get_float",
    "get_flag",
    "get_choice",
    "get_str",
    "knob_table",
    "format_knob_table",
]


class KnobError(ValueError):
    """A knob read failed loudly: unparseable value, unregistered name,
    or a typed helper applied to a knob of another kind."""


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    kind: str  # "int" | "float" | "flag" | "choice" | "str"
    default: object
    doc: str
    choices: Tuple[str, ...] = ()


def _k(name: str, kind: str, default, doc: str,
       choices: Tuple[str, ...] = ()) -> Knob:
    return Knob(name=name, kind=kind, default=default, doc=doc,
                choices=choices)


#: every `DVT_*` environment variable the tree reads, in one place.
#: DV203 (lint/distlint.py) fails any `os.environ` read of a `DVT_*`
#: name outside this module, and any helper call naming a knob that is
#: not declared here.
KNOBS: Dict[str, Knob] = {k.name: k for k in (
    _k("DVT_ALERT_BURN", "float", 2.0,
       "Burn-rate multiplier for obs/alerts.py rules: an error budget "
       "is 'burning' when the bad ratio exceeds budget * this in both "
       "the fast and slow windows."),
    _k("DVT_ALERT_ERROR_BUDGET", "float", 0.01,
       "Serving error budget (fraction of transport_request rows that "
       "may be 5xx/torn) the serve_error_burn rule guards."),
    _k("DVT_ALERT_FAST_S", "float", 5.0,
       "Fast window (seconds of event time) for burn-rate alert rules "
       "(obs/alerts.py) — the page-quickly half of the pair."),
    _k("DVT_ALERT_GOODPUT_FLOOR", "float", 0.0,
       "Goodput floor: mean goodput_frac over the slow window below "
       "this fires the goodput_floor alert; 0 disables the rule."),
    _k("DVT_ALERT_LATENCY_BUDGET_MS", "float", 0.0,
       "Serving latency budget (ms): ok-request p95 over the slow "
       "window above this fires serve_latency_budget; 0 disables."),
    _k("DVT_ALERT_RECOMPILE_BURST", "int", 8,
       "Recompile burst bound: more than this many new recompiles "
       "within the slow window fires recompile_burst; 0 disables."),
    _k("DVT_ALERT_SLOW_S", "float", 60.0,
       "Slow window (seconds of event time) for alert rules "
       "(obs/alerts.py) — the don't-page-on-a-blip half."),
    _k("DVT_ALERT_STARVATION_FRAC", "float", 0.0,
       "Data-starvation bound: fraction of steps in the slow window "
       "with data_wait_ms > dispatch_ms above this fires "
       "data_starvation; 0 disables the rule."),
    _k("DVT_COLLECTIVE_DEADLINE_S", "float", 600.0,
       "Deadline (seconds) for the raw-jax fallback collectives in "
       "parallel/multihost.py; a barrier blocked past this declares a "
       "lost peer instead of hanging forever."),
    _k("DVT_EXCACHE", "str", None,
       "Executable-cache directory (core/excache.py) used when "
       "--executable-cache is absent; empty/unset disables the cache."),
    _k("DVT_FAULT_SEED", "int", 0,
       "RNG seed for the resilience/faults.py injector; exported with "
       "the spec so spawned data-loader workers draw the same faults."),
    _k("DVT_FAULT_SPEC", "str", None,
       "Fault-injection spec (resilience/faults.py), inherited by "
       "spawned worker processes at import time."),
    _k("DVT_FLASH_MIN_TOKENS", "int", 1024,
       "Flash-attention routing floor: sequences at least this many "
       "tokens route onto the Pallas kernel (ops/pallas/"
       "flash_attention.py); lower routes shorter sequences onto it."),
    _k("DVT_GOODPUT_INTERVAL_S", "float", 30.0,
       "Cadence (seconds) of the live GoodputMeter's goodput_interval "
       "journal events (obs/goodput.py)."),
    _k("DVT_HOST_SMOKE_DEBUG", "flag", False,
       "Arm faulthandler periodic stack dumps in tools/host_smoke.py "
       "worker processes (hang triage)."),
    _k("DVT_LOCKSMITH", "flag", False,
       "Arm the locksmith runtime lock-order sanitizer "
       "(obs/locksmith.py) — set in serve/chaos/data smoke children."),
    _k("DVT_LOCKSMITH_HOLD_MS", "float", 1000.0,
       "Locksmith hold-time outlier threshold in milliseconds; holds "
       "past this emit a typed lock_contention event."),
    _k("DVT_LOCKSMITH_WAIT_MS", "float", 1000.0,
       "Locksmith acquire-wait outlier threshold in milliseconds."),
    _k("DVT_NMS_IMPL", "choice", None,
       "Force the NMS selection backend (ops/nms.py); unset = auto "
       "(pallas when the backend compiles Pallas, lax elsewhere).",
       choices=("lax", "pallas")),
    _k("DVT_PALLAS_FUSED", "flag", None,
       "Force the fused Pallas scale/bias/act path (ops/pallas/"
       "bn_act.py) on (1) or off (0); unset = on only when the backend "
       "compiles Pallas."),
    _k("DVT_PREFLIGHT_BUDGET_S", "float", 60.0,
       "Per-probe time budget (seconds) for tools/preflight.py backend "
       "checks; raise it for slow relays."),
    _k("DVT_RDZV_GENERATION", "int", None,
       "Rendezvous generation to re-attach to (resilience/"
       "rendezvous.py) — set for re-exec'd host agents."),
    _k("DVT_TELEMETRY", "int", None,
       "Telemetry HTTP port used when --telemetry-port is absent; "
       "0 binds a free port."),
    _k("DVT_TRANSPORT_DEADLINE_MS", "float", 0.0,
       "Default request deadline (milliseconds) the serving front door "
       "(serve/transport.py) applies to requests that carry no "
       "X-DVT-Deadline-Ms header; 0 means no default deadline."),
    _k("DVT_TRANSPORT_RETRY_AFTER_MS", "float", 50.0,
       "Retry-After hint (milliseconds) the front door attaches to 429/"
       "503 shed responses; the loadgen socket client sleeps exactly "
       "this before retrying."),
)}

_UNSET = object()

_TRUE = ("1", "true", "on", "yes")
_FALSE = ("0", "false", "off", "no")


def _lookup(name: str, kind: str) -> Knob:
    knob = KNOBS.get(name)
    if knob is None:
        raise KnobError(
            f"{name} is not a registered knob — declare it in "
            "deep_vision_tpu/core/knobs.py KNOBS (DV203)")
    if knob.kind != kind:
        raise KnobError(
            f"{name} is registered as a {knob.kind!r} knob, not "
            f"{kind!r} — use get_{knob.kind}()")
    return knob


def _raw(name: str) -> Optional[str]:
    """The raw env value, with unset and empty/whitespace both mapping
    to None (= use the default) — `DVT_EXCACHE=""` must disable the
    cache, not name a cache directory called ''."""
    v = os.environ.get(name)
    if v is None or not v.strip():
        return None
    return v


def _default(knob: Knob, default):
    return knob.default if default is _UNSET else default


def get_int(name: str, default=_UNSET) -> Optional[int]:
    knob = _lookup(name, "int")
    v = _raw(name)
    if v is None:
        return _default(knob, default)
    try:
        return int(v)
    except ValueError:
        raise KnobError(
            f"{name}={v!r} is not an integer — {knob.doc}") from None


def get_float(name: str, default=_UNSET) -> Optional[float]:
    knob = _lookup(name, "float")
    v = _raw(name)
    if v is None:
        return _default(knob, default)
    try:
        return float(v)
    except ValueError:
        raise KnobError(
            f"{name}={v!r} is not a number — {knob.doc}") from None


def get_flag(name: str, default=_UNSET) -> Optional[bool]:
    knob = _lookup(name, "flag")
    v = _raw(name)
    if v is None:
        return _default(knob, default)
    low = v.strip().lower()
    if low in _TRUE:
        return True
    if low in _FALSE:
        return False
    raise KnobError(
        f"{name}={v!r} is not a flag value "
        f"({'/'.join(_TRUE)} or {'/'.join(_FALSE)}) — {knob.doc}")


def get_choice(name: str, default=_UNSET) -> Optional[str]:
    knob = _lookup(name, "choice")
    v = _raw(name)
    if v is None:
        return _default(knob, default)
    if v not in knob.choices:
        # NO normalization: 'LAX' / 'lax ' raising is the point — a
        # triage knob that silently runs the suspect default defeats it
        raise KnobError(
            f"{name}={v!r} is not one of {'|'.join(knob.choices)} — "
            f"{knob.doc}")
    return v


def get_str(name: str, default=_UNSET) -> Optional[str]:
    knob = _lookup(name, "str")
    v = _raw(name)
    if v is None:
        return _default(knob, default)
    return v


def knob_table() -> List[Knob]:
    return [KNOBS[name] for name in sorted(KNOBS)]


def format_knob_table() -> str:
    """The human-readable registry dump behind
    `python -m deep_vision_tpu.lint --knobs`."""
    rows = []
    for knob in knob_table():
        kind = knob.kind
        if knob.choices:
            kind = f"{kind}({'|'.join(knob.choices)})"
        default = "unset" if knob.default is None else repr(knob.default)
        rows.append((knob.name, kind, default, knob.doc))
    w_name = max(len(r[0]) for r in rows)
    w_kind = max(len(r[1]) for r in rows)
    w_def = max(len(r[2]) for r in rows)
    lines = [f"{'knob':<{w_name}}  {'kind':<{w_kind}}  "
             f"{'default':<{w_def}}  doc"]
    for name, kind, default, doc in rows:
        lines.append(f"{name:<{w_name}}  {kind:<{w_kind}}  "
                     f"{default:<{w_def}}  {doc}")
    return "\n".join(lines)
