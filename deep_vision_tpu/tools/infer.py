"""Image-in, result-out inference CLI for every task family.

The script form of the reference's demo surfaces — per-model notebooks
(ResNet50.ipynb, demo_mscoco.ipynb, demo_hourglass_pose.ipynb — SURVEY.md §4)
and the CycleGAN inference script (CycleGAN/tensorflow/inference.py:11-70:
restore checkpoint, run the generator over a folder, save outputs):

    python -m deep_vision_tpu.tools.infer -m resnet50 -c ck/ img1.jpg img2.jpg
    python -m deep_vision_tpu.tools.infer -m yolov3_voc -c ck/ street.jpg
    python -m deep_vision_tpu.tools.infer -m hourglass_mpii -c ck/ person.jpg
    python -m deep_vision_tpu.tools.infer -m cyclegan -c ck/ photo.jpg -o out/

Classification prints top-5; detection prints NMS'd boxes (and writes a
..._boxes.txt sidecar); pose prints per-joint (x, y, score); GAN configs run
the generator and save translated JPEGs next to the inputs (or under -o).
"""
from __future__ import annotations

import argparse
import os
from typing import List, Optional

import numpy as np


def _load_image(path: str, size: int, normalize: str, rescale: int = 0):
    """Decode + the EXACT eval chain training used (train_cli eval_tf):
    mismatched normalization silently wrecks predictions, so the chains here
    mirror build_dataloaders' eval branches per `normalize` mode."""
    from deep_vision_tpu.data.datasets import decode_image
    from deep_vision_tpu.data import transforms as T

    with open(path, "rb") as f:
        img = decode_image(f.read())
    sample = {"image": img}
    rng = np.random.default_rng(0)
    if normalize == "imagenet":  # torch chain (train_cli eval_tf)
        chain = [T.Rescale(rescale or size + 32), T.CenterCrop(size),
                 T.ToFloatNormalize(expand_gray_to_rgb=True)]
    elif normalize == "imagenet_tf":  # the 0-255 mean-subtraction chain
        chain = [T.Rescale(rescale or size + 32), T.CenterCrop(size),
                 T.ToFloat(expand_gray_to_rgb=True, scale=False),
                 T.MeanSubtract()]
    elif normalize == "unit":  # [0,1]
        chain = [T.Resize(size), T.ToFloat(expand_gray_to_rgb=True)]
    else:  # [-1,1] (GANs)
        chain = [T.Resize(size), T.ToFloat(expand_gray_to_rgb=True),
                 T.Normalize(mean=[0.5] * 3, std=[0.5] * 3)]
    for t in chain:
        sample = t(sample, rng)
    return sample["image"]


# MPII skeleton: limb edges drawn between joint indices (r-leg, l-leg,
# spine/head, r-arm, l-arm) — the demo overlay of
# demo_hourglass_pose.ipynb as data
POSE_SKELETON = ((0, 1), (1, 2), (2, 6), (3, 6), (3, 4), (4, 5), (6, 7),
                 (7, 8), (8, 9), (10, 11), (11, 12), (12, 7), (13, 7),
                 (13, 14), (14, 15))
_PALETTE = ((255, 99, 71), (60, 179, 113), (65, 105, 225), (255, 215, 0),
            (186, 85, 211), (0, 206, 209), (255, 140, 0), (154, 205, 50))


def _write_jpeg(dst: str, rgb_u8: np.ndarray) -> None:
    """RGB uint8 -> JPEG on disk; cv2 when present, PIL otherwise (cv2 is
    optional everywhere in this package)."""
    try:
        import cv2

        if not cv2.imwrite(dst, rgb_u8[..., ::-1]):  # RGB -> BGR for cv2
            raise IOError(f"cv2.imwrite returned False for {dst}")
    except Exception:  # cv2 may fail at load time with OSError, not ImportError
        from PIL import Image

        Image.fromarray(rgb_u8).save(dst, quality=95)


def _reload_rgb(path: str, size: int) -> np.ndarray:
    """The display copy: decoded + resized, NOT normalized."""
    from deep_vision_tpu.data.datasets import decode_image
    from deep_vision_tpu.data import transforms as T

    with open(path, "rb") as f:
        img = decode_image(f.read())
    s = T.Resize(size)({"image": img}, np.random.default_rng(0))
    return np.ascontiguousarray(s["image"][..., :3])


def draw_detections(image: np.ndarray, boxes, scores, classes,
                    class_names=None) -> np.ndarray:
    """Box + label overlay on an RGB uint8 image; normalized [x1,y1,x2,y2]
    boxes (the rendered-output parity of demo_mscoco.ipynb)."""
    import cv2

    out = image.copy()
    h, w = out.shape[:2]
    for b, s, c in zip(boxes, scores, classes):
        color = _PALETTE[int(c) % len(_PALETTE)]
        x1, y1 = int(b[0] * w), int(b[1] * h)
        x2, y2 = int(b[2] * w), int(b[3] * h)
        cv2.rectangle(out, (x1, y1), (x2, y2), color, 2)
        name = (class_names[int(c)] if class_names
                and 0 <= int(c) < len(class_names) else f"class {int(c)}")
        label = f"{name} {float(s):.2f}"
        (tw, th), _ = cv2.getTextSize(label, cv2.FONT_HERSHEY_SIMPLEX, 0.5, 1)
        ty = y1 - 4 if y1 - th - 8 >= 0 else y2 + th + 4
        cv2.rectangle(out, (x1, ty - th - 4), (x1 + tw + 2, ty + 2), color, -1)
        cv2.putText(out, label, (x1 + 1, ty - 2), cv2.FONT_HERSHEY_SIMPLEX,
                    0.5, (255, 255, 255), 1, cv2.LINE_AA)
    return out


def draw_classification(image: np.ndarray, label: str,
                        prob: float) -> np.ndarray:
    """Top-1 label banner on an RGB uint8 image (the rendered-output parity
    of ResNet50.ipynb's classify-a-real-photo demo). PIL text, so the
    classification overlay stays cv2-free like the rest of this path."""
    from PIL import Image, ImageDraw

    im = Image.fromarray(image)
    d = ImageDraw.Draw(im, "RGBA")
    h = max(20, image.shape[0] // 14)
    d.rectangle([0, 0, image.shape[1], h], fill=(0, 0, 0, 190))
    d.text((8, max(3, h // 4)), f"{label}  {prob:.2f}",
           fill=(255, 255, 255, 255))
    return np.asarray(im)


def draw_pose(image: np.ndarray, kpts, score_threshold: float = 0.1,
              skeleton=POSE_SKELETON) -> np.ndarray:
    """Joint dots + skeleton limbs; kpts (J, 3) = normalized x, y, score
    (the rendered-output parity of demo_hourglass_pose.ipynb)."""
    import cv2

    out = image.copy()
    h, w = out.shape[:2]
    pts = [(int(x * w), int(y * h)) if s >= score_threshold else None
           for x, y, s in np.asarray(kpts, np.float32)]
    for e, (a, b) in enumerate(skeleton):
        if a < len(pts) and b < len(pts) and pts[a] and pts[b]:
            cv2.line(out, pts[a], pts[b], _PALETTE[e % len(_PALETTE)], 2,
                     cv2.LINE_AA)
    for p in pts:
        if p:
            cv2.circle(out, p, 3, (255, 255, 255), -1, cv2.LINE_AA)
            cv2.circle(out, p, 3, (30, 30, 30), 1, cv2.LINE_AA)
    return out


def _restore_variables(model, sample, ckpt_dir: Optional[str]):
    import jax
    import jax.numpy as jnp

    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.asarray(sample), train=False,
    )
    if not ckpt_dir:
        print("warning: no -c checkpoint; running with fresh-init weights")
        return variables
    from deep_vision_tpu.core.checkpoint import CheckpointManager

    return CheckpointManager(ckpt_dir).restore_variables()


def main(argv: Optional[List[str]] = None) -> int:
    from deep_vision_tpu.configs import CONFIG_REGISTRY, get_config

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model", required=True, choices=sorted(CONFIG_REGISTRY))
    p.add_argument("-c", "--checkpoint", default=None)
    p.add_argument("-o", "--output-dir", default=None,
                   help="GAN outputs / detection sidecars go here "
                        "(default: alongside inputs)")
    p.add_argument("--score-threshold", type=float, default=0.3)
    p.add_argument("--preprocessing", default="torch", choices=["torch", "tf"],
                   help="must match how the checkpoint was trained "
                        "(train.py --preprocessing)")
    p.add_argument("--render", action="store_true",
                   help="classification configs: also write a "
                        "<name>_classified.jpg display copy with the top-1 "
                        "label drawn")
    p.add_argument("--labels", default=None,
                   help="class-name file, one name per line, line i = model "
                        "class index i (the converter's imagenet labels are "
                        "1-based with 0 = background)")
    p.add_argument("images", nargs="+")
    args = p.parse_args(argv)

    import jax.numpy as jnp

    from deep_vision_tpu.models import get_model

    cfg = get_config(args.model)
    size = cfg.input_shape[0]

    # class names apply to classification (top-5 lines, --render banner)
    # AND detection (printed lines + box overlay labels)
    names = None
    if args.labels:
        with open(args.labels) as fh:
            names = [line.strip() for line in fh if line.strip()]
    elif cfg.dataset.get("schema") == "voc":
        # the 20 VOC names are fixed by the dataset (interop constants,
        # like the anchor priors): the demo output shows "person 0.92",
        # not "class 14", with no flag needed
        from deep_vision_tpu.tools.converters import VOC_CLASSES

        names = list(VOC_CLASSES)

    def name_of(i: int) -> str:
        return names[i] if names and 0 <= i < len(names) else f"class {i}"

    def outpath(src: str, suffix: str) -> str:
        base = os.path.basename(src)
        root, _ = os.path.splitext(base)
        d = args.output_dir or os.path.dirname(src) or "."
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, root + suffix)

    if cfg.task == "classification":
        if cfg.dataset.get("kind") == "imagenet":
            mode = "imagenet_tf" if args.preprocessing == "tf" else "imagenet"
            batch = np.stack([
                _load_image(f, cfg.eval_crop, mode, rescale=cfg.train_resize)
                for f in args.images
            ])
        else:
            # small-input configs (mnist-style): resize to the config's
            # input_shape; collapse to grayscale when it wants one channel
            batch = np.stack([
                _load_image(f, size, "unit") for f in args.images
            ])
            if cfg.input_shape[2] == 1:
                luma = np.array([0.299, 0.587, 0.114], np.float32)
                batch = (batch @ luma)[..., None]
                batch = (batch - 0.1307) / 0.3081  # the mnist chain's stats
        if cfg.model_kwargs.get("stem") == "s2d":
            from deep_vision_tpu.data.transforms import space_to_depth

            batch = np.stack([space_to_depth(im) for im in batch])
        kwargs = dict(cfg.model_kwargs)
        model = get_model(cfg.model, num_classes=cfg.num_classes, **kwargs)
        variables = _restore_variables(model, batch[:1], args.checkpoint)
        logits = np.asarray(
            model.apply(variables, jnp.asarray(batch), train=False),
            np.float32,
        )
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        for f, pr in zip(args.images, probs):
            top = np.argsort(pr)[::-1][:5]
            picks = " ".join(f"{name_of(int(i))}: {pr[i]:.3f}" for i in top)
            print(f"{f}: {picks}")
            if args.render:
                k = int(top[0])
                drawn = draw_classification(
                    _reload_rgb(f, size), name_of(k), float(pr[k])
                )
                dst = outpath(f, "_classified.jpg")
                _write_jpeg(dst, drawn)
                print(f"  wrote {dst}")
        return 0

    if cfg.task in ("detection", "centernet"):
        from deep_vision_tpu.inference import (
            make_centernet_detector,
            make_yolo_detector,
        )

        batch = np.stack([
            _load_image(f, size, "unit") for f in args.images
        ])
        model = get_model(cfg.model, num_classes=cfg.num_classes,
                          **cfg.model_kwargs)
        variables = _restore_variables(model, batch[:1], args.checkpoint)
        detect = (
            make_yolo_detector(model, score_threshold=args.score_threshold)
            if cfg.task == "detection"
            else make_centernet_detector(
                model, score_threshold=args.score_threshold
            )
        )
        out = {k: np.asarray(v) for k, v in
               detect(variables, jnp.asarray(batch)).items()}
        try:  # overlay rendering needs cv2, which is optional everywhere
            import cv2
        except Exception:
            cv2 = None
            print("note: opencv not installed; skipping _detected.jpg "
                  "overlays (text sidecars still written)")
        for i, f in enumerate(args.images):
            n = int(out["num"][i])
            print(f"{f}: {n} detections")
            lines = []
            for j in range(n):
                b = out["boxes"][i, j]
                line = (f"  {name_of(int(out['classes'][i, j]))} "
                        f"score {float(out['scores'][i, j]):.3f} "
                        f"box [{b[0]:.3f} {b[1]:.3f} {b[2]:.3f} {b[3]:.3f}]")
                print(line)
                lines.append(line.strip())
            with open(outpath(f, "_boxes.txt"), "w") as fh:
                fh.write("\n".join(lines) + "\n")
            if cv2 is not None:
                # rendered overlay beside the sidecar (demo_mscoco.ipynb
                # parity)
                drawn = draw_detections(
                    _reload_rgb(f, size), out["boxes"][i, :n],
                    out["scores"][i, :n], out["classes"][i, :n],
                    class_names=names,
                )
                dst = outpath(f, "_detected.jpg")
                cv2.imwrite(dst, drawn[..., ::-1])  # RGB -> BGR
                print(f"  -> {dst}")
        return 0

    if cfg.task == "pose":
        from deep_vision_tpu.inference import make_pose_estimator

        batch = np.stack([
            _load_image(f, size, "unit") for f in args.images
        ])
        model = get_model(cfg.model, **cfg.model_kwargs)
        variables = _restore_variables(model, batch[:1], args.checkpoint)
        estimate = make_pose_estimator(model)
        kpts = np.asarray(estimate(variables, jnp.asarray(batch)))
        try:
            import cv2
        except Exception:
            cv2 = None
            print("note: opencv not installed; skipping _pose.jpg overlays")
        for f, kp in zip(args.images, kpts):
            print(f"{f}:")
            for j, (x, y, s) in enumerate(kp):
                print(f"  joint {j}: x={x:.3f} y={y:.3f} score={s:.3f}")
            if cv2 is not None:
                # skeleton overlay (demo_hourglass_pose.ipynb parity)
                drawn = draw_pose(_reload_rgb(f, size), kp)
                dst = outpath(f, "_pose.jpg")
                cv2.imwrite(dst, drawn[..., ::-1])
                print(f"  -> {dst}")
        return 0

    if cfg.task in ("dcgan", "cyclegan"):
        if cfg.task == "dcgan":
            model = get_model("dcgan_generator")
            z = np.random.RandomState(0).randn(len(args.images), 100)
            variables = _restore_variables(model, z[:1].astype(np.float32),
                                           args.checkpoint)
            imgs = np.asarray(model.apply(
                variables, jnp.asarray(z, jnp.float32), train=False
            ), np.float32)
        else:
            batch = np.stack([
                _load_image(f, size, "gan") for f in args.images
            ])
            model = get_model("cyclegan_generator")
            variables = _restore_variables(model, batch[:1], args.checkpoint)
            imgs = np.asarray(
                model.apply(variables, jnp.asarray(batch), train=False),
                np.float32,
            )
        for f, im in zip(args.images, imgs):
            u8 = np.clip((im + 1.0) * 127.5, 0, 255).astype(np.uint8)
            dst = outpath(f, "_generated.jpg")
            _write_jpeg(dst, u8)
            print(f"{f} -> {dst}")
        return 0

    raise ValueError(f"unsupported task {cfg.task!r}")


if __name__ == "__main__":
    raise SystemExit(main())
