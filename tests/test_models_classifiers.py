"""Shape tests for every classifier in the zoo (reference had none; SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import pytest

from deep_vision_tpu.models import get_model

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)

RNG = jax.random.PRNGKey(0)


def _init_apply(model, x, train=False):
    variables = model.init({"params": RNG, "dropout": RNG}, x, train=train)
    out = model.apply(
        variables, x, train=train,
        rngs={"dropout": RNG},
        mutable=["batch_stats"] if "batch_stats" in variables else False,
    )
    if isinstance(out, tuple) and len(out) == 2 and isinstance(out[1], dict):
        out = out[0]
    return out, variables


@pytest.mark.parametrize(
    "name,shape,classes",
    [
        ("lenet5", (2, 32, 32, 1), 10),
        ("alexnet1", (1, 227, 227, 3), 17),
        ("alexnet2", (1, 224, 224, 3), 17),
        ("vgg16", (1, 224, 224, 3), 17),
        ("vgg19", (1, 224, 224, 3), 17),
        ("resnet34", (1, 224, 224, 3), 17),
        ("resnet50", (1, 224, 224, 3), 17),
        ("resnet152", (1, 96, 96, 3), 17),
        ("resnet50v2", (1, 224, 224, 3), 17),
        ("mobilenet1", (1, 224, 224, 3), 17),
        ("shufflenet1", (1, 224, 224, 3), 17),
    ],
)
def test_classifier_eval_shapes(name, shape, classes):
    model = get_model(name, num_classes=classes)
    out, _ = _init_apply(model, jnp.zeros(shape))
    assert out.shape == (shape[0], classes)
    assert out.dtype == jnp.float32


def test_inception_v1_aux_heads():
    model = get_model("inception1", num_classes=11)
    x = jnp.zeros((1, 224, 224, 3))
    out, variables = _init_apply(model, x, train=True)
    logits, aux1, aux2 = out
    assert logits.shape == aux1.shape == aux2.shape == (1, 11)
    # eval mode: single output
    out_eval = model.apply(variables, x, train=False)
    assert out_eval.shape == (1, 11)


def test_inception_v3_aux_head():
    model = get_model("inception3", num_classes=7)
    x = jnp.zeros((1, 299, 299, 3))
    out, _ = _init_apply(model, x, train=True)
    logits, aux = out
    assert logits.shape == (1, 7)
    assert aux.shape == (1, 7)


def test_mobilenet_alpha_shrinks_params():
    import numpy as np

    def nparams(model, x):
        v = model.init({"params": RNG, "dropout": RNG}, x, train=False)
        return sum(np.prod(p.shape) for p in jax.tree_util.tree_leaves(v["params"]))

    x = jnp.zeros((1, 224, 224, 3))
    full = nparams(get_model("mobilenet1", num_classes=10, alpha=1.0), x)
    half = nparams(get_model("mobilenet1", num_classes=10, alpha=0.5), x)
    assert half < full * 0.5


def test_shufflenet_channel_shuffle_roundtrip():
    from deep_vision_tpu.nn.layers import channel_shuffle

    x = jnp.arange(2 * 1 * 1 * 12, dtype=jnp.float32).reshape(2, 1, 1, 12)
    y = channel_shuffle(x, 3)
    # shuffling with g then with c//g is the identity permutation inverse
    z = channel_shuffle(y, 4)
    assert jnp.allclose(z, x)
    assert not jnp.allclose(y, x)


class TestSpaceToDepthStem:
    """The s2d stem must be mathematically identical to the conv7 stem."""

    def test_equivalence_to_conv7(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import flax.linen as nn
        from deep_vision_tpu.data.transforms import space_to_depth
        from deep_vision_tpu.models.resnet import SpaceToDepthStem

        rng = np.random.RandomState(0)
        x = rng.rand(2, 32, 32, 3).astype(np.float32)
        stem = SpaceToDepthStem(16)
        x2 = np.stack([space_to_depth(im) for im in x])
        v = stem.init(jax.random.PRNGKey(0), jnp.asarray(x2))
        w = v["params"]["kernel"]  # canonical (7,7,3,16)
        y_s2d = stem.apply(v, jnp.asarray(x2))
        y_ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), w, window_strides=(2, 2),
            padding=((3, 3), (3, 3)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        assert y_s2d.shape == y_ref.shape
        np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref),
                                   rtol=1e-4, atol=1e-4)

    def test_resnet_s2d_forward(self):
        import jax
        import jax.numpy as jnp
        from deep_vision_tpu.models import get_model

        model = get_model("resnet50", num_classes=10, stem="s2d")
        x = jnp.zeros((2, 32, 32, 12), jnp.float32)  # 64x64 image, s2d'd
        v = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
        out = model.apply(v, x, train=False)
        assert out.shape == (2, 10)


class TestFusedBatchNormParity:
    """nn/layers.py BatchNorm must match flax nn.BatchNorm numerically."""

    def _pair(self, train):
        import flax.linen as nn
        from deep_vision_tpu.nn.layers import BatchNorm as FusedBN

        ours = FusedBN(use_running_average=not train, momentum=0.9)
        ref = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                           use_fast_variance=True)
        return ours, ref

    def test_train_mode_and_ema_match(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        x = jnp.asarray(np.random.RandomState(0).randn(8, 4, 4, 6) * 3 + 7,
                        jnp.float32)
        ours, ref = self._pair(train=True)
        vo = ours.init(jax.random.PRNGKey(0), x)
        vr = ref.init(jax.random.PRNGKey(0), x)
        yo, mo = ours.apply(vo, x, mutable=["batch_stats"])
        yr, mr = ref.apply(vr, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(yo), np.asarray(yr),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(mo["batch_stats"]["mean"]),
            np.asarray(mr["batch_stats"]["mean"]), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(mo["batch_stats"]["var"]),
            np.asarray(mr["batch_stats"]["var"]), rtol=1e-4, atol=1e-5)

    def test_eval_mode_matches(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        x = jnp.asarray(np.random.RandomState(1).randn(8, 4, 4, 6), jnp.float32)
        ours, ref = self._pair(train=False)
        stats = {"mean": jnp.asarray(np.random.RandomState(2).randn(6), jnp.float32),
                 "var": jnp.abs(jnp.asarray(np.random.RandomState(3).randn(6),
                                            jnp.float32)) + 0.5}
        vo = ours.init(jax.random.PRNGKey(0), x)
        vr = ref.init(jax.random.PRNGKey(0), x)
        vo = {"params": vo["params"], "batch_stats": stats}
        vr = {"params": vr["params"], "batch_stats": stats}
        yo = ours.apply(vo, x)
        yr = ref.apply(vr, x)
        np.testing.assert_allclose(np.asarray(yo), np.asarray(yr),
                                   rtol=2e-5, atol=2e-5)

    def test_bf16_large_mean_precision(self):
        """No catastrophic cancellation: bf16 input with |mean| >> std."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        xf = np.random.RandomState(0).randn(64, 2, 2, 4).astype(np.float32) * 3 + 105
        x = jnp.asarray(xf, jnp.bfloat16)
        ours, _ = self._pair(train=True)
        v = ours.init(jax.random.PRNGKey(0), x)
        y, _ = ours.apply(v, x, mutable=["batch_stats"])
        # reference: exact f32 normalization of the bf16-quantized input
        x32 = np.asarray(x, np.float32)
        mean = x32.mean((0, 1, 2))
        var = (x32 ** 2).mean((0, 1, 2)) - mean ** 2
        y_ref = (x32 - mean) / np.sqrt(var + 1e-5)
        err = np.abs(np.asarray(y, np.float32) - y_ref).max()
        assert err < 0.02, err  # bf16 output quantization only, not 0.29
