"""GAN trainer tests: DCGAN twin update and CycleGAN 2G/2D + image pool."""
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.models import get_model
from deep_vision_tpu.models.cyclegan import CycleGanGenerator, PatchGanDiscriminator
from deep_vision_tpu.train.gan import CycleGanTrainer, DcganTrainer, ImagePool
from deep_vision_tpu.train.optimizers import build_optimizer


def test_image_pool_semantics():
    pool = ImagePool(size=4, seed=0)
    first = np.ones((4, 2, 2, 1), np.float32)
    out = pool.query(first)
    assert np.allclose(out, first)  # fills up, returns as-is
    out2 = pool.query(np.zeros((4, 2, 2, 1), np.float32))
    assert out2.shape == first.shape
    # after the swap phase the pool holds a mix of old/new
    assert 0 < len(pool.images) <= 4


def test_image_pool_size_zero_passthrough():
    pool = ImagePool(size=0)
    x = np.random.rand(2, 2, 2, 1).astype(np.float32)
    assert np.allclose(pool.query(x), x)


def test_dcgan_step_and_generate(mesh8):
    g = get_model("dcgan_generator", latent_dim=16)
    d = get_model("dcgan_discriminator")
    trainer = DcganTrainer(
        g, d,
        build_optimizer("adam", 1e-4, b1=0.5),
        build_optimizer("adam", 1e-4, b1=0.5),
        latent_dim=16, mesh=mesh8,
    )
    real = np.random.rand(8, 28, 28, 1).astype(np.float32) * 2 - 1
    m1 = trainer.train_step(real)
    m2 = trainer.train_step(real)
    assert np.isfinite(float(m1["g_loss"])) and np.isfinite(float(m1["d_loss"]))
    assert int(trainer.g_state.step) == 2 and int(trainer.d_state.step) == 2
    imgs = trainer.generate(4)
    assert imgs.shape == (4, 28, 28, 1)
    assert float(jnp.max(jnp.abs(imgs))) <= 1.0  # tanh range


def test_cyclegan_step(mesh8):
    shape = (32, 32, 3)
    mk_g = lambda: CycleGanGenerator(n_blocks=1, base=8)
    mk_d = lambda: PatchGanDiscriminator(base=8)
    trainer = CycleGanTrainer(
        mk_g(), mk_g(), mk_d(), mk_d(),
        g_tx_fn=lambda: build_optimizer("adam", 2e-4, b1=0.5),
        d_tx_fn=lambda: build_optimizer("adam", 2e-4, b1=0.5),
        image_shape=shape, mesh=mesh8, pool_size=4,
    )
    a = np.random.rand(8, *shape).astype(np.float32) * 2 - 1
    b = np.random.rand(8, *shape).astype(np.float32) * 2 - 1
    m = trainer.train_step(a, b)
    for k in ("g_loss", "g_adv", "g_cycle", "g_identity", "d_loss"):
        assert np.isfinite(float(m[k])), k
    out = trainer.translate(a[:2])
    assert out.shape == (2, *shape)
