"""GAN trainer tests: DCGAN twin update and CycleGAN 2G/2D + image pool."""
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.models import get_model
from deep_vision_tpu.models.cyclegan import CycleGanGenerator, PatchGanDiscriminator
from deep_vision_tpu.train.gan import CycleGanTrainer, DcganTrainer, ImagePool
from deep_vision_tpu.train.optimizers import build_optimizer

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)


def test_image_pool_semantics():
    pool = ImagePool(size=4, seed=0)
    first = np.ones((4, 2, 2, 1), np.float32)
    out = pool.query(first)
    assert np.allclose(out, first)  # fills up, returns as-is
    out2 = pool.query(np.zeros((4, 2, 2, 1), np.float32))
    assert out2.shape == first.shape
    # after the swap phase the pool holds a mix of old/new
    assert 0 < len(pool.images) <= 4


def test_image_pool_size_zero_passthrough():
    pool = ImagePool(size=0)
    x = np.random.rand(2, 2, 2, 1).astype(np.float32)
    assert np.allclose(pool.query(x), x)


def test_dcgan_step_and_generate(mesh8):
    g = get_model("dcgan_generator", latent_dim=16)
    d = get_model("dcgan_discriminator")
    trainer = DcganTrainer(
        g, d,
        build_optimizer("adam", 1e-4, b1=0.5),
        build_optimizer("adam", 1e-4, b1=0.5),
        latent_dim=16, mesh=mesh8,
    )
    real = np.random.rand(8, 28, 28, 1).astype(np.float32) * 2 - 1
    m1 = trainer.train_step(real)
    m2 = trainer.train_step(real)
    assert np.isfinite(float(m1["g_loss"])) and np.isfinite(float(m1["d_loss"]))
    assert int(trainer.g_state.step) == 2 and int(trainer.d_state.step) == 2
    imgs = trainer.generate(4)
    assert imgs.shape == (4, 28, 28, 1)
    assert float(jnp.max(jnp.abs(imgs))) <= 1.0  # tanh range


def test_cyclegan_step(mesh8):
    shape = (32, 32, 3)
    mk_g = lambda: CycleGanGenerator(n_blocks=1, base=8)
    mk_d = lambda: PatchGanDiscriminator(base=8)
    trainer = CycleGanTrainer(
        mk_g(), mk_g(), mk_d(), mk_d(),
        g_tx_fn=lambda: build_optimizer("adam", 2e-4, b1=0.5),
        d_tx_fn=lambda: build_optimizer("adam", 2e-4, b1=0.5),
        image_shape=shape, mesh=mesh8, pool_size=4,
    )
    a = np.random.rand(8, *shape).astype(np.float32) * 2 - 1
    b = np.random.rand(8, *shape).astype(np.float32) * 2 - 1
    m = trainer.train_step(a, b)
    for k in ("g_loss", "g_adv", "g_cycle", "g_identity", "d_loss"):
        assert np.isfinite(float(m[k])), k
    out = trainer.translate(a[:2])
    assert out.shape == (2, *shape)


def test_gan_cli_checkpoint_and_resume(tmp_path, mesh8, capsys):
    """GAN checkpoint/resume via the CLI: the reference's restore-or-
    initialize pattern (DCGAN/tensorflow/main.py:34-40)."""
    from deep_vision_tpu.train_cli import main

    ck = str(tmp_path / "ck")
    rc = main(["-m", "dcgan_mnist", "--fake-data", "--epochs", "1",
               "--batch-size", "8", "--fake-batches", "1",
               "--ckpt-dir", ck])
    assert rc == 0
    rc = main(["-m", "dcgan_mnist", "--fake-data", "--epochs", "2",
               "--batch-size", "8", "--fake-batches", "1",
               "--ckpt-dir", ck, "-c", "auto"])
    assert rc == 0
    assert "resumed GAN training at epoch 1" in capsys.readouterr().out


def test_cyclegan_trainer_save_restore_roundtrip(tmp_path, mesh8):
    import numpy as np
    from deep_vision_tpu.core import CheckpointManager
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.gan import CycleGanTrainer
    from deep_vision_tpu.train.optimizers import build_optimizer

    tx_fn = lambda: build_optimizer("adam", 2e-4, b1=0.5)
    mk = lambda: CycleGanTrainer(
        get_model("cyclegan_generator"), get_model("cyclegan_generator"),
        get_model("cyclegan_discriminator"), get_model("cyclegan_discriminator"),
        tx_fn, tx_fn, image_shape=(64, 64, 3), mesh=mesh8,
    )
    t1 = mk()
    rng = np.random.RandomState(0)
    a = rng.rand(8, 64, 64, 3).astype(np.float32) * 2 - 1
    b = rng.rand(8, 64, 64, 3).astype(np.float32) * 2 - 1
    t1.train_step(a, b)
    ck = CheckpointManager(str(tmp_path / "ck"))
    t1.save(ck, epoch=0)
    ck.wait()

    t2 = mk()
    next_epoch = t2.restore(ck)
    assert next_epoch == 1
    import jax

    p1 = jax.tree_util.tree_leaves(t1.gab.params)
    p2 = jax.tree_util.tree_leaves(t2.gab.params)
    for x, y in zip(p1, p2):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert int(t2.gab.step) == int(t1.gab.step)


def test_gan_preempt_save_marks_incomplete_epoch(tmp_path):
    """save(..., completed_epoch=epoch-1) stores mid-epoch states under the
    current epoch's step but resumes AT that epoch (the CLI preemption
    path); works at epoch 0 too (no orbax step collision, resumes at 0)."""
    import jax

    from deep_vision_tpu.core import CheckpointManager
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.gan import DcganTrainer
    from deep_vision_tpu.train.optimizers import build_optimizer

    def make():
        return DcganTrainer(
            get_model("dcgan_generator"), get_model("dcgan_discriminator"),
            build_optimizer("adam", 1e-4, b1=0.5),
            build_optimizer("adam", 1e-4, b1=0.5),
            rng=jax.random.PRNGKey(0),
        )

    ckpt = CheckpointManager(str(tmp_path))
    t = make()
    t.save(ckpt, 0, completed_epoch=-1)  # preempted during epoch 0
    ckpt.wait()
    t2 = make()
    assert t2.restore(CheckpointManager(str(tmp_path))) == 0  # re-run epoch 0


def test_convergence_run_gan_dcgan_smoke(tmp_path):
    """The hardware GAN-evidence runner end-to-end at CPU-smoke scale:
    curves artifact + real/generated sample grids."""
    import json
    import os

    from deep_vision_tpu.tools.convergence_run import run_gan_dcgan

    out = str(tmp_path / "dcgan.json")
    r = run_gan_dcgan(steps=6, batch=8, out_path=out,
                      render_dir=str(tmp_path))
    assert np.isfinite(r["final_g_loss"]) and np.isfinite(r["final_d_loss"])
    assert r["sample_std"] >= 0.0 and len(r["curves"]["g_loss"]) >= 2
    assert os.path.exists(out) and json.load(open(out))["steps"] == 6
    for name in ("demo_gan_dcgan_real.jpg", "demo_gan_dcgan_samples.jpg"):
        assert (tmp_path / name).exists(), name


def test_convergence_run_gan_cyclegan_smoke(tmp_path):
    import json
    import os

    from deep_vision_tpu.tools.convergence_run import run_gan_cyclegan

    out = str(tmp_path / "cyclegan.json")
    # batch divisible by the 8-device test mesh (trainer shards over data)
    r = run_gan_cyclegan(steps=3, batch=8, size=32, out_path=out,
                         render_dir=str(tmp_path))
    for k in ("final_g_loss", "final_g_cycle", "final_d_loss"):
        assert np.isfinite(r[k]), k
    assert r["orientation_ratio_input"] > 0
    assert os.path.exists(out) and json.load(open(out))["steps"] == 3
    assert (tmp_path / "demo_gan_cyclegan_a2b.jpg").exists()
