"""Unified telemetry: metrics registry, run journal, step-time breakdown,
span tracing, and the training health monitor.

The observability layer every perf PR reports through (SURVEY.md §2.7
records the reference's instrumentation as one examples/sec print):

- `registry`: counters / gauges / log-scale histograms, exported as
  Prometheus text format or JSONL snapshots (`Registry`, `get_registry`).
- `journal`: append-only JSONL of typed run events — manifest, steps,
  evals, checkpoints, health, crash/exit markers (`RunJournal`,
  `read_journal`).
- `stepclock`: host data-wait vs dispatch vs device-compute breakdown
  with periodic `block_until_ready` fences, plus recompile and HBM
  tracking (`StepClock`, `recompile_count`, `hbm_bytes_in_use`).
- `trace`: Chrome trace-event spans across the data pipeline, trainers,
  and inference — *where* the time went (`Tracer`, `span`, `set_tracer`).
- `health`: NaN/Inf guard with warn/skip_step/abort policies, rolling
  z-score divergence detection, and a hang watchdog that dumps thread
  stacks — *why* the run died (`HealthMonitor`, `TrainingHealthError`).
- `flight`: always-on bounded-memory flight recorder that dumps an
  atomic crc-checked postmortem bundle on crash/hang/abort/preemption —
  the black box (`FlightRecorder`, `set_flight`, `validate_bundle`).
- `autoprof`: anomaly-triggered `jax.profiler` capture with cooldown
  and budget, plus the configurable static window (`AutoProfiler`).
- `merge`: per-host journal merge + cross-host straggler detection for
  multi-host runs (`merge_journal_files`; CLI in tools/obs_merge.py),
  plus per-request trace-id stitching into causal cross-process
  timelines (`trace_timelines`; rendered by `obs_report --merged`).
- `telemetry`: the live plane — per-process HTTP `/metrics` `/varz`
  `/healthz` `/statusz` on a daemon thread, with run-dir discovery
  files and typed `telemetry_server` journal events (`TelemetryServer`;
  poller in tools/obs_poll.py).
- `propagate`: W3C-traceparent-style trace context minted at
  request/batch ingress, carried over the data-service frame protocol
  and the serve request path, auto-stamped onto journal events and
  trace spans (`TraceContext`, `new_trace`, `use`, `current`).
- `costmodel`: compiled-artifact introspection — XLA cost/memory
  analysis plus the collective inventory parsed from compiled HLO
  (`cost_summary`, `collective_inventory`, `tree_bytes`) — the
  predicted flop/byte/comm bill of every jit pair.
- `perfwatch`: the performance-attribution hook — profiles compiled
  executables where a build already happened (Engine.warmup, the
  Trainer's cached steps) into typed `perf_profile`/`perf_collective`
  events and registry gauges, and feeds the `/statusz` perf section
  (step-time quantiles, last perf-gate verdict, last trace digest);
  ledger + regression gate in tools/perf_gate.py, step-time
  decomposition in tools/trace_digest.py (`profile_compiled`,
  `telemetry_status`).
- `locksmith`: opt-in runtime lock-order sanitizer — named lock/condition
  wrappers adopted by serve/ and obs/, order-inversion + hold-time-outlier
  detection journaled as `lock_order_violation`/`lock_contention` events;
  armed in serve-smoke/chaos-smoke, a module-global None-check when
  disabled (`locksmith.lock`, `locksmith.arm`, `locksmith.report`). The
  static half is lint/concur.py (jaxlint DV101-DV104).

- `goodput`: the wall-clock attribution ledger — every second of a run
  lands in exactly one typed bucket (productive_step, data_wait,
  compile, checkpoint, host_loss_recovery, replica_respawn,
  rendezvous_wait, drain, overhead) with `sum(buckets) == wall_clock`
  by construction; live tap (`GoodputMeter`) and offline replay
  (`attribute_journal`) run the same accountant, and `goodput_frac`
  feeds the perf ledger's MAD gate.
- `alerts`: multi-window burn-rate SLO rules over the journal stream —
  serving error/latency budgets and training budgets (goodput floor,
  recompile bursts, starvation) evaluated at event time, live on
  `/alertz` and offline over merged journals, with typed
  `alert_fired`/`alert_resolved` events (`AlertEngine`,
  `evaluate_journal`).

Metric/journal/trace writers are process-0-only in single-process runs;
multi-process runs write per-host `.pN` files (registry.process_suffix)
that `tools/obs_merge.py` stitches back into one timeline.
"""
from deep_vision_tpu.obs.alerts import (
    AlertEngine,
    default_rules,
    default_serving_rules,
    default_training_rules,
    evaluate_journal,
)
from deep_vision_tpu.obs.autoprof import AutoProfiler
from deep_vision_tpu.obs.goodput import (
    GOODPUT_BUCKETS,
    GoodputAccountant,
    GoodputMeter,
    attribute_journal,
)
from deep_vision_tpu.obs.flight import (
    FlightRecorder,
    get_flight,
    set_flight,
    validate_bundle,
)
from deep_vision_tpu.obs.health import (
    HealthMonitor,
    TrainingHealthError,
    dump_all_stacks,
)
from deep_vision_tpu.obs.journal import RunJournal, read_journal
from deep_vision_tpu.obs.propagate import (
    TraceContext,
    from_traceparent,
    new_trace,
)
from deep_vision_tpu.obs.telemetry import TelemetryServer
from deep_vision_tpu.obs.trace import (
    Tracer,
    get_tracer,
    set_tracer,
    span,
    trace_event,
    traced,
)
from deep_vision_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    is_primary_host,
    process_suffix,
)
from deep_vision_tpu.obs.stepclock import (
    StepClock,
    compile_seconds,
    hbm_bytes_in_use,
    hbm_stats,
    recompile_count,
)

__all__ = [
    "AlertEngine",
    "AutoProfiler",
    "Counter",
    "GOODPUT_BUCKETS",
    "GoodputAccountant",
    "GoodputMeter",
    "FlightRecorder",
    "Gauge",
    "HealthMonitor",
    "Histogram",
    "Registry",
    "RunJournal",
    "StepClock",
    "TelemetryServer",
    "TraceContext",
    "Tracer",
    "TrainingHealthError",
    "attribute_journal",
    "compile_seconds",
    "default_rules",
    "default_serving_rules",
    "default_training_rules",
    "dump_all_stacks",
    "evaluate_journal",
    "from_traceparent",
    "get_flight",
    "get_registry",
    "get_tracer",
    "hbm_bytes_in_use",
    "hbm_stats",
    "is_primary_host",
    "new_trace",
    "process_suffix",
    "read_journal",
    "recompile_count",
    "set_flight",
    "set_tracer",
    "span",
    "trace_event",
    "traced",
    "validate_bundle",
]
