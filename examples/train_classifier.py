"""Quickstart: train a classifier end to end (the LeNet.ipynb analog).

The reference walks this flow in per-model notebooks
(LeNet/pytorch/LeNet.ipynb, VGG/pytorch/VGG16.ipynb); here it is an
executable script against the library API. Swap the model name for any
registered classifier (resnet50, vit_s16, ...) — the Trainer, loss, and
checkpointing are shared across the whole zoo.

    python examples/train_classifier.py [--model lenet5] [--epochs 3]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor a JAX_PLATFORMS override even when a site hook imported jax before
# the env var could take effect at backend init (e.g. JAX_PLATFORMS=cpu to
# run this example without an accelerator)
import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


import argparse
import tempfile

import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.core import CheckpointManager
from deep_vision_tpu.losses import classification_loss_fn
from deep_vision_tpu.models import get_model
from deep_vision_tpu.train import Trainer, build_optimizer


def quadrant_data(n=256, size=32, seed=0):
    """Synthetic 4-class stand-in for MNIST: class = brightest quadrant."""
    rng = np.random.RandomState(seed)
    images = rng.rand(n, size, size, 1).astype(np.float32) * 0.1
    labels = rng.randint(0, 4, size=n)
    half = size // 2
    for i, l in enumerate(labels):
        r, c = divmod(l, 2)
        images[i, r * half:(r + 1) * half, c * half:(c + 1) * half, 0] += 0.9
    return images, labels


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="lenet5")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--ckpt-dir", default=None)
    args = p.parse_args()

    images, labels = quadrant_data()

    def batches():
        for i in range(0, len(images) - 32 + 1, 32):
            yield {"image": images[i:i + 32], "label": labels[i:i + 32]}

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="dv_example_")
    trainer = Trainer(
        get_model(args.model, num_classes=4),
        build_optimizer("adam", 1e-3),
        classification_loss_fn,
        sample_input=jnp.zeros((8, 32, 32, 1)),
        checkpoint_manager=CheckpointManager(ckpt_dir),
        ema_decay=0.99,  # evaluate with EMA weights
    )
    trainer.fit(batches, batches, epochs=args.epochs)
    metrics = trainer.eval_step({"image": images[:64], "label": labels[:64]})
    print(f"final top-1 {float(metrics['top1']):.3f}  (checkpoints in {ckpt_dir})")


if __name__ == "__main__":
    main()
