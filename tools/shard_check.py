#!/usr/bin/env python
"""Standalone entry point for the sharding-table semantic checker.

Thin wrapper over deep_vision_tpu.tools.shard_check so the audit can
run from a checkout without installing the package:

    python tools/shard_check.py [--family vit|moe|resnet] [--format json]

Exit 0: every audited table passes its coverage floor with no
resolution errors. Exit 1: at least one table failed (gutted table,
unknown mesh axis, rank-mismatched spec).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deep_vision_tpu.tools.shard_check import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
