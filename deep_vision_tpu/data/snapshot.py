"""Input-pipeline checkpointing: make the data iterator a checkpoint citizen.

PR 10 made *training* preemption-native — a kill/resume lands on the
exact optimizer step — while the `DataLoader` silently restarted from
shard zero, re-visiting data the run had already trained on (the
classic elastic-training corruption: the step counter says epoch 3,
the input stream says epoch 0). This module closes that gap: a
`DataLoaderState` captures everything host-side the pipeline needs to
reproduce its position — epoch, batches consumed, epoch seed, the
shard cursor (which shard / which record the reader had reached), the
buffer-shuffle RNG state, and the `BadRecordBudget` spend — and rides
the PR 4 crc32c checkpoint sidecar next to the model state
(`host_state["data_state"]`), restored by `Trainer.resume()` as a
typed `data_resume` journal event.

Resume semantics (the part worth being precise about):

* Every random decision in an epoch derives from `(seed, epoch)` —
  the epoch RNG, the per-sample transform keys `(epoch_seed, k)`, and
  the shard-order reshuffle all do. Restoring therefore does NOT need
  to deserialize live RNG objects: `load_state_dict` re-arms the
  epoch counter and replays the interrupted epoch's stream
  deterministically, SKIPPING the first `batches * batch_size`
  samples at the transform boundary (the sample index `k` keeps
  advancing across the skip, so per-sample augmentation keys stay
  aligned) — the post-resume batch sequence is byte-identical to an
  uninterrupted run's, proven by `make data-smoke` and the
  chaos-smoke deterministic-resume phase on content hashes.
* The `BadRecordBudget` spend is restored to its epoch-start values
  and the replay re-spends the intra-epoch portion deterministically,
  so the budget a resumed run exhausts is the budget the uninterrupted
  run would have (dead-letter rows for the replayed prefix are
  suppressed via the budget's `replaying` latch — counters move,
  duplicate rows don't).
* The shard cursor / record offset / RNG state in the saved state are
  the producer's **read frontier** at snapshot time (what had been
  pulled from storage, which runs ahead of what the consumer had been
  handed by the shuffle buffer and in-flight transform window). They
  are the observability view — "where in the shard stream was this
  run" — and the `data_resume` event's payload; exactness of the
  resume itself comes from the deterministic replay, not from seeking
  to the frontier.
* A `fingerprint` of the source (shard list, or map-style length)
  travels in the state: restoring against a dataset that changed on
  disk raises `SnapshotMismatch` instead of silently training on a
  shifted stream.

Unsupported configurations fail loudly: `num_procs > 0` interleaves
worker output nondeterministically (`SnapshotUnsupported`); the shared
dataset service (`data/service.py`) is a continuous global stream whose
clients snapshot nothing — its resume story is the service's own
restart plus the trainer's step checkpoint.

jax-free (like the rest of data/): importable from spawned workers.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Optional

import numpy as np

STATE_VERSION = 1


class SnapshotError(RuntimeError):
    """Base for input-pipeline snapshot failures."""


class SnapshotUnsupported(SnapshotError):
    """The loader's configuration cannot snapshot (num_procs > 0: worker
    interleave order is nondeterministic, so no host-side state can
    reproduce the stream)."""


class SnapshotMismatch(SnapshotError):
    """The saved state does not match the current pipeline (dataset
    changed on disk, different batch size/seed): resuming would silently
    re-visit or skip data, so refusing is the only honest answer."""


@dataclasses.dataclass
class DataLoaderState:
    """One resumable position of a DataLoader's batch stream.

    epoch/batches are the exact resume point (consumer side); cursor,
    rng, and budget are the producer's read frontier at that point
    (see module docstring). Everything is JSON-serializable so the
    state rides the checkpoint's crc32c host sidecar unchanged.
    """

    epoch: int
    batches: int
    epoch_seed: int
    fingerprint: str
    cursor: Optional[Dict[str, Any]] = None  # shard/shard_index/record/read
    rng: Optional[Dict[str, Any]] = None     # np.Generator bit_generator state
    budget: Optional[Dict[str, int]] = None  # {"bad": n, "ok": n} at frontier
    budget_epoch_start: Optional[Dict[str, int]] = None
    version: int = STATE_VERSION

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "DataLoaderState":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def validate_state(d: dict) -> DataLoaderState:
    """Parse + sanity-check a state dict from a sidecar; raises
    SnapshotMismatch on anything unusable (version from the future,
    negative counters) rather than resuming on garbage."""
    try:
        st = DataLoaderState.from_dict(d)
    except TypeError as e:
        raise SnapshotMismatch(f"unusable data_state: {e}") from None
    if st.version > STATE_VERSION:
        raise SnapshotMismatch(
            f"data_state version {st.version} is from a newer writer "
            f"(this reader knows {STATE_VERSION})")
    if st.epoch < 0 or st.batches < 0:
        raise SnapshotMismatch(
            f"data_state has negative position (epoch={st.epoch}, "
            f"batches={st.batches})")
    return st


def fingerprint(dataset, batch_size: int, seed: int,
                shuffle: bool = False, shuffle_buffer: int = 0,
                drop_remainder: bool = False,
                host_shard: Optional[tuple] = None) -> str:
    """Identity of the stream a state belongs to: the shard list for
    record-backed datasets, the length for map-style ones, plus EVERY
    loader knob that changes the sample order or batch boundaries —
    shuffle/shuffle_buffer permute the post-shuffle order `skip` counts
    in, drop_remainder moves the epoch boundary, and `host_shard`
    (shard_index, num_shards) pins WHICH host's slice of a multi-host
    world this stream is: a snapshot taken at world N must refuse
    restore at world M (the elastic-resize contract — the re-derived
    slice is a different stream, and replaying the old position on it
    would silently re-visit/skip data). Saved into every state; a
    mismatch at restore is a changed-stream signal."""
    h = hashlib.sha1()
    h.update(f"bs={batch_size};seed={seed};sh={int(shuffle)};"
             f"buf={shuffle_buffer};dr={int(drop_remainder)};".encode())
    if host_shard is not None:
        idx, n = host_shard
        h.update(f"hs={int(idx)}/{int(n)};".encode())
    files = getattr(dataset, "files", None)
    if files is not None:
        import os

        for f in files:
            h.update(str(f).encode())
            # shard SIZE too: a rebuilt shard under the same name is a
            # different stream (full content hashing would cost a read
            # of the dataset; size catches the common rebuild cheaply)
            try:
                h.update(f";{os.path.getsize(f)}\n".encode())
            except OSError:
                h.update(b";?\n")
    else:
        try:
            h.update(f"len={len(dataset)}".encode())
        except TypeError:
            h.update(b"iterable")
    h.update(type(dataset).__name__.encode())
    return h.hexdigest()


def rng_state(rng: np.random.Generator) -> dict:
    """JSON-clean snapshot of a numpy Generator's bit-generator state
    (observability + cross-run comparison; restore replays instead of
    deserializing — see module docstring)."""
    return json.loads(json.dumps(rng.bit_generator.state, default=int))


class LiveCursor:
    """The producer-updated shard read frontier.

    A RecordDataset with a cursor attached updates it as it reads:
    shard index within the epoch's (possibly reshuffled) order, shard
    path, record index within the shard, and total records read this
    epoch. Single-writer; readers snapshot via one atomic tuple load,
    so no lock sits on the per-record hot path.
    """

    __slots__ = ("_v",)

    def __init__(self):
        self._v = (0, None, 0, 0)  # (shard_index, shard_path, record, read)

    def begin_epoch(self) -> None:
        self._v = (0, None, 0, 0)

    def begin_shard(self, index: int, path: str) -> None:
        _, _, _, read = self._v
        self._v = (index, path, 0, read)

    def advance(self) -> None:
        si, path, rec, read = self._v
        self._v = (si, path, rec + 1, read + 1)

    def read_count(self) -> int:
        return self._v[3]

    def snapshot(self) -> dict:
        si, path, rec, read = self._v
        return {"shard_index": si, "shard": path, "record": rec,
                "read": read}
