"""Shape tests for every classifier in the zoo (reference had none; SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import pytest

from deep_vision_tpu.models import get_model

RNG = jax.random.PRNGKey(0)


def _init_apply(model, x, train=False):
    variables = model.init({"params": RNG, "dropout": RNG}, x, train=train)
    out = model.apply(
        variables, x, train=train,
        rngs={"dropout": RNG},
        mutable=["batch_stats"] if "batch_stats" in variables else False,
    )
    if isinstance(out, tuple) and len(out) == 2 and isinstance(out[1], dict):
        out = out[0]
    return out, variables


@pytest.mark.parametrize(
    "name,shape,classes",
    [
        ("lenet5", (2, 32, 32, 1), 10),
        ("alexnet1", (1, 227, 227, 3), 17),
        ("alexnet2", (1, 224, 224, 3), 17),
        ("vgg16", (1, 224, 224, 3), 17),
        ("vgg19", (1, 224, 224, 3), 17),
        ("resnet34", (1, 224, 224, 3), 17),
        ("resnet50", (1, 224, 224, 3), 17),
        ("resnet152", (1, 96, 96, 3), 17),
        ("resnet50v2", (1, 224, 224, 3), 17),
        ("mobilenet1", (1, 224, 224, 3), 17),
        ("shufflenet1", (1, 224, 224, 3), 17),
    ],
)
def test_classifier_eval_shapes(name, shape, classes):
    model = get_model(name, num_classes=classes)
    out, _ = _init_apply(model, jnp.zeros(shape))
    assert out.shape == (shape[0], classes)
    assert out.dtype == jnp.float32


def test_inception_v1_aux_heads():
    model = get_model("inception1", num_classes=11)
    x = jnp.zeros((1, 224, 224, 3))
    out, variables = _init_apply(model, x, train=True)
    logits, aux1, aux2 = out
    assert logits.shape == aux1.shape == aux2.shape == (1, 11)
    # eval mode: single output
    out_eval = model.apply(variables, x, train=False)
    assert out_eval.shape == (1, 11)


def test_inception_v3_aux_head():
    model = get_model("inception3", num_classes=7)
    x = jnp.zeros((1, 299, 299, 3))
    out, _ = _init_apply(model, x, train=True)
    logits, aux = out
    assert logits.shape == (1, 7)
    assert aux.shape == (1, 7)


def test_mobilenet_alpha_shrinks_params():
    import numpy as np

    def nparams(model, x):
        v = model.init({"params": RNG, "dropout": RNG}, x, train=False)
        return sum(np.prod(p.shape) for p in jax.tree_util.tree_leaves(v["params"]))

    x = jnp.zeros((1, 224, 224, 3))
    full = nparams(get_model("mobilenet1", num_classes=10, alpha=1.0), x)
    half = nparams(get_model("mobilenet1", num_classes=10, alpha=0.5), x)
    assert half < full * 0.5


def test_shufflenet_channel_shuffle_roundtrip():
    from deep_vision_tpu.nn.layers import channel_shuffle

    x = jnp.arange(2 * 1 * 1 * 12, dtype=jnp.float32).reshape(2, 1, 1, 12)
    y = channel_shuffle(x, 3)
    # shuffling with g then with c//g is the identity permutation inverse
    z = channel_shuffle(y, 4)
    assert jnp.allclose(z, x)
    assert not jnp.allclose(y, x)
