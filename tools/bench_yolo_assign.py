"""Measure on-device YOLO anchor assignment cost inside the train step.

VERDICT r1 weak #6: `yolo_train_loss_fn` rebuilds the 3-scale target grids
from padded GT boxes inside every jitted step; this times the full YOLOv3
train step with (a) on-device assignment from `boxes`/`classes` and (b)
precomputed host labels fed as arrays, on the real chip.
"""
from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def build(batch_size=16, image=416, n_boxes=20, host_labels=False):
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.yolo import yolo_loss_fn, yolo_train_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.ops.anchors import assign_anchors_to_grid
    from deep_vision_tpu.ops.boxes import xyxy_to_xywh
    from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding, replicated
    from deep_vision_tpu.train.optimizers import build_optimizer

    mesh = create_mesh()
    model = get_model("yolov3", num_classes=80, dtype=jnp.bfloat16)
    tx = build_optimizer("adam", 1e-3)
    state = create_train_state(
        model, tx, jnp.ones((2, image, image, 3), jnp.float32)
    )
    state = jax.device_put(state, replicated(mesh))

    rng = np.random.RandomState(0)
    cxy = rng.rand(batch_size, n_boxes, 2) * 0.8 + 0.1
    wh = rng.rand(batch_size, n_boxes, 2) * 0.15 + 0.02
    boxes = np.concatenate([cxy - wh / 2, cxy + wh / 2], -1).astype(np.float32)
    boxes[:, 10:] = 0.0  # half the rows padded
    classes = rng.randint(0, 80, size=(batch_size, n_boxes)).astype(np.int32)
    batch = {
        "image": rng.rand(batch_size, image, image, 3).astype(np.float32),
        "boxes": boxes,
        "classes": classes,
    }
    grid = image // 32
    grids = (grid, grid * 2, grid * 4)

    if host_labels:
        xywh = np.asarray(xyxy_to_xywh(jnp.asarray(boxes)))
        labels = jax.vmap(
            lambda b, c: tuple(assign_anchors_to_grid(b, c, grids))
        )(jnp.asarray(xywh), jnp.asarray(classes))
        batch = {
            "image": batch["image"],
            "boxes": xywh,
            "labels": tuple(np.asarray(l) for l in labels),
        }
        loss_fn = yolo_loss_fn
    else:
        loss_fn = functools.partial(yolo_train_loss_fn, grid_sizes=grids)

    batch = jax.tree_util.tree_map(
        lambda v: jax.device_put(np.asarray(v),
                                 data_sharding(mesh, np.asarray(v).ndim)),
        batch,
    )

    def train_step(state, batch):
        def lf(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            outputs, nms = state.apply_fn(
                variables, batch["image"], train=True, mutable=["batch_stats"]
            )
            loss, _ = loss_fn(outputs, batch)
            return loss, nms["batch_stats"]

        (loss, nbs), grads = jax.value_and_grad(lf, has_aux=True)(state.params)
        return state.apply_gradients(grads).replace(batch_stats=nbs), loss

    return jax.jit(train_step, donate_argnums=0), state, batch


def timeit(name, host_labels):
    step, state, batch = build(host_labels=host_labels)
    for _ in range(4):
        state, loss = step(state, batch)
    float(loss)
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            state, loss = step(state, batch)
        float(loss)
        dts.append((time.perf_counter() - t0) / 10)
    print(f"{name}: med {np.median(dts)*1e3:.1f} min {min(dts)*1e3:.1f} ms/step",
          flush=True)


if __name__ == "__main__":
    timeit("on-device assignment", host_labels=False)
    timeit("host labels          ", host_labels=True)
