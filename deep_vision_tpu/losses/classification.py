"""Classification losses: softmax CE (+ label smoothing) with aux-head support.

Replaces `nn.CrossEntropyLoss` (ResNet/pytorch/train.py:358) and Keras
`categorical_crossentropy` (ResNet/tensorflow/train.py:275-297), and fixes the
Inception aux-head plumbing the reference broke (SURVEY.md §2.9): a model may
return `logits` or a tuple `(logits, *aux_logits)`; aux heads are weighted
0.3 as in the GoogLeNet paper.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import optax

from deep_vision_tpu.core.metrics import topk_accuracy


def cross_entropy_loss(logits, labels, label_smoothing: float = 0.0, weights=None):
    """Mean softmax cross entropy; labels are int class ids. `weights` (B,)
    masks padded rows of the final partial batch."""
    num_classes = logits.shape[-1]
    onehot = jnp.asarray(
        optax.smooth_labels(
            jnp.eye(num_classes, dtype=jnp.float32)[labels], label_smoothing
        )
    )
    ce = optax.softmax_cross_entropy(logits, onehot)
    if weights is None:
        return jnp.mean(ce)
    return jnp.sum(ce * weights) / jnp.maximum(jnp.sum(weights), 1e-9)


def classification_loss_fn(
    outputs,
    batch,
    aux_weight: float = 0.3,
    label_smoothing: float = 0.0,
    penalty_weight: float = 0.01,
):
    """loss + metrics from model outputs (logits or (logits, *aux)) + batch.

    batch: {'image': ..., 'label': int (B,)}.
    Aux entries may be logits tensors (Inception heads: weighted CE at
    `aux_weight`) or dicts of named scalar penalties (e.g. the ViT-MoE
    Switch load-balancing loss, key 'moe_aux': added at `penalty_weight`
    and surfaced as a metric).
    """
    labels = batch["label"]
    weights = batch.get("_mask")
    aux_logits = ()
    if isinstance(outputs, (tuple, list)):
        logits, *aux_logits = outputs
    else:
        logits = outputs
    loss = cross_entropy_loss(logits, labels, label_smoothing, weights)
    metrics = {}
    for aux in aux_logits:
        if aux is None:
            continue
        if isinstance(aux, dict):
            for name, value in aux.items():
                # '_'-prefixed names are DIAGNOSTIC metrics, surfaced but
                # never added to the loss (e.g. the MoE router entropy /
                # expert-load telemetry from models/vit.py). The reserved-
                # key guard applies to the SURFACED name: '_loss' would be
                # silently clobbered by the real loss below.
                if name.startswith("_"):
                    if name[1:] in ("loss", "top1", "top5"):
                        raise ValueError(
                            f"aux metric name {name!r} collides with a "
                            "reserved metric key; rename it"
                        )
                    if name[1:] in metrics:
                        # same fail-loud intent as the reserved-key guard:
                        # '_x' next to a penalty 'x' (or a repeated name
                        # across aux dicts) would silently last-writer-win
                        raise ValueError(
                            f"duplicate aux metric name {name[1:]!r}; "
                            "rename one of the colliding aux outputs"
                        )
                    metrics[name[1:]] = value
                    continue
                # reserved keys are written below and would silently
                # swallow the penalty's metric (the penalty itself would
                # still be added to the loss — a confusing half-effect)
                if name in ("loss", "top1", "top5"):
                    raise ValueError(
                        f"aux penalty name {name!r} collides with a reserved "
                        "metric key; rename it (e.g. 'aux_" + name + "')"
                    )
                if name in metrics:
                    raise ValueError(
                        f"duplicate aux penalty name {name!r}; rename one "
                        "of the colliding aux outputs"
                    )
                loss = loss + penalty_weight * value
                metrics[name] = value
        else:
            loss = loss + aux_weight * cross_entropy_loss(
                aux, labels, label_smoothing, weights
            )
    metrics["loss"] = loss
    metrics.update(topk_accuracy(logits, labels, weights=weights))
    return loss, metrics
