"""EMA evaluation weights: math, trainer integration, checkpoint round-trip."""
import jax
import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.core import CheckpointManager
from deep_vision_tpu.losses import classification_loss_fn
from deep_vision_tpu.models import get_model
from deep_vision_tpu.train import Trainer, build_optimizer
from deep_vision_tpu.train.ema import EmaParams
import pytest

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)


def test_ema_math_matches_reference():
    params = {"w": jnp.zeros((3,))}
    ema = EmaParams(params, decay=0.9, warmup=False)
    ref = np.zeros(3)
    for step in range(5):
        new = {"w": jnp.full((3,), float(step + 1))}
        ema.update(new)
        ref = ref * 0.9 + (step + 1) * 0.1
    np.testing.assert_allclose(np.asarray(ema.params["w"]), ref, rtol=1e-6)


def test_ema_warmup_tracks_early_params_closely():
    params = {"w": jnp.zeros((2,))}
    ema = EmaParams(params, decay=0.9999)  # warmup on
    ema.update({"w": jnp.ones((2,))})
    # step 1 decay is min(0.9999, 2/11) -> ema ~0.82, not ~1e-4
    assert float(ema.params["w"][0]) > 0.5


def _data(n=128, seed=0):
    rng = np.random.RandomState(seed)
    images = rng.rand(n, 32, 32, 1).astype(np.float32) * 0.1
    labels = rng.randint(0, 4, size=n)
    for i, l in enumerate(labels):
        r, c = divmod(l, 2)
        images[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16, 0] += 0.9
    return images, labels


def _batches(images, labels, bs=32):
    for i in range(0, len(images) - bs + 1, bs):
        yield {"image": images[i:i + bs], "label": labels[i:i + bs]}


def test_resume_with_ema_from_pre_ema_checkpoint(mesh8, tmp_path):
    """Enabling --ema-decay on an existing run must not break resume: the
    main checkpoint structure is flag-independent (EMA lives in a sibling
    dir) and the shadow seeds from the restored weights."""
    images, labels = _data()

    def make(ema):
        return Trainer(
            get_model("lenet5", num_classes=4),
            build_optimizer("adam", 1e-3),
            classification_loss_fn,
            sample_input=jnp.zeros((8, 32, 32, 1)),
            mesh=mesh8,
            checkpoint_manager=CheckpointManager(str(tmp_path)),
            ema_decay=ema,
        )

    t1 = make(None)
    t1.fit(lambda: _batches(images, labels), epochs=1)
    step1 = int(t1.state.step)

    t2 = make(0.99)  # flag turned on mid-run
    assert t2.resume() == 1
    assert int(t2.state.step) == step1
    # shadow seeded from the restored params, not the fresh init
    for a, b in zip(jax.tree_util.tree_leaves(t2.ema.params),
                    jax.tree_util.tree_leaves(t2.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    # and the reverse: a run saved WITH ema resumes fine without the flag
    t2.fit(lambda: _batches(images, labels), epochs=2, start_epoch=1)
    t3 = make(None)
    assert t3.resume() == 2


def test_trainer_ema_eval_and_checkpoint_roundtrip(mesh8, tmp_path):
    images, labels = _data()

    def make():
        return Trainer(
            get_model("lenet5", num_classes=4),
            build_optimizer("adam", 1e-3),
            classification_loss_fn,
            sample_input=jnp.zeros((8, 32, 32, 1)),
            mesh=mesh8,
            checkpoint_manager=CheckpointManager(str(tmp_path)),
            ema_decay=0.99,
        )

    trainer = make()
    trainer.fit(lambda: _batches(images, labels),
                lambda: _batches(images, labels), epochs=2)
    assert trainer.ema is not None and trainer.ema._count > 0
    # EMA weights differ from the raw optimum but still classify well
    m = trainer.eval_step({"image": images[:64], "label": labels[:64]})
    assert float(m["top1"]) > 0.9
    raw_leaf = jax.tree_util.tree_leaves(trainer.state.params)[0]
    ema_leaf = jax.tree_util.tree_leaves(trainer.ema.params)[0]
    assert float(jnp.max(jnp.abs(raw_leaf - ema_leaf))) > 0

    # resume restores both the raw state and the EMA shadow
    trainer2 = make()
    next_epoch = trainer2.resume()
    assert next_epoch == 2
    assert trainer2.ema._count == trainer.ema._count
    for a, b in zip(jax.tree_util.tree_leaves(trainer.ema.params),
                    jax.tree_util.tree_leaves(trainer2.ema.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    m2 = trainer2.eval_step({"image": images[:64], "label": labels[:64]})
    np.testing.assert_allclose(float(m2["top1"]), float(m["top1"]))
