"""Shared helpers for the smoke harnesses (serve/fleet/cache/host/chaos).

One tolerant JSONL reader instead of five drifting copies: smokes read
journals whose FINAL line may be torn (a SIGKILLed child's signature),
so undecodable lines are skipped, a missing file is an empty list, and
the caller asserts on the events that did land.
"""
from __future__ import annotations

import json
import os
from typing import List


def read_jsonl(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # a torn final line (crash/SIGKILL mid-write)
    return out
