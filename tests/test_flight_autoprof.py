"""Flight recorder, anomaly-triggered profiling, and multi-host journal
aggregation (obs/flight.py, obs/autoprof.py, obs/merge.py + the tools/
CLIs and the trainer wiring)."""
import json
import os
import zlib

import numpy as np
import pytest

from deep_vision_tpu.obs import (
    AutoProfiler,
    FlightRecorder,
    Registry,
    RunJournal,
    read_journal,
    set_flight,
)
from deep_vision_tpu.obs import flight as flight_mod
from deep_vision_tpu.obs.flight import find_bundles, validate_bundle


@pytest.fixture(autouse=True)
def _clean_global_obs_state():
    """Flight recorder and profiler latch are process-global; a test that
    leaks either would poison its neighbors."""
    yield
    set_flight(None)
    from deep_vision_tpu.obs import autoprof as ap_mod

    ap_mod._release_capture()


@pytest.fixture()
def fake_profiler(monkeypatch):
    """Replace jax.profiler start/stop with call recorders: most autoprof
    tests assert the DECISIONS, not the (slow) real trace I/O."""
    import jax

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d, **kw: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    return calls


# -- flight recorder: buffers and bundles ------------------------------------

def _step_row(i, ms=10.0):
    return {"event": "step", "ts": 1000.0 + i, "run_id": "r", "step": i,
            "step_time_ms": ms, "data_wait_ms": 1.0}


def test_flight_observe_routes_and_bounds(tmp_path):
    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r",
                        max_steps=8, max_tail=16, max_health=4)
    for i in range(100):
        fr.observe(_step_row(i))
    fr.observe({"event": "health", "ts": 2000.0, "run_id": "r",
                "kind": "loss_spike"})
    assert len(fr._steps) == 8          # bounded
    assert len(fr._tail) == 16
    assert fr._steps[-1]["step"] == 99  # ...keeping the most recent
    assert len(fr._health) == 1
    fr.close()


def test_flight_dump_bundle_valid_and_latched(tmp_path):
    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
    for i in range(5):
        fr.observe(_step_row(i))
    fr.note("data_worker_restart", worker=2)
    p1 = fr.dump("manual")
    assert p1 and os.path.basename(p1) == "r-manual"
    assert validate_bundle(p1) == []
    man = json.load(open(os.path.join(p1, "MANIFEST.json")))
    assert man["run_id"] == "r" and man["reason"] == "manual"
    steps = [json.loads(ln) for ln in open(os.path.join(p1, "steps.jsonl"))]
    assert [s["step"] for s in steps] == list(range(5))
    notes = [json.loads(ln) for ln in open(os.path.join(p1, "notes.jsonl"))]
    assert notes[0]["category"] == "data_worker_restart"
    # latch: same reason returns the same bundle; a new reason gets its own
    assert fr.dump("manual") == p1
    p2 = fr.dump("hang")
    assert p2 != p1 and validate_bundle(p2) == []
    assert set(fr.dumped) == {"manual", "hang"}
    # atomic: no torn tmp dirs remain
    assert not [d for d in os.listdir(tmp_path / "flight") if ".tmp-" in d]
    fr.close()


def test_flight_dump_never_clobbers_prior_run(tmp_path):
    d = tmp_path / "flight"
    fr1 = FlightRecorder(str(d), run_id="r")
    p1 = fr1.dump("crash")
    fr1.close()
    fr2 = FlightRecorder(str(d), run_id="r")  # same run_id (restart)
    p2 = fr2.dump("crash")
    assert p2 != p1 and p2.endswith("-2")
    assert validate_bundle(p1) == [] and validate_bundle(p2) == []
    fr2.close()


def test_validate_bundle_detects_rot_and_truncation(tmp_path):
    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
    fr.observe(_step_row(1))
    p = fr.dump("manual")
    fr.close()
    target = os.path.join(p, "steps.jsonl")
    data = bytearray(open(target, "rb").read())
    data[0] ^= 0xFF
    open(target, "wb").write(bytes(data))
    errs = validate_bundle(p)
    assert errs and "crc32" in errs[0]
    open(target, "wb").write(bytes(data[:-2]))
    errs = validate_bundle(p)
    assert any("size" in e for e in errs)
    os.remove(target)
    errs = validate_bundle(p)
    assert any("unreadable" in e for e in errs)


def test_flight_tap_and_flight_dump_event(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = RunJournal(path, run_id="r")
    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
    fr.attach(j)
    j.manifest()
    for i in range(3):
        j.step(i, step_time_ms=5.0)
    p = fr.dump("manual")
    j.close()
    fr.close()
    events = read_journal(path)
    dumps = [e for e in events if e["event"] == "flight_dump"]
    assert len(dumps) == 1
    assert dumps[0]["reason"] == "manual"
    assert dumps[0]["outcome"] == "written"
    assert dumps[0]["dir"] == p
    # the tap fed the buffers: the bundle's tail is the journal's history
    tail = [json.loads(ln)
            for ln in open(os.path.join(p, "journal_tail.jsonl"))]
    assert [e["event"] for e in tail] == ["run_manifest"] + ["step"] * 3
    from tools.check_journal import check_journal

    assert check_journal(path, strict=True) == []


def test_flight_dumps_on_hang_and_health_abort(tmp_path):
    j = RunJournal(str(tmp_path / "j.jsonl"), run_id="r")
    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
    fr.attach(j)
    j.write("health", kind="hang", stalled_s=12.0, timeout_s=5.0,
            stacks={"MainThread": ["frame"]})
    j.write("health", kind="non_finite", action="abort", step=7,
            fields=["loss"])
    assert set(fr.dumped) == {"hang", "health_abort"}
    for p in fr.dumped.values():
        assert validate_bundle(p) == []
    j.close()
    fr.close()


def test_journal_less_health_events_reach_flight(tmp_path):
    """A run with --flight-dir but no --journal must still dump on a
    hang: HealthMonitor feeds the recorder directly when no journal tap
    can route for it."""
    from deep_vision_tpu.obs import HealthMonitor

    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
    set_flight(fr)
    h = HealthMonitor(policy="warn", registry=Registry())
    h._emit("hang", stalled_s=9.0, timeout_s=1.0, stacks={"t": ["f"]})
    assert "hang" in fr.dumped
    assert validate_bundle(fr.dumped["hang"]) == []
    health = [json.loads(ln) for ln in
              open(os.path.join(fr.dumped["hang"], "health.jsonl"))]
    assert health and health[0]["kind"] == "hang"
    fr.close()


def test_flight_atexit_dumps_only_while_armed(tmp_path):
    fr = FlightRecorder(str(tmp_path / "armed"), run_id="r")
    fr.observe(_step_row(1))
    fr._atexit()  # simulated interpreter exit without close()
    assert len(find_bundles(str(tmp_path / "armed"))) == 1
    fr.close()

    fr2 = FlightRecorder(str(tmp_path / "disarmed"), run_id="r")
    fr2.close()  # clean exit disarms
    fr2._atexit()
    assert find_bundles(str(tmp_path / "disarmed")) == []


def test_module_level_note_and_emergency_dump(tmp_path):
    # no recorder installed: both are no-ops
    flight_mod.note("probe", x=1)
    assert flight_mod.emergency_dump("manual") is None
    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
    set_flight(fr)
    flight_mod.note("probe", x=1)
    p = flight_mod.emergency_dump("manual")
    assert p is not None and validate_bundle(p) == []
    notes = [json.loads(ln) for ln in open(os.path.join(p, "notes.jsonl"))]
    assert notes and notes[0]["category"] == "probe" and notes[0]["x"] == 1
    fr.close()
    assert flight_mod.get_flight() is None  # close deregisters itself


def test_flight_bundle_snapshots_span_tail(tmp_path):
    from deep_vision_tpu.obs import Tracer, set_tracer, span

    tracer = Tracer(str(tmp_path / "t.json"), run_id="r")
    set_tracer(tracer)
    try:
        with span("unit/probe", k=1):
            pass
        fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
        p = fr.dump("manual")
        fr.close()
    finally:
        tracer.close()
        set_tracer(None)
    doc = json.load(open(os.path.join(p, "spans.json")))
    names = [e["name"] for e in doc["traceEvents"]]
    assert "unit/probe" in names


def test_journal_tap_exception_swallowed(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = RunJournal(path, run_id="r")

    def bad_tap(row):
        raise RuntimeError("observer must never kill the run")

    j.add_tap(bad_tap)
    j.write("note", note="still written")
    j.close()
    events = read_journal(path)
    assert [e["event"] for e in events] == ["note", "exit"]


# -- per-process file suffix --------------------------------------------------

def test_per_process_paths_for_followers(tmp_path, monkeypatch):
    import jax

    from deep_vision_tpu.obs.registry import process_suffix

    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(jax, "process_index", lambda: 3)
    assert process_suffix() == ".p3"
    path = str(tmp_path / "j.jsonl")
    j = RunJournal(path, run_id="r")
    # the FOLLOWER writes its own suffixed file (it would be a silent
    # non-writer under the old process-0-only contract)
    assert j.path == path + ".p3"
    j.write("note", note="from host 3")
    j.close()
    assert not os.path.exists(path)
    events = read_journal(path + ".p3")
    assert events[0]["note"] == "from host 3"

    from deep_vision_tpu.obs import Tracer

    t = Tracer(str(tmp_path / "t.json"), run_id="r")
    assert t.path.endswith(".p3")
    with t.span("probe"):
        pass
    assert t.num_events > 0  # follower collects AND writes
    t.close()
    assert os.path.exists(str(tmp_path / "t.json") + ".p3")


def test_flight_bundle_per_host_suffix(tmp_path, monkeypatch):
    """Hosts of a pod can share run_id (pid + launch second): on a shared
    flight dir their simultaneous preemption dumps must land at distinct
    per-host paths instead of racing one rename."""
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
    p = fr.dump("preempt")
    fr.close()
    assert os.path.basename(p) == "r-preempt.p1"
    assert validate_bundle(p) == []
    assert json.load(open(os.path.join(p, "MANIFEST.json")))[
        "process_index"] == 1


def test_tracer_tail(tmp_path):
    from deep_vision_tpu.obs import Tracer

    t = Tracer(str(tmp_path / "t.json"), run_id="r")
    for i in range(10):
        with t.span(f"s{i}"):
            pass
    tail = t.tail(3)
    assert len(tail) == 3
    assert tail[-1]["name"] == "s9"
    t.close()


# -- stepclock peak HBM -------------------------------------------------------

def test_hbm_stats_reads_peak():
    from deep_vision_tpu.obs.stepclock import hbm_stats

    class FakeDev:
        def memory_stats(self):
            return {"bytes_in_use": 100, "peak_bytes_in_use": 250}

    assert hbm_stats(FakeDev()) == (100, 250)

    class NoPeak:
        def memory_stats(self):
            return {"bytes_in_use": 7}

    assert hbm_stats(NoPeak()) == (7, None)

    class NoStats:
        def memory_stats(self):
            return None

    assert hbm_stats(NoStats()) == (None, None)


def test_stepclock_journals_peak_bytes(tmp_path, monkeypatch):
    from deep_vision_tpu.obs import StepClock
    from deep_vision_tpu.obs import stepclock as sc_mod

    monkeypatch.setattr(sc_mod, "hbm_stats", lambda dev=None: (100, 250))
    path = str(tmp_path / "j.jsonl")
    j = RunJournal(path, run_id="r")
    reg = Registry()
    clock = StepClock(registry=reg, journal=j, sample_every=1)
    with clock.step(batch_size=4):
        pass
    j.close()
    step = [e for e in read_journal(path) if e["event"] == "step"][0]
    assert step["hbm_bytes"] == 100
    assert step["hbm_peak_bytes"] == 250
    assert reg.gauge("hbm_peak_bytes_in_use").value == 250


# -- autoprof: windows, triggers, guards -------------------------------------

def _drive(ap, n, ms=10.0, start=1):
    for s in range(start, start + n):
        ap.on_step_start(s)
        ap.observe_step(s, {"step_time_ms": ms})
    return start + n


def test_static_window_configurable(tmp_path, fake_profiler):
    j = RunJournal(str(tmp_path / "j.jsonl"), run_id="r")
    ap = AutoProfiler(str(tmp_path / "p"), journal=j, registry=Registry(),
                      window=(3, 5))
    for s in range(1, 8):
        ap.on_step_start(s)
    ap.close()
    j.close()
    assert [c[0] for c in fake_profiler] == ["start", "stop"]
    evs = [e for e in read_journal(str(tmp_path / "j.jsonl"))
           if e["event"] == "profile_capture"]
    assert [(e["reason"], e["outcome"], e["step"]) for e in evs] == [
        ("static_window", "started", 3), ("static_window", "captured", 5)]


def test_static_window_tolerates_resume_past_start(tmp_path, fake_profiler):
    ap = AutoProfiler(str(tmp_path / "p"), registry=Registry(),
                      window=(10, 20))
    ap.on_step_start(14)  # resumed mid-window: capture starts here
    assert ap.capturing
    ap.on_step_start(20)
    assert not ap.capturing
    ap.close()
    assert [c[0] for c in fake_profiler] == ["start", "stop"]


def test_static_window_retries_while_latch_held(tmp_path, fake_profiler):
    """A static window blocked at START by another in-flight capture must
    retry at the next step inside the window, not silently drop the
    user's explicit capture request."""
    j = RunJournal(str(tmp_path / "j.jsonl"), run_id="r")
    blocker = AutoProfiler(str(tmp_path / "b"), registry=Registry(),
                           window=(1, 3))
    ap = AutoProfiler(str(tmp_path / "p"), journal=j, registry=Registry(),
                      window=(2, 10))
    blocker.on_step_start(1)   # holds the process-wide latch
    ap.on_step_start(2)        # skipped_inflight — stays pending
    assert not ap.capturing and ap.needs_step_index
    blocker.on_step_start(3)   # blocker's window ends, latch released
    ap.on_step_start(4)        # retry inside [2, 10) succeeds
    assert ap.capturing and not ap.needs_step_index
    ap.close()
    blocker.close()
    j.close()
    outcomes = [e["outcome"] for e in
                read_journal(str(tmp_path / "j.jsonl"))
                if e["event"] == "profile_capture"]
    assert outcomes == ["skipped_inflight", "started", "closed_early"]


def test_needs_step_index_expires_with_window(tmp_path, fake_profiler):
    """needs_step_index (the trainer's pay-the-device-sync gate) is True
    only while the static window is still pending — auto-only profilers
    and consumed/expired windows never cost the per-step fetch."""
    auto_only = AutoProfiler(str(tmp_path / "a"), registry=Registry(),
                             auto=True)
    assert not auto_only.needs_step_index
    auto_only.close()
    ap = AutoProfiler(str(tmp_path / "p"), registry=Registry(),
                      window=(5, 8))
    assert ap.needs_step_index
    ap.on_step_start(100)  # resumed far past the window: expire it
    assert not ap.needs_step_index and not ap.capturing
    ap.close()


def test_counterless_on_step_start_advances(tmp_path, fake_profiler):
    """Bare train_step callers (no observe_step) drive the capture
    lifecycle through the internal counter alone."""
    ap = AutoProfiler(str(tmp_path / "p"), registry=Registry(),
                      window=(2, 4))
    ap.on_step_start(2)        # real index anchors the window
    assert ap.capturing
    ap.on_step_start(None)     # counter: 3
    assert ap.capturing
    ap.on_step_start(None)     # counter: 4 -> stop boundary
    assert not ap.capturing
    ap.close()
    assert [c[0] for c in fake_profiler] == ["start", "stop"]


def test_static_window_rejects_bad_bounds(tmp_path):
    with pytest.raises(ValueError):
        AutoProfiler(str(tmp_path / "p"), registry=Registry(),
                     window=(20, 10))


def test_reentry_guard_skipped_inflight(tmp_path, fake_profiler):
    """A second trigger while a trace is in flight must not double-start
    the (process-global) profiler."""
    j = RunJournal(str(tmp_path / "j.jsonl"), run_id="r")
    ap1 = AutoProfiler(str(tmp_path / "p1"), journal=j,
                       registry=Registry(), window=(1, 100))
    ap2 = AutoProfiler(str(tmp_path / "p2"), journal=j,
                       registry=Registry(), window=(1, 100))
    ap1.on_step_start(1)
    assert ap1.capturing
    ap2.on_step_start(1)  # would have been the double-start
    assert not ap2.capturing
    ap1.close()
    ap2.close()
    j.close()
    assert [c[0] for c in fake_profiler] == ["start", "stop"]
    evs = [e for e in read_journal(str(tmp_path / "j.jsonl"))
           if e["event"] == "profile_capture"]
    assert [e["outcome"] for e in evs] == ["started", "skipped_inflight",
                                           "closed_early"]


def test_close_stops_inflight_and_releases_latch(tmp_path, fake_profiler):
    j = RunJournal(str(tmp_path / "j.jsonl"), run_id="r")
    ap = AutoProfiler(str(tmp_path / "p"), journal=j, registry=Registry(),
                      window=(1, 10_000))
    ap.on_step_start(1)
    assert ap.capturing
    ap.close()
    assert not ap.capturing
    ap.close()  # idempotent
    j.close()
    assert [c[0] for c in fake_profiler] == ["start", "stop"]
    evs = [e for e in read_journal(str(tmp_path / "j.jsonl"))
           if e["event"] == "profile_capture"]
    assert evs[-1]["outcome"] == "closed_early"
    # the latch is free again: a fresh profiler can capture
    ap2 = AutoProfiler(str(tmp_path / "p2"), registry=Registry(),
                       window=(1, 2))
    ap2.on_step_start(1)
    assert ap2.capturing
    ap2.close()


def test_step_time_z_trigger_and_cooldown(tmp_path, fake_profiler):
    j = RunJournal(str(tmp_path / "j.jsonl"), run_id="r")
    ap = AutoProfiler(str(tmp_path / "p"), journal=j, registry=Registry(),
                      auto=True, window_steps=2, cooldown_steps=30,
                      max_captures=1, z_threshold=4.0, min_history=8)
    s = _drive(ap, 12)                      # baseline
    ap.on_step_start(s)
    ap.observe_step(s, {"step_time_ms": 500.0})  # regression -> arm
    s += 1
    s = _drive(ap, 4, start=s)              # capture runs + stops
    ap.close()
    j.close()
    evs = [e for e in read_journal(str(tmp_path / "j.jsonl"))
           if e["event"] == "profile_capture"]
    assert [e["outcome"] for e in evs] == ["started", "captured"]
    assert evs[0]["reason"] == "step_time_z"
    assert evs[0]["z"] > 4.0


def test_spikes_stay_out_of_baseline(tmp_path, fake_profiler):
    """Consecutive regressions must keep registering: a spike admitted to
    the rolling window would inflate the std until triggers went blind."""
    # budget 0: every spike is evaluated (none spent inside a capture
    # window), so the trigger counter isolates the baseline-exclusion rule
    ap = AutoProfiler(str(tmp_path / "p"), registry=Registry(), auto=True,
                      cooldown_steps=0, max_captures=0,
                      z_threshold=4.0, min_history=8, window_steps=1)
    s = _drive(ap, 12)
    triggers_before = ap._c_triggers.value
    for _ in range(5):
        ap.on_step_start(s)
        ap.observe_step(s, {"step_time_ms": 500.0})
        s += 1
    ap.close()
    assert ap._c_triggers.value - triggers_before == 5


def test_static_window_does_not_consume_cooldown(tmp_path, fake_profiler):
    """Like the budget, the cooldown is spent only by TRIGGERED captures:
    a static window ending at step N must not blind the anomaly policy
    until N + cooldown."""
    j = RunJournal(str(tmp_path / "j.jsonl"), run_id="r")
    ap = AutoProfiler(str(tmp_path / "p"), journal=j, registry=Registry(),
                      window=(1, 3), auto=True, window_steps=2,
                      cooldown_steps=1000, max_captures=1,
                      z_threshold=4.0, min_history=8)
    s = _drive(ap, 14)  # consumes the static window, builds the baseline
    ap.on_step_start(s)
    ap.observe_step(s, {"step_time_ms": 500.0})  # regression right after
    s += 1
    s = _drive(ap, 4, start=s)
    ap.close()
    j.close()
    evs = [(e["reason"], e["outcome"]) for e in
           read_journal(str(tmp_path / "j.jsonl"))
           if e["event"] == "profile_capture"]
    assert ("step_time_z", "captured") in evs
    assert not any(o == "skipped_cooldown" for _r, o in evs)


def test_divergence_abort_dumps_health_abort_bundle(tmp_path):
    """The documented health_abort trigger must fire for divergence
    escalation under the abort policy, not only for non_finite aborts."""
    from deep_vision_tpu.obs import HealthMonitor, TrainingHealthError

    j = RunJournal(str(tmp_path / "j.jsonl"), run_id="r")
    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
    fr.attach(j)
    h = HealthMonitor(policy="abort", journal=j, registry=Registry(),
                      min_history=5, patience=2, z_threshold=3.0)
    with pytest.raises(TrainingHealthError):
        for step in range(40):
            h.check_step(step, loss=1.0 + 0.001 * (step % 3))
        for step in range(40, 50):
            h.check_step(step, loss=100.0)
    assert "health_abort" in fr.dumped
    assert validate_bundle(fr.dumped["health_abort"]) == []
    j.close()
    fr.close()


def test_flight_note_keeps_structured_values(tmp_path):
    fr = FlightRecorder(str(tmp_path / "flight"), run_id="r")
    fr.note("probe", mesh_shape={"data": 2, "model": 1}, dims=[1, 2])
    p = fr.dump("manual")
    fr.close()
    notes = [json.loads(ln) for ln in open(os.path.join(p, "notes.jsonl"))]
    assert notes[0]["mesh_shape"] == {"data": 2, "model": 1}
    assert notes[0]["dims"] == [1, 2]


def test_recompile_burst_trigger(tmp_path, fake_profiler):
    ap = AutoProfiler(str(tmp_path / "p"), registry=Registry(), auto=True,
                      recompile_burst=2, min_history=1000)  # z-path off
    ap.observe_step(1, {"step_time_ms": 10.0, "recompiles": 3})
    assert ap._armed is None  # first observation only sets the baseline
    ap.observe_step(2, {"step_time_ms": 10.0, "recompiles": 3})
    assert ap._armed is None  # no new compiles
    ap.observe_step(3, {"step_time_ms": 10.0, "recompiles": 6})
    assert ap._armed is not None and ap._armed[0] == "recompile_burst"
    ap.close()


def test_hbm_jump_trigger(tmp_path, fake_profiler):
    ap = AutoProfiler(str(tmp_path / "p"), registry=Registry(), auto=True,
                      hbm_jump_frac=0.25, min_history=1000)
    ap.observe_step(1, {"step_time_ms": 10.0, "hbm_peak_bytes": 1000})
    assert ap._armed is None  # high-water baseline
    ap.observe_step(2, {"step_time_ms": 10.0, "hbm_peak_bytes": 1100})
    assert ap._armed is None  # +10% < 25% jump
    ap.observe_step(3, {"step_time_ms": 10.0, "hbm_peak_bytes": 1400})
    assert ap._armed is not None and ap._armed[0] == "hbm_jump"
    ap.close()


# -- trainer integration ------------------------------------------------------

def _tiny_trainer(mesh8, **kw):
    import jax.numpy as jnp

    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    return Trainer(
        get_model("lenet5", num_classes=4),
        build_optimizer("adam", 1e-3),
        classification_loss_fn,
        jnp.ones((2, 32, 32, 1)),
        mesh=mesh8,
        **kw,
    )


def _tiny_batches(n=3, bs=8):
    rng = np.random.RandomState(0)
    return [
        {"image": rng.rand(bs, 32, 32, 1).astype(np.float32),
         "label": rng.randint(0, 4, (bs,)).astype(np.int32)}
        for _ in range(n)
    ]


def test_trainer_close_stops_inflight_autocapture(tmp_path, mesh8):
    """Satellite regression test: Trainer.close() must stop an in-flight
    (auto-)capture without leaking — journaled as closed_early, and the
    process-wide latch released for the next run."""
    path = str(tmp_path / "j.jsonl")
    journal = RunJournal(path, run_id="r")
    trainer = _tiny_trainer(
        mesh8, journal=journal,
        profile_dir=str(tmp_path / "trace"),
        profile_steps=(1, 10_000),  # stop gate unreachable in a short run
    )
    for batch in _tiny_batches(2):
        trainer.train_step(batch)
    assert trainer._profiling, "capture should be open mid-run"
    trainer.close()
    assert not trainer._profiling
    trainer.close()  # idempotent
    journal.close()
    evs = [e for e in read_journal(path) if e["event"] == "profile_capture"]
    assert [e["outcome"] for e in evs] == ["started", "closed_early"]
    from deep_vision_tpu.obs import autoprof as ap_mod

    assert not ap_mod._capture_active, "profiler latch leaked"
    found = []
    for _root, _dirs, files in os.walk(tmp_path / "trace"):
        found += files
    assert found, "closed capture produced no artifacts"


def test_trainer_static_window_journals_profile_capture(tmp_path, mesh8):
    path = str(tmp_path / "j.jsonl")
    journal = RunJournal(path, run_id="r")
    trainer = _tiny_trainer(
        mesh8, journal=journal,
        profile_dir=str(tmp_path / "trace"), profile_steps=(1, 3),
    )
    for batch in _tiny_batches(5):
        trainer.train_step(batch)
    assert not trainer._profiling
    trainer.close()
    journal.close()
    evs = [e for e in read_journal(path) if e["event"] == "profile_capture"]
    assert [(e["reason"], e["outcome"]) for e in evs] == [
        ("static_window", "started"), ("static_window", "captured")]
    from tools.check_journal import check_journal

    assert check_journal(path, strict=True) == []


# -- merge + straggler detection ----------------------------------------------

def _host_journal(tmp_path, host, slow=(), n=20, base_ms=50.0,
                  slow_ms=300.0):
    path = str(tmp_path / f"j.jsonl.p{host}")
    rows = [{"event": "run_manifest", "ts": 100.0, "kind": "train",
             "argv": [], "run_id": f"h{host}", "process_index": host,
             "process_count": 2}]
    for s in range(1, n + 1):
        rows.append({"event": "step", "ts": 100.0 + s, "run_id": f"h{host}",
                     "step": s,
                     "step_time_ms": slow_ms if s in slow else base_ms})
    rows.append({"event": "exit", "ts": 100.0 + n + 1,
                 "status": "clean_exit", "run_id": f"h{host}"})
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return path


def test_merge_detects_straggler_and_annotates_hosts(tmp_path):
    from deep_vision_tpu.obs.merge import merge_journal_files

    p0 = _host_journal(tmp_path, 0)
    p1 = _host_journal(tmp_path, 1, slow={5, 6})
    out = str(tmp_path / "merged.jsonl")
    summary = merge_journal_files([p0, p1], out)
    assert summary["hosts"] == [0, 1]
    assert len(summary["stragglers"]) == 2
    events = read_journal(out)
    assert events[0]["event"] == "note" and events[0]["note"] == "obs_merge"
    stragglers = [e for e in events if e["event"] == "straggler"]
    assert {e["step"] for e in stragglers} == {5, 6}
    assert all(e["host"] == 1 for e in stragglers)
    # 2 hosts: median of (50, 300) = 175, gap = 125
    assert stragglers[0]["gap_ms"] == pytest.approx(125.0)
    # every source event is host-annotated, and the timeline is sorted
    hosts = {e.get("host") for e in events if e["event"] == "step"}
    assert hosts == {0, 1}
    ts = [e["ts"] for e in events if e.get("ts") is not None]
    assert ts == sorted(ts)
    from tools.check_journal import check_journal

    assert check_journal(out, strict=True) == []


def test_straggler_thresholds_absolute_and_relative(tmp_path):
    from deep_vision_tpu.obs.merge import detect_stragglers

    def steps(times):
        return {h: {1: {"step": 1, "ts": 0.0, "step_time_ms": t}}
                for h, t in enumerate(times)}

    # 10ms gap: below the 25ms absolute floor even though relative is huge
    assert detect_stragglers(steps([1.0, 11.0])) == []
    # 30ms gap on a 5s step: above absolute, below relative -> noise
    assert detect_stragglers(steps([5000.0, 5030.0])) == []
    # 200ms gap on a 100ms median: both floors cleared
    out = detect_stragglers(steps([100.0, 100.0, 300.0]))
    assert len(out) == 1 and out[0]["host"] == 2
    # a step only one host reported can never flag
    assert detect_stragglers({0: {1: {"step": 1, "ts": 0.0,
                                      "step_time_ms": 900.0}}}) == []


def test_host_index_fallbacks(tmp_path):
    from deep_vision_tpu.obs.merge import host_index

    assert host_index("x.jsonl", [{"event": "run_manifest",
                                   "process_index": 7}], 0) == 7
    assert host_index("x.jsonl.p3", [], 0) == 3
    assert host_index("x.jsonl", [], 5) == 5


def test_obs_merge_cli_auto_glob(tmp_path, capsys):
    from tools.obs_merge import main as merge_main

    _host_journal(tmp_path, 0)
    _host_journal(tmp_path, 1, slow={9})
    base = str(tmp_path / "j.jsonl")
    rc = merge_main(["--auto", base])
    assert rc == 0
    out = capsys.readouterr().out
    assert "hosts [0, 1]" in out and "stragglers: 1" in out
    assert os.path.exists(base + ".merged")


def test_obs_report_merged_rendering(tmp_path, capsys):
    from deep_vision_tpu.obs.merge import merge_journal_files
    from tools.obs_report import main as report_main

    p0 = _host_journal(tmp_path, 0)
    p1 = _host_journal(tmp_path, 1, slow={5})
    out = str(tmp_path / "merged.jsonl")
    merge_journal_files([p0, p1], out)
    rc = report_main([out, "--merged"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "host 0: 20 steps" in text
    assert "host 1: 20 steps" in text
    assert "stragglers (1)" in text
    assert "gap 125.0 ms" in text


def test_span_summary_has_percentiles(tmp_path, capsys):
    from tools.obs_report import render_trace, summarize_trace

    events = [{"name": "s", "ph": "X", "ts": i, "dur": (i + 1) * 1000.0,
               "pid": 1, "tid": 1} for i in range(10)]
    path = str(tmp_path / "t.json")
    json.dump({"traceEvents": events}, open(path, "w"))
    spans = summarize_trace(path)
    assert spans[0]["count"] == 10
    assert spans[0]["p50_ms"] == pytest.approx(5.0, abs=1.1)
    assert spans[0]["p95_ms"] == pytest.approx(10.0, abs=1.1)
    text = render_trace(spans, path)
    assert "p50 ms" in text and "p95 ms" in text


# -- check_journal: new event schemas ----------------------------------------

def _write_journal(tmp_path, rows, name="j.jsonl"):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return path


BASE = [{"event": "run_manifest", "ts": 1.0, "run_id": "r",
         "kind": "train", "argv": []}]
EXIT = [{"event": "exit", "ts": 9.0, "run_id": "r",
         "status": "clean_exit"}]


def test_check_journal_accepts_new_event_types(tmp_path):
    from tools.check_journal import check_journal

    path = _write_journal(tmp_path, BASE + [
        {"event": "profile_capture", "ts": 2.0, "run_id": "r",
         "reason": "step_time_z", "outcome": "captured", "step": 40},
        {"event": "flight_dump", "ts": 3.0, "run_id": "r",
         "reason": "hang", "outcome": "written", "dir": "/tmp/x"},
        {"event": "straggler", "ts": 4.0, "run_id": "r", "step": 7,
         "gap_ms": 120.5, "host": 3},
    ] + EXIT)
    assert check_journal(path, strict=True) == []


def test_check_journal_rejects_bad_new_events(tmp_path):
    from tools.check_journal import check_journal

    path = _write_journal(tmp_path, BASE + [
        {"event": "profile_capture", "ts": 2.0, "run_id": "r",
         "reason": "vibes", "outcome": "captured"},
        {"event": "profile_capture", "ts": 2.1, "run_id": "r",
         "reason": "step_time_z", "outcome": "maybe"},
        {"event": "flight_dump", "ts": 3.0, "run_id": "r",
         "reason": "bored", "outcome": "written", "dir": "/tmp/x"},
        {"event": "flight_dump", "ts": 3.1, "run_id": "r",
         "reason": "crash", "outcome": "written"},  # missing dir
        {"event": "straggler", "ts": 4.0, "run_id": "r", "step": 7,
         "gap_ms": "huge", "host": "h3"},
    ] + EXIT)
    errs = check_journal(path, strict=True)
    assert any("profile_capture reason" in e for e in errs)
    assert any("profile_capture outcome" in e for e in errs)
    assert any("flight_dump reason" in e for e in errs)
    assert any("missing field 'dir'" in e for e in errs)
    assert any("straggler host" in e for e in errs)
    assert any("straggler gap_ms" in e for e in errs)


def test_check_journal_cli_exit_codes_new_events(tmp_path):
    from tools.check_journal import EXIT_INVALID, EXIT_OK, main

    good = _write_journal(tmp_path, BASE + [
        {"event": "profile_capture", "ts": 2.0, "run_id": "r",
         "reason": "manual", "outcome": "started"},
    ] + EXIT, name="good.jsonl")
    assert main([good, "--strict"]) == EXIT_OK
    bad = _write_journal(tmp_path, BASE + [
        {"event": "flight_dump", "ts": 2.0, "run_id": "r",
         "reason": "crash", "outcome": "lost", "dir": "/x"},
    ] + EXIT, name="bad.jsonl")
    assert main([bad, "--strict"]) == EXIT_INVALID
