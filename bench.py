"""Benchmark: ResNet-50 training throughput (images/sec) on the local chip(s).

Default mode runs the framework's real jitted train step (forward + loss +
backward + SGD update + BN stat update) on the flagship model with synthetic
ImageNet-shaped data in bfloat16 compute (fp32 params), and prints ONE JSON
line:

    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}

Baseline: the reference repo publishes no throughput for its classifiers (its
only perf number is YOLOv3 epoch time, BASELINE.md); the driver's north star
is ">= 0.9x A100x8 images/sec" for ResNet-50 (BASELINE.json). We normalize
per chip: an A100 sustains ~2900 images/sec on ResNet-50/224 mixed-precision
training (MLPerf-class recipe), so the per-chip target is 0.9 * 2900 = 2610
and vs_baseline = value_per_chip / 2610.

`--data host` / `--data fused` instead benchmark the REAL input pipeline
(SURVEY §7 hard part #1): sharded records -> JPEG decode -> augment -> host
batches (`host`), plus space-to-depth + device_put onto the chip (`fused`),
over a self-generated JPEG record fixture. The number is reported per host
CPU core (this VM has one; the 224-vCPU host of a real v5e-8 slice scales
the pipeline linearly with cores via DataLoader(num_procs=...)), with
vs_baseline = per_core / (8 * 2610 / 224) — the per-core rate at which a
full v5e-8 host (224 vCPUs) keeps all 8 chips fed.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

A100_IMG_PER_SEC = 2900.0
TARGET_PER_CHIP = 0.9 * A100_IMG_PER_SEC

BATCH_PER_CHIP = 256
IMAGE_SIZE = 224
WARMUP_STEPS = 5
TIMED_STEPS = 20
WINDOWS = 3  # report the MEDIAN window: robust to the tunnel's +-4% jitter
             # without inflating the metric the way a best-of-N min would


FIXTURE_DIR = "/tmp/deep_vision_tpu_bench_records"
# per-core feed target: 8 chips x 2610 img/s spread over a v5e-8 host's 224
# vCPUs (GCP ct5lp-hightpu-8t machine shape)
DATA_TARGET_PER_CORE = 8 * 2610.0 / 224.0


def _ensure_fixture(n_shards: int = 4, per_shard: int = 256) -> str:
    """Self-generated JPEG record shards (~45KB/img, ImageNet-like sizes)."""
    import cv2

    from deep_vision_tpu.data.example_codec import encode_example
    from deep_vision_tpu.data.records import RecordWriter

    if os.path.isdir(FIXTURE_DIR) and len(os.listdir(FIXTURE_DIR)) == n_shards:
        return FIXTURE_DIR
    os.makedirs(FIXTURE_DIR, exist_ok=True)
    rng = np.random.RandomState(0)
    for s in range(n_shards):
        path = os.path.join(FIXTURE_DIR, f"train-{s:05d}")
        # write-then-rename: a Ctrl-C'd prior run must not leave a truncated
        # shard that the count-based reuse check above would accept
        tmp = path + ".tmp"
        with RecordWriter(tmp) as w:
            for _ in range(per_shard):
                img = (rng.rand(375, 500, 3) * 60 + 90).astype(np.uint8)
                img += np.arange(500, dtype=np.uint8)[None, :, None] // 4
                ok, enc = cv2.imencode(
                    ".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, 90]
                )
                assert ok
                w.write(encode_example({
                    "image/encoded": [enc.tobytes()],
                    "image/class/label": [int(rng.randint(1, 1001))],
                }))
        os.replace(tmp, path)
    return FIXTURE_DIR


def data_main(mode: str, num_procs: int) -> None:
    """Input-pipeline benchmark: the full ImageNet train chain."""
    from deep_vision_tpu.data import Compose, DataLoader, RecordDataset
    from deep_vision_tpu.data import transforms as T

    _ensure_fixture()
    ds = RecordDataset(FIXTURE_DIR + "/*", "imagenet", shuffle_shards=True)
    chain = Compose([
        T.Rescale(256), T.RandomHorizontalFlip(), T.RandomCrop(IMAGE_SIZE),
        T.ColorJitter(0.4, 0.4, 0.4),
        T.ToFloatNormalize(expand_gray_to_rgb=True),
        T.SpaceToDepth(),  # flagship config's host half of the s2d stem
    ])
    dl = DataLoader(ds, BATCH_PER_CHIP, chain, shuffle=True,
                    shuffle_buffer=1024, num_workers=8, num_procs=num_procs,
                    drop_remainder=True)
    if mode == "fused":
        from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding

        mesh = create_mesh()
        put = lambda b: jax.device_put(
            jnp.asarray(b["image"], jnp.bfloat16),
            data_sharding(mesh, 4),
        )
    n_cores = os.cpu_count() or 1
    n = 0
    t0 = time.perf_counter()
    for batch in dl:
        if mode == "fused":
            jax.block_until_ready(put(batch))
        n += len(batch["image"])
    dt = time.perf_counter() - t0
    per_core = n / dt / n_cores
    print(
        f"bench-data: {mode} {n} imgs in {dt:.1f}s on {n_cores} core(s), "
        f"num_procs={num_procs}",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": f"imagenet_pipeline_{mode}_images_per_sec_per_core",
        "value": round(per_core, 1),
        "unit": "images/sec/core",
        "vs_baseline": round(per_core / DATA_TARGET_PER_CORE, 3),
    }))


def main() -> None:
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding, replicated
    from deep_vision_tpu.train.optimizers import build_optimizer

    devices = jax.devices()
    n_chips = len(devices)
    mesh = create_mesh(devices=devices)
    batch_size = BATCH_PER_CHIP * n_chips
    print(
        f"bench: {n_chips}x {devices[0].device_kind} | resnet50 bf16 "
        f"batch={batch_size} image={IMAGE_SIZE}",
        file=sys.stderr,
    )

    # space-to-depth stem (models/resnet.py SpaceToDepthStem): the host
    # pipeline ships (H/2, W/2, 12) images; the stem conv is math-identical
    # to 7x7/s2 but MXU-efficient. Input staged in bf16, as the real
    # pipeline does (uint8 decode -> normalize -> bf16 cast on host).
    model = get_model("resnet50", num_classes=1000, dtype=jnp.bfloat16,
                      stem="s2d")
    tx = build_optimizer("sgd", learning_rate=0.1, momentum=0.9, weight_decay=1e-4)
    sample = jnp.ones((8, IMAGE_SIZE // 2, IMAGE_SIZE // 2, 12), jnp.float32)
    state = create_train_state(model, tx, sample)
    state = jax.device_put(state, replicated(mesh))

    rng = np.random.RandomState(0)
    batch = {
        "image": rng.rand(
            batch_size, IMAGE_SIZE // 2, IMAGE_SIZE // 2, 12
        ).astype(np.float32).astype(jnp.bfloat16),
        "label": rng.randint(0, 1000, size=(batch_size,)).astype(np.int32),
    }
    batch = {
        k: jax.device_put(v, data_sharding(mesh, v.ndim)) for k, v in batch.items()
    }

    def train_step(state, batch):
        step_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            outputs, new_model_state = state.apply_fn(
                variables,
                batch["image"],
                train=True,
                rngs={"dropout": step_rng},
                mutable=["batch_stats"],
            )
            loss, _ = classification_loss_fn(outputs, batch)
            return loss, new_model_state["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        return state.apply_gradients(grads).replace(batch_stats=new_bs), loss

    step = jax.jit(train_step, donate_argnums=0)

    # Timing is closed by a host fetch of the step's loss scalar: on the
    # experimental axon platform block_until_ready() on a mesh-sharded state
    # can return before execution completes, but a device->host scalar
    # transfer cannot.
    t0 = time.perf_counter()
    for _ in range(WARMUP_STEPS):
        state, loss = step(state, batch)
    float(loss)
    print(f"bench: warmup {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    window_dts = []
    for w in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(TIMED_STEPS):
            state, loss = step(state, batch)
        float(loss)
        dt = time.perf_counter() - t0
        print(
            f"bench: window {w}: {dt / TIMED_STEPS * 1e3:.1f} ms/step",
            file=sys.stderr,
        )
        window_dts.append(dt)

    wall_img_per_sec = TIMED_STEPS * batch_size / float(np.median(window_dts))

    # Device step time from a profiler trace: on this rig the chip is
    # reached through a relay that adds a fixed per-dispatch turnaround
    # (~6 ms/step at batch 256; invariant under scan/fori multi-step
    # dispatch, see README "Performance"), which a real v5e host does not
    # pay. The chip's sustained throughput is the device-time number; wall
    # rate is reported alongside for full transparency and is the fallback
    # when no trace can be captured.
    dev_ms = _device_step_ms(step, state, batch)
    if dev_ms is not None:
        per_chip = batch_size / n_chips / (dev_ms / 1e3)
        method = "device_time_profiler"
        print(f"bench: device step {dev_ms:.1f} ms", file=sys.stderr)
    else:
        per_chip = wall_img_per_sec / n_chips
        method = "wall_time"
    print(
        json.dumps(
            {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / TARGET_PER_CHIP, 3),
                "method": method,
                "wall_images_per_sec_per_chip": round(
                    wall_img_per_sec / n_chips, 1
                ),
            }
        )
    )


def _device_step_ms(step, state, batch, n_steps: int = 10):
    """Median on-device ms/step from a jax.profiler trace (None on failure).

    Parses the trace's "/device:TPU:0" plane, "XLA Modules" line: one event
    per executed program, whose duration is the device-side execution time
    of the whole jitted train step (matmuls, DMAs and stalls included —
    everything but host/relay dispatch overhead).
    """
    import glob
    import shutil
    import tempfile

    tmpdir = tempfile.mkdtemp(prefix="dv_bench_trace_")
    try:
        jax.profiler.start_trace(tmpdir)
        for _ in range(n_steps):
            state, loss = step(state, batch)
        float(loss)
        jax.profiler.stop_trace()
        # TF ships stale generated protos; the pure-python parser accepts
        # them (must be set before google.protobuf first loads)
        os.environ.setdefault(
            "PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python"
        )
        from tensorflow.tsl.profiler.protobuf import xplane_pb2

        path = glob.glob(
            os.path.join(tmpdir, "**", "*.xplane.pb"), recursive=True
        )[0]
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        durs = []
        for plane in xs.planes:
            if not plane.name.startswith("/device:TPU"):
                continue
            for line in plane.lines:
                if line.name != "XLA Modules":
                    continue
                durs += [ev.duration_ps / 1e9 for ev in line.events]
        if len(durs) < n_steps // 2:
            return None
        return float(np.median(durs))
    except Exception as e:  # no TF proto, trace unsupported on backend, ...
        print(f"bench: no device trace ({type(e).__name__}: {e}); "
              "falling back to wall time", file=sys.stderr)
        return None
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--data", choices=["host", "fused"], default=None,
                        help="benchmark the input pipeline instead of the "
                             "train step")
    parser.add_argument("--num-procs", type=int, default=0,
                        help="decode worker processes (0 = thread pool)")
    args = parser.parse_args()
    if args.data:
        data_main(args.data, args.num_procs)
    else:
        main()
