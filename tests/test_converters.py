"""Converter round-trips: build tiny datasets on disk, convert to shards,
read back through the data layer's schemas (end-to-end format compatibility:
what `tools.convert` writes, `RecordDataset` trains from)."""
import json
import os

import cv2
import numpy as np
import pytest

from deep_vision_tpu.data import RecordDataset
from deep_vision_tpu.tools import converters as C
from deep_vision_tpu.tools.convert import main as convert_main


def _write_jpeg(path, h=24, w=32):
    img = np.random.RandomState(0).randint(0, 255, (h, w, 3), np.uint8)
    cv2.imwrite(str(path), img)


def _make_voc(tmp_path):
    root = tmp_path / "VOC2007"
    for d in ("Annotations", "JPEGImages", "ImageSets/Main"):
        os.makedirs(root / d, exist_ok=True)
    ids = ["000001", "000002", "000003"]
    for i in ids:
        _write_jpeg(root / "JPEGImages" / f"{i}.jpg")
        (root / "Annotations" / f"{i}.xml").write_text(f"""
<annotation>
  <size><width>32</width><height>24</height><depth>3</depth></size>
  <object><name>dog</name>
    <bndbox><xmin>4</xmin><ymin>6</ymin><xmax>20</xmax><ymax>18</ymax></bndbox>
  </object>
  <object><name>person</name>
    <bndbox><xmin>8</xmin><ymin>2</ymin><xmax>30</xmax><ymax>22</ymax></bndbox>
  </object>
</annotation>""")
    (root / "ImageSets/Main/train.txt").write_text("\n".join(ids) + "\n")
    return root


def test_voc_convert_roundtrip(tmp_path):
    root = _make_voc(tmp_path)
    out = tmp_path / "records"
    rc = convert_main([
        "voc", "--voc-root", str(root), "--split", "train",
        "--out-dir", str(out), "--num-shards", "2", "--workers", "1",
    ])
    assert rc == 0
    shards = sorted(os.listdir(out))
    assert len(shards) == 2
    ds = RecordDataset(str(out / "train_*"), schema="voc")
    samples = list(ds)
    assert len(samples) == 3
    s = samples[0]
    assert s["image"].shape == (24, 32, 3)
    np.testing.assert_allclose(
        s["boxes"][0], [4 / 32, 6 / 24, 20 / 32, 18 / 24], atol=1e-6
    )
    assert s["classes"].tolist() == [
        C.VOC_CLASSES.index("dog"), C.VOC_CLASSES.index("person")
    ]


def test_coco_convert_roundtrip(tmp_path):
    imgs = tmp_path / "images"
    os.makedirs(imgs)
    _write_jpeg(imgs / "img1.jpg", h=40, w=60)
    coco = {
        "images": [{"id": 7, "file_name": "img1.jpg", "width": 60, "height": 40}],
        "categories": [{"id": 18, "name": "dog"}, {"id": 1, "name": "person"}],
        "annotations": [
            {"image_id": 7, "category_id": 18, "bbox": [6, 8, 12, 16],
             "iscrowd": 0},
            {"image_id": 7, "category_id": 1, "bbox": [0, 0, 30, 20],
             "iscrowd": 1},  # crowd: dropped
        ],
    }
    jpath = tmp_path / "instances.json"
    jpath.write_text(json.dumps(coco))
    out = tmp_path / "records"
    convert_main([
        "coco", "--instances-json", str(jpath), "--images-dir", str(imgs),
        "--out-dir", str(out), "--num-shards", "1", "--workers", "1",
    ])
    (sample,) = list(RecordDataset(str(out / "train_*"), schema="coco"))
    assert sample["image"].shape == (40, 60, 3)
    np.testing.assert_allclose(
        sample["boxes"], [[6 / 60, 8 / 40, 18 / 60, 24 / 40]], atol=1e-6
    )
    # dense remap sorted by original id: person(1)->0, dog(18)->1
    assert sample["classes"].tolist() == [1]


def test_mpii_convert_roundtrip(tmp_path):
    imgs = tmp_path / "images"
    os.makedirs(imgs)
    _write_jpeg(imgs / "p.jpg", h=50, w=100)
    people = [{
        "image": "p.jpg",
        "joints": [[10 * j, 2 * j] for j in range(16)],
        "joints_vis": [1] * 8 + [0] * 8,
        "center": [50, 25],
        "scale": 1.25,
    }]
    jpath = tmp_path / "train.json"
    jpath.write_text(json.dumps(people))
    out = tmp_path / "records"
    convert_main([
        "mpii", "--json", str(jpath), "--images-dir", str(imgs),
        "--out-dir", str(out), "--num-shards", "1", "--workers", "1",
    ])
    (s,) = list(RecordDataset(str(out / "train_*"), schema="mpii"))
    assert s["keypoints"].shape == (16, 2)
    np.testing.assert_allclose(s["keypoints"][2], [20 / 100, 4 / 50], atol=1e-6)
    assert s["visibility"].tolist() == [1.0] * 8 + [0.0] * 8
    # person scale survives the round trip (feeds CropRoi's body-height pad)
    assert abs(s["scale"] - 1.25) < 1e-6


def test_imagenet_convert_roundtrip(tmp_path):
    root = tmp_path / "train_flatten"
    os.makedirs(root)
    _write_jpeg(root / "n01440764_1.JPEG")
    _write_jpeg(root / "n01443537_1.JPEG")
    synsets = tmp_path / "synsets.txt"
    synsets.write_text("n01440764\nn01443537\n")
    out = tmp_path / "records"
    convert_main([
        "imagenet", "--root", str(root), "--synsets", str(synsets),
        "--out-dir", str(out), "--num-shards", "2", "--workers", "2",
    ])
    samples = list(RecordDataset(str(out / "train_*"), schema="imagenet"))
    # writer labels are 1-based (background=0); schema shifts to 0-based
    assert sorted(int(s["label"]) for s in samples) == [0, 1]


def test_chunkify():
    assert C.chunkify(list(range(10)), 3) == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    assert C.chunkify([], 4) == []
    assert C.chunkify([1], 5) == [[1]]


def test_celeba_split(tmp_path):
    # synthetic list_attr_celeba.txt: 2 header lines, then filename + flags
    img_dir = tmp_path / "img_align_celeba"
    img_dir.mkdir()
    names = ["000001.jpg", "000002.jpg", "000003.jpg", "000004.jpg"]
    for n in names[:3]:  # 000004 intentionally missing on disk
        (img_dir / n).write_bytes(b"jpegdata-" + n.encode())
    attr = tmp_path / "list_attr_celeba.txt"
    attr.write_text(
        "4\n"
        "Attractive Male Young\n"
        "000001.jpg  1  1 -1\n"
        "000002.jpg -1 -1  1\n"
        "000003.jpg  1  1  1\n"
        "000004.jpg -1  1 -1\n"
    )
    out = tmp_path / "celeba"
    n_a, n_b = C.celeba_split(str(attr), str(img_dir), str(out), "Male")
    assert (n_a, n_b) == (2, 1)
    assert sorted(os.listdir(out / "trainA")) == ["000001.jpg", "000003.jpg"]
    assert sorted(os.listdir(out / "trainB")) == ["000002.jpg"]
    assert (out / "trainA" / "000001.jpg").read_bytes().endswith(b"000001.jpg")

    # split by a different attribute column
    out2 = tmp_path / "celeba2"
    n_a, n_b = C.celeba_split(str(attr), str(img_dir), str(out2), "Young")
    assert (n_a, n_b) == (2, 1)
    with pytest.raises(ValueError):
        C.celeba_split(str(attr), str(img_dir), str(out2), "NoSuchAttr")


def test_imagenet_bbox_pipeline(tmp_path):
    """process_bounding_boxes.py parity: XML -> relative CSV (clamped,
    min/max-swapped, synset-filtered) -> bbox fields in the Example."""
    xml_dir = tmp_path / "bbox_xml" / "n01440764"
    os.makedirs(xml_dir)
    xml = """<annotation>
      <filename>n01440764_1</filename>
      <size><width>200</width><height>100</height></size>
      <object><name>n01440764</name>
        <bndbox><xmin>20</xmin><ymin>10</ymin><xmax>100</xmax><ymax>90</ymax></bndbox>
      </object>
      <object><name>n01440764</name>
        <bndbox><xmin>180</xmin><ymin>95</ymin><xmax>150</xmax><ymax>250</ymax></bndbox>
      </object>
    </annotation>"""
    (xml_dir / "n01440764_1.xml").write_text(xml)
    other = tmp_path / "bbox_xml" / "n99999999"
    os.makedirs(other)
    (other / "n99999999_5.xml").write_text(xml.replace("n01440764", "n99999999"))

    synsets = tmp_path / "synsets.txt"
    synsets.write_text("n01440764\n")
    out_csv = tmp_path / "boxes.csv"

    from deep_vision_tpu.tools.converters import (
        imagenet_annotations,
        imagenet_bbox_csv,
        imagenet_example,
        load_bbox_csv,
    )

    stats = imagenet_bbox_csv(str(tmp_path / "bbox_xml"), str(out_csv),
                              str(synsets))
    assert stats["boxes"] == 2
    assert stats["skipped_files"] == 1  # the off-challenge synset dir

    boxes = load_bbox_csv(str(out_csv))
    # keyed by extensionless stem so .jpg/.png datasets still match
    got = boxes["n01440764_1"]
    # box 1: straight normalization by the displayed 200x100 size
    np.testing.assert_allclose(got[0], [0.1, 0.1, 0.5, 0.9], atol=1e-4)
    # box 2: inverted x pair swapped, y clamped to [0, 1]
    np.testing.assert_allclose(got[1], [0.75, 0.95, 0.9, 1.0], atol=1e-4)

    # end to end: the Example carries the reference's bbox field layout
    root = tmp_path / "train_flatten"
    os.makedirs(root)
    _write_jpeg(root / "n01440764_1.JPEG")
    annos = imagenet_annotations(str(root), str(synsets),
                                 bbox_csv=str(out_csv))
    ex = imagenet_example(annos[0])
    np.testing.assert_allclose(ex["image/object/bbox/xmin"], [0.1, 0.75],
                               atol=1e-4)
    np.testing.assert_allclose(ex["image/object/bbox/ymax"], [0.9, 1.0],
                               atol=1e-4)
    assert ex["image/object/bbox/label"] == [1, 1]

    # no-bbox run writes no bbox fields (field set matches the reference's
    # plain classifier records)
    ex2 = imagenet_example(imagenet_annotations(str(root), str(synsets))[0])
    assert "image/object/bbox/xmin" not in ex2


def test_prepare_imagenet(tmp_path):
    """untar-script.sh + flatten-script.sh + flatten-val-script.sh analog:
    per-synset tars AND an untarred tree flatten into train_flatten/, val
    images get synset-prefixed names from the labels file, and the result
    feeds imagenet_annotations directly."""
    import tarfile

    # raw layout: one synset tar, one untarred synset dir, two val images
    tars = tmp_path / "tars"
    os.makedirs(tars)
    img_src = tmp_path / "n01440764_10.JPEG"
    _write_jpeg(img_src)
    with tarfile.open(tars / "n01440764.tar", "w") as tf:
        tf.add(img_src, arcname="n01440764_10.JPEG")
    tree = tmp_path / "train_tree" / "n02119789"
    os.makedirs(tree)
    _write_jpeg(tree / "n02119789_7.JPEG")
    val = tmp_path / "val"
    os.makedirs(val)
    _write_jpeg(val / "ILSVRC2012_val_00000001.JPEG")
    _write_jpeg(val / "ILSVRC2012_val_00000002.JPEG")
    val_labels = tmp_path / "val_synsets.txt"
    val_labels.write_text("n02119789\nn01440764\n")

    out = tmp_path / "prepared"
    convert_main([
        "prepare-imagenet", "--out-dir", str(out),
        "--train-tars", str(tars), "--train-dir", str(tmp_path / "train_tree"),
        "--val-dir", str(val), "--val-synsets", str(val_labels),
    ])
    assert sorted(os.listdir(out / "train_flatten")) == [
        "n01440764_10.JPEG", "n02119789_7.JPEG"
    ]
    assert sorted(os.listdir(out / "val_flatten")) == [
        "n01440764_ILSVRC2012_val_00000002.JPEG",
        "n02119789_ILSVRC2012_val_00000001.JPEG",
    ]
    # idempotent re-run: no duplicates, no crash
    C.prepare_imagenet(str(out), train_tars=str(tars))
    assert len(os.listdir(out / "train_flatten")) == 2

    # ADVICE r4: a renamed val file that still matches the extension filter
    # must refuse loudly, not silently shift labels for part of the split
    bad = tmp_path / "val_bad"
    os.makedirs(bad)
    _write_jpeg(bad / "ILSVRC2012_val_00000001.JPEG")
    _write_jpeg(bad / "copy_of_val_2.JPEG")
    with pytest.raises(ValueError, match="unrecognized validation"):
        C.prepare_imagenet(str(tmp_path / "p2"), val_dir=str(bad),
                           val_synsets=str(val_labels))
    # a gap in the index sequence (file 1 missing, files 2-3 present) would
    # misalign every later label even though the counts match
    gap = tmp_path / "val_gap"
    os.makedirs(gap)
    _write_jpeg(gap / "ILSVRC2012_val_00000002.JPEG")
    _write_jpeg(gap / "ILSVRC2012_val_00000003.JPEG")
    with pytest.raises(ValueError, match="gap"):
        C.prepare_imagenet(str(tmp_path / "p3"), val_dir=str(gap),
                           val_synsets=str(val_labels))

    # the flattened output is exactly what the converter consumes
    synsets = tmp_path / "synsets.txt"
    synsets.write_text("n01440764\nn02119789\n")
    annos = C.imagenet_annotations(str(out / "train_flatten"), str(synsets))
    assert [a["label"] for a in annos] == [1, 2]
    vannos = C.imagenet_annotations(str(out / "val_flatten"), str(synsets))
    assert sorted(a["label"] for a in vannos) == [1, 2]
