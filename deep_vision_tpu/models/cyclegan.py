"""CycleGAN (Zhu 2017): ResNet generator + PatchGAN discriminator.

Parity targets: CycleGAN/tensorflow/models.py — generator with ReflectionPad
+ 9 ResNet blocks + two up/down sampling stages (:8-78), 70x70 PatchGAN
discriminator (:81-104). Instance norm per the paper (the reference uses BN;
we default to instance norm which is the published recipe, with `use_in=False`
to reproduce the reference exactly).
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import FusedBatchNorm

_INIT = nn.initializers.normal(0.02)


def reflect_pad(x, pad: int):
    return jnp.pad(x, [(0, 0), (pad, pad), (pad, pad), (0, 0)], mode="reflect")


class _Norm(nn.Module):
    use_in: bool = True  # instance norm (paper) vs batch norm (reference)

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.use_in:
            # instance norm: per-sample, per-channel spatial normalization
            mean = jnp.mean(x, axis=(1, 2), keepdims=True)
            var = jnp.var(x, axis=(1, 2), keepdims=True)
            x = (x - mean) / jnp.sqrt(var + 1e-5)
            scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
            bias = self.param("bias", nn.initializers.zeros, (x.shape[-1],))
            return x * scale + bias
        return FusedBatchNorm(use_running_average=not train, momentum=0.9)(x)


class ResNetBlock(nn.Module):
    features: int
    use_in: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = reflect_pad(x, 1)
        y = nn.Conv(self.features, (3, 3), padding="VALID", kernel_init=_INIT)(y)
        y = _Norm(self.use_in)(y, train)
        y = nn.relu(y)
        y = reflect_pad(y, 1)
        y = nn.Conv(self.features, (3, 3), padding="VALID", kernel_init=_INIT)(y)
        y = _Norm(self.use_in)(y, train)
        return x + y


class CycleGanGenerator(nn.Module):
    n_blocks: int = 9
    base: int = 64
    use_in: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = reflect_pad(x, 3)
        x = nn.Conv(self.base, (7, 7), padding="VALID", kernel_init=_INIT)(x)
        x = nn.relu(_Norm(self.use_in)(x, train))
        for mult in (2, 4):  # downsample
            x = nn.Conv(self.base * mult, (3, 3), strides=(2, 2), padding="SAME",
                        kernel_init=_INIT)(x)
            x = nn.relu(_Norm(self.use_in)(x, train))
        for _ in range(self.n_blocks):
            x = ResNetBlock(self.base * 4, self.use_in)(x, train)
        for mult in (2, 1):  # upsample
            x = nn.ConvTranspose(self.base * mult, (3, 3), strides=(2, 2),
                                 padding="SAME", kernel_init=_INIT)(x)
            x = nn.relu(_Norm(self.use_in)(x, train))
        x = reflect_pad(x, 3)
        x = nn.Conv(3, (7, 7), padding="VALID", kernel_init=_INIT)(x)
        return nn.tanh(x)


class PatchGanDiscriminator(nn.Module):
    """70x70 PatchGAN: 4 strided convs -> 1-channel patch logits."""

    base: int = 64
    use_in: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.base, (4, 4), strides=(2, 2), padding="SAME",
                    kernel_init=_INIT)(x)
        x = nn.leaky_relu(x, 0.2)
        for mult in (2, 4):
            x = nn.Conv(self.base * mult, (4, 4), strides=(2, 2), padding="SAME",
                        kernel_init=_INIT)(x)
            x = nn.leaky_relu(_Norm(self.use_in)(x, train), 0.2)
        x = nn.Conv(self.base * 8, (4, 4), strides=(1, 1), padding="SAME",
                    kernel_init=_INIT)(x)
        x = nn.leaky_relu(_Norm(self.use_in)(x, train), 0.2)
        return nn.Conv(1, (4, 4), strides=(1, 1), padding="SAME",
                       kernel_init=_INIT)(x)


@register_model("cyclegan_generator")
def cyclegan_generator(n_blocks: int = 9, **kw):
    return CycleGanGenerator(n_blocks=n_blocks, **kw)


@register_model("cyclegan_discriminator")
def cyclegan_discriminator(**kw):
    return PatchGanDiscriminator(**kw)
