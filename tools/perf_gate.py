"""Perf ledger + noise-aware regression gate.

    PYTHONPATH=. python tools/perf_gate.py RESULT.json [...] \
        --ledger artifacts/perf_ledger.jsonl [--journal run.jsonl] \
        [--k 4.0] [--window 8] [--min-history 3] [--bless]
    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/perf_gate.py --smoke \
        [--workdir artifacts/perf_gate]

The repo's perf story used to be write-only: BENCH_*/MULTICHIP_* JSON
artifacts accumulated with no consumer, so BENCH_r01's `vs_baseline
0.949` regression would sail through verify unnoticed. This tool is the
consumer. Every bench/smoke result appends one row to an append-only
`perf_ledger.jsonl` — stamped with the excache-style env fingerprint
(jax/jaxlib/platform/device kind+count/mesh shape), carrying its own
crc32c so torn or hand-edited rows quarantine instead of poisoning the
baseline — and is compared against the rolling per-(metric, env
fingerprint) history before it lands:

    baseline  = median of the last N same-key rows (failed rows excluded)
    threshold = max(k * 1.4826 * MAD, rel_floor * |median|)
    verdict   = fail when the new value is worse than baseline+threshold

Median +/- scaled-MAD is the noise-aware part: one outlier in the
history moves the threshold barely at all (a mean/std gate would chase
it), and the relative floor keeps a perfectly quiet history (MAD=0)
from failing runs over measurement jitter. Worse is direction-aware —
`ms` metrics regress upward, `per_sec`/`efficiency` metrics downward.
A breach exits nonzero and journals a typed `perf_regression` event;
an INTENTIONAL regression is blessed (`--bless`): the row lands with
verdict `blessed`, joins the baseline, and the gate re-anchors.

`--smoke` is the `make perf-gate` CI loop, proved end-to-end on CPU:
two seeded bench runs build the ledger, a third run slowed through the
fault-injection machinery (injected data.read io_errors absorbed at
retry-backoff cost, exactly like the pipeline's bad-record path) must
FAIL the gate via the real CLI with a strict-valid perf_regression
event — plus the collective-inventory cross-check: a data-parallel
sharded ViT table step's predicted all-reduce bytes must match its
gradient-tree size within 5% (obs/costmodel's end-to-end honesty
assertion).

Exit status: 0 = all gated results passed (or --smoke held), 1 = a
regression breached (or a smoke contract broke), 2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import google_crc32c

GATE_VERDICTS = ("pass", "fail", "insufficient_history", "blessed")

#: gate defaults — the knobs `README.md` documents
DEFAULT_K = 4.0
DEFAULT_WINDOW = 8
DEFAULT_MIN_HISTORY = 3
DEFAULT_REL_FLOOR = 0.05
#: consistency constant: MAD of a normal distribution * 1.4826 ~= sigma
MAD_SCALE = 1.4826

#: ledger rotation: past `max_rows` rows, the oldest spill to
#: `<ledger>.old` and the newest `keep_rows` stay hot
DEFAULT_MAX_ROWS = 4096
DEFAULT_KEEP_ROWS = 1024


def _row_crc(row: dict) -> int:
    """crc32c over the canonical JSON of the row WITHOUT its crc field."""
    payload = {k: v for k, v in row.items() if k != "crc"}
    blob = json.dumps(payload, sort_keys=True).encode()
    return int(google_crc32c.value(blob))


def env_key(env: dict) -> str:
    """The stable ledger-key projection of an env fingerprint: history
    is only comparable within one software+hardware+mesh world."""
    return "|".join(f"{k}={env.get(k)}" for k in sorted(env))


def default_env(mesh_shape=None) -> dict:
    """The excache env fingerprint, or a degraded host-only stamp when
    jax isn't importable (the gate must still work on bare artifacts)."""
    try:
        from deep_vision_tpu.core.excache import env_fingerprint

        return env_fingerprint(mesh_shape=mesh_shape)
    except Exception:
        import platform

        return {"jax": None, "jaxlib": None, "platform": sys.platform,
                "platform_version": platform.platform(),
                "device_kind": None, "device_count": None,
                "mesh_shape": mesh_shape}


def metric_direction(metric: str, unit: Optional[str] = None) -> str:
    """'lower' when smaller is better (times), 'higher' otherwise
    (throughput/efficiency/accuracy). Heuristic over the repo's metric
    vocabulary; rows may carry an explicit `direction` to override."""
    text = f"{metric} {unit or ''}"
    for marker in ("_ms", " ms", "wall", "latency", "_s ", "seconds",
                   "compile", "bytes", "recompiles"):
        if marker in text:
            return "lower"
    return "higher"


class PerfLedger:
    """Append-only crc-manifested JSONL perf history.

    Normal operation only ever appends (one fsynced line per result).
    `read()` validates every row's embedded crc32c; corrupt rows are
    moved to `<path>.quarantine` and the main file is rewritten without
    them (tmp+fsync+rename, the excache idiom) — a torn write costs one
    row, never the history. Past `max_rows` rows, `append` spills the
    oldest into `<path>.old` so the hot file stays scan-cheap.
    """

    def __init__(self, path: str, max_rows: int = DEFAULT_MAX_ROWS,
                 keep_rows: int = DEFAULT_KEEP_ROWS):
        self.path = path
        self.max_rows = int(max_rows)
        self.keep_rows = min(int(keep_rows), self.max_rows)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    @property
    def quarantine_path(self) -> str:
        return self.path + ".quarantine"

    @property
    def rotated_path(self) -> str:
        return self.path + ".old"

    def append(self, row: dict) -> dict:
        """Stamp + crc + append one row; returns the stored form."""
        row = dict(row)
        row.setdefault("ts", time.time())
        row["crc"] = _row_crc(row)
        line = json.dumps(row, sort_keys=True) + "\n"
        with open(self.path, "a") as f:
            f.write(line)
            f.flush()
            os.fsync(f.fileno())
        self._maybe_rotate()
        return row

    def read(self) -> List[dict]:
        """Every crc-valid row, oldest first; quarantines the rest."""
        rows, bad = self._scan()
        if bad:
            self._quarantine(rows, bad)
        return rows

    def _scan(self) -> Tuple[List[dict], List[str]]:
        rows: List[dict] = []
        bad: List[str] = []
        if not os.path.exists(self.path):
            return rows, bad
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                    if not isinstance(row, dict):
                        raise ValueError("not an object")
                    if int(row.get("crc", -1)) != _row_crc(row):
                        raise ValueError("crc mismatch")
                except (ValueError, TypeError, json.JSONDecodeError):
                    bad.append(line)
                    continue
                rows.append(row)
        return rows, bad

    def _rewrite(self, rows: List[dict]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for row in rows:
                f.write(json.dumps(row, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def _quarantine(self, rows: List[dict], bad: List[str]) -> None:
        with open(self.quarantine_path, "a") as f:
            for line in bad:
                f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._rewrite(rows)

    def _maybe_rotate(self) -> None:
        rows, bad = self._scan()
        if len(rows) + len(bad) <= self.max_rows:
            return
        if bad:
            self._quarantine(rows, bad)
        spill, keep = rows[:-self.keep_rows], rows[-self.keep_rows:]
        with open(self.rotated_path, "a") as f:
            for row in spill:
                f.write(json.dumps(row, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._rewrite(keep)


def _median(values: List[float]) -> float:
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def mad_gate(history: List[float], value: float, *,
             direction: str = "lower", k: float = DEFAULT_K,
             window: int = DEFAULT_WINDOW,
             min_history: int = DEFAULT_MIN_HISTORY,
             rel_floor: float = DEFAULT_REL_FLOOR) -> dict:
    """Verdict of one value against its rolling history (oldest first).

    Returns {"verdict", "baseline", "observed", "threshold", "window"};
    baseline/threshold are None under insufficient history.
    """
    recent = [float(v) for v in history[-int(window):]]
    if len(recent) < max(1, int(min_history)):
        return {"verdict": "insufficient_history", "baseline": None,
                "observed": float(value), "threshold": None,
                "window": len(recent)}
    med = _median(recent)
    mad = _median([abs(v - med) for v in recent])
    threshold = max(k * MAD_SCALE * mad, rel_floor * abs(med))
    worse = (float(value) - med) if direction == "lower" \
        else (med - float(value))
    return {
        "verdict": "fail" if worse > threshold else "pass",
        "baseline": round(med, 6),
        "observed": float(value),
        "threshold": round(threshold, 6),
        "window": len(recent),
    }


def gate_result(ledger: PerfLedger, metric: str, value: float, *,
                unit: Optional[str] = None, env: Optional[dict] = None,
                direction: Optional[str] = None, journal=None,
                k: float = DEFAULT_K, window: int = DEFAULT_WINDOW,
                min_history: int = DEFAULT_MIN_HISTORY,
                rel_floor: float = DEFAULT_REL_FLOOR,
                bless: bool = False, extra: Optional[dict] = None) -> dict:
    """Gate one result against the ledger, then append it.

    History is the same-(metric, env_key) rows minus failed ones — a
    regression that FAILED the gate must not become the baseline the
    next regression hides behind. `bless=True` skips the verdict and
    lands the row as `blessed`: history RESTARTS at the most recent
    blessed row (the pre-bless level must not drag the median back),
    and that one row is baseline enough on its own — blessing is an
    explicit declaration, not a sample. On `fail`, a typed
    `perf_regression` event is journaled when a journal is given.
    """
    env = env or default_env()
    key = env_key(env)
    direction = direction or metric_direction(metric, unit)
    rows_h = [r for r in ledger.read()
              if r.get("metric") == metric and r.get("env_key") == key
              and r.get("verdict") != "fail"]
    anchor = max((i for i, r in enumerate(rows_h)
                  if r.get("verdict") == "blessed"), default=None)
    if anchor is not None:
        rows_h = rows_h[anchor:]
        min_history = 1
    history = [float(r["value"]) for r in rows_h]
    if bless:
        verdict = {"verdict": "blessed", "baseline": None,
                   "observed": float(value), "threshold": None,
                   "window": len(history[-int(window):])}
    else:
        verdict = mad_gate(history, value, direction=direction, k=k,
                           window=window, min_history=min_history,
                           rel_floor=rel_floor)
    row = {
        "metric": metric,
        "value": float(value),
        "unit": unit,
        "direction": direction,
        "env": env,
        "env_key": key,
        "verdict": verdict["verdict"],
    }
    if extra:
        row.update({k_: v for k_, v in extra.items() if k_ not in row})
    ledger.append(row)
    out = dict(verdict, metric=metric, direction=direction)
    if verdict["verdict"] == "fail" and journal is not None:
        journal.write("perf_regression", metric=metric,
                      baseline=verdict["baseline"],
                      observed=verdict["observed"],
                      threshold=verdict["threshold"],
                      direction=direction, window=verdict["window"],
                      env_key=key)
    try:
        from deep_vision_tpu.obs import perfwatch

        perfwatch.note_gate(out)
    except Exception:
        pass
    return out


def _iter_results(paths: List[str]):
    """Yield (metric, value, unit, env, extra) from bench-contract JSON
    artifacts: a single object, a list, or JSONL — anything with a
    numeric `value` and a `metric`."""
    for path in paths:
        with open(path) as f:
            text = f.read()
        docs: List[dict] = []
        try:
            obj = json.loads(text)
            docs = obj if isinstance(obj, list) else [obj]
        except json.JSONDecodeError:
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    docs.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
        for doc in docs:
            if not isinstance(doc, dict):
                continue
            metric = doc.get("metric")
            value = doc.get("value")
            if not metric or not isinstance(value, (int, float)):
                continue
            extra = {kk: doc[kk] for kk in ("run", "n_devices", "multistep")
                     if kk in doc}
            yield (str(metric), float(value), doc.get("unit"),
                   doc.get("env"), extra)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("results", nargs="*",
                   help="bench-contract JSON artifacts to gate+append")
    p.add_argument("--ledger", default="artifacts/perf_ledger.jsonl")
    p.add_argument("--journal", default=None,
                   help="journal path for typed perf_regression events")
    p.add_argument("--k", type=float, default=DEFAULT_K)
    p.add_argument("--window", type=int, default=DEFAULT_WINDOW)
    p.add_argument("--min-history", type=int, default=DEFAULT_MIN_HISTORY)
    p.add_argument("--rel-floor", type=float, default=DEFAULT_REL_FLOOR)
    p.add_argument("--bless", action="store_true",
                   help="land the results as an intentional new baseline "
                        "(verdict 'blessed', no gating)")
    p.add_argument("--smoke", action="store_true",
                   help="run the make perf-gate CI loop")
    p.add_argument("--workdir", default="artifacts/perf_gate")
    args = p.parse_args(argv)

    if args.smoke:
        return smoke(args.workdir)
    if not args.results:
        p.error("no result files given (or use --smoke)")

    journal = None
    if args.journal:
        from deep_vision_tpu.obs.journal import RunJournal

        journal = RunJournal(args.journal, kind="perf_gate")
        journal.manifest(config={"tool": "perf_gate"})
    ledger = PerfLedger(args.ledger)
    failed = []
    try:
        for metric, value, unit, env, extra in _iter_results(args.results):
            out = gate_result(
                ledger, metric, value, unit=unit, env=env, journal=journal,
                k=args.k, window=args.window, min_history=args.min_history,
                rel_floor=args.rel_floor, bless=args.bless, extra=extra)
            print(f"perf_gate: {metric} = {value:g} -> {out['verdict']}"
                  + (f" (baseline {out['baseline']:g} "
                     f"threshold {out['threshold']:g})"
                     if out["baseline"] is not None else ""))
            if out["verdict"] == "fail":
                failed.append(metric)
    finally:
        if journal is not None:
            journal.close()
    if failed:
        print(f"perf_gate: REGRESSION in {len(failed)} metric(s): "
              + ", ".join(failed))
        return 1
    return 0


# -- the make perf-gate smoke ------------------------------------------------


def _smoke_bench_step_ms(steps: int = 24) -> float:
    """One seeded micro-bench: wall ms/step of a jitted matmul step fed
    through a data-read boundary that absorbs injected io_errors at
    retry-backoff cost — the same shape as the pipeline's bad-record
    path, which is what makes the fault-slowed run honest."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.resilience import faults

    rng = np.random.RandomState(0)
    batches = [rng.rand(32, 256).astype(np.float32) for _ in range(8)]
    w = jnp.asarray(rng.rand(256, 256).astype(np.float32) * 0.01)

    @jax.jit
    def step(w, x):
        return jnp.tanh(x @ w).sum()

    def read(i):
        for _ in range(4):
            try:
                faults.fire("data.read")
                return batches[i % len(batches)]
            except faults.FaultInjected:
                time.sleep(0.02)  # the retry backoff the fault costs
        return batches[i % len(batches)]

    step(w, jnp.asarray(batches[0])).block_until_ready()
    t0 = time.perf_counter()
    loss = None
    for i in range(steps):
        loss = step(w, jnp.asarray(read(i)))
    loss.block_until_ready()
    return (time.perf_counter() - t0) / steps * 1e3


def _smoke_vit_inventory(check) -> None:
    """The collective-inventory honesty cross-check: a data-parallel
    sharded ViT table step's predicted all-reduce bytes vs its gradient
    tree, within 5%. Pure DP on purpose — a model-parallel mesh mixes
    activation collectives into the bill (shard_smoke covers that
    shape); here the all-reduces ARE the gradient reduction and nothing
    else, so the equality is exact up to the loss scalars."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models.vit import ViT
    from deep_vision_tpu.obs import costmodel
    from deep_vision_tpu.parallel.mesh import create_mesh, data_sharding
    from deep_vision_tpu.parallel.shardmap import VIT_RULES
    from deep_vision_tpu.train.optimizers import build_optimizer

    mesh = create_mesh(data=len(jax.devices()), model=1)
    model = ViT(depth=2, dim=16, num_heads=2, patch=8, num_classes=8)
    tx = build_optimizer("sgd", learning_rate=0.05, momentum=0.9)
    state = create_train_state(model, tx,
                               jnp.ones((2, 16, 16, 3), jnp.float32))
    shardings, _ = VIT_RULES.resolve(state, mesh)
    state = jax.device_put(state, shardings)
    rng = np.random.RandomState(0)
    batch = {
        "image": rng.rand(16, 16, 16, 3).astype(np.float32),
        "label": (np.arange(16) % 8).astype(np.int32),
    }
    batch = {k: jax.device_put(v, data_sharding(mesh, np.asarray(v).ndim))
             for k, v in batch.items()}

    def train_step(state, batch):
        step_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            outputs = state.apply_fn(
                {"params": params}, batch["image"], train=True,
                rngs={"dropout": step_rng})
            loss, _ = classification_loss_fn(outputs, batch)
            return loss

        grads = jax.grad(loss_fn)(state.params)
        return state.apply_gradients(grads)

    # jaxlint: disable=DV003 -- inventory probe: compiled to be PARSED, never dispatched, so donation has nothing to buy
    compiled = jax.jit(train_step).lower(state, batch).compile()
    hlo = costmodel.hlo_text(compiled)
    inv = costmodel.collective_inventory(hlo) if hlo else []
    ar = costmodel.predicted_collective_bytes(inv, "all-reduce")
    grad_bytes = costmodel.tree_bytes(state.params)
    rel = abs(ar - grad_bytes) / max(1, grad_bytes)
    kinds = sorted({c["kind"] for c in inv})
    check(any(c["kind"] == "all-reduce" for c in inv),
          f"sharded ViT step inventory names its all-reduces ({kinds})")
    check(rel <= 0.05,
          f"predicted all-reduce bytes {ar} match grad-tree bytes "
          f"{grad_bytes} within 5% (off by {rel * 100:.2f}%)")


def smoke(workdir: str) -> int:
    """make perf-gate: the regression-gate loop, end to end on CPU."""
    # the forced 8-device mesh must precede jax's first backend init
    # (shard_smoke precedent) — the ViT inventory phase wants real
    # data-parallel all-reduces, not a 1-device no-op
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import shutil
    import subprocess

    from deep_vision_tpu.resilience import faults
    from tools.smoke_util import read_jsonl

    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    failures: List[str] = []

    def check(ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}")
        if not ok:
            failures.append(what)
        return ok

    ledger_path = os.path.join(workdir, "perf_ledger.jsonl")
    journal_path = os.path.join(workdir, "journal.jsonl")
    ledger = PerfLedger(ledger_path)
    metric = "perf_gate_smoke_step_ms"
    # two runs of history + min_history=2 arms the gate for the third
    gate_kw = dict(unit="ms_per_step", min_history=2, window=8)

    print("-- phase 1: two seeded bench runs build the ledger --")
    for run in (1, 2):
        ms = _smoke_bench_step_ms()
        out = gate_result(ledger, metric, ms, extra={"run": run}, **gate_kw)
        check(out["verdict"] in ("insufficient_history", "pass"),
              f"clean run {run} ({ms:.2f} ms/step) -> {out['verdict']}")
    rows = ledger.read()
    check(len(rows) == 2 and all(r.get("crc") for r in rows),
          "ledger holds 2 crc-stamped rows")
    check(all(r.get("env", {}).get("jax") and r.get("env_key")
              for r in rows),
          "every row carries the env fingerprint + ledger key")

    print("-- phase 2: a fault-slowed third run FAILS the gate --")
    faults.install_spec("data.read:io_error@0.4", seed=7)
    try:
        slow_ms = _smoke_bench_step_ms()
    finally:
        faults.install_spec(None)
    result_path = os.path.join(workdir, "slow_result.json")
    with open(result_path, "w") as f:
        json.dump({"metric": metric, "value": slow_ms,
                   "unit": "ms_per_step"}, f)
    baseline_ms = _median([r["value"] for r in rows])
    check(slow_ms > baseline_ms * 2,
          f"injected io_errors slowed the bench ({slow_ms:.2f} vs "
          f"{baseline_ms:.2f} ms/step baseline)")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), result_path,
         "--ledger", ledger_path, "--journal", journal_path,
         "--min-history", "2"],
        capture_output=True, text=True, cwd=ROOT,
        env=dict(os.environ, PYTHONPATH=ROOT))
    check(proc.returncode == 1,
          f"perf_gate CLI exits nonzero on the breach (rc={proc.returncode}"
          f", {proc.stdout.strip()!r})")
    events = read_jsonl(journal_path)
    regress = [e for e in events if e.get("event") == "perf_regression"]
    check(len(regress) == 1 and regress[0].get("metric") == metric
          and regress[0].get("observed", 0) > regress[0].get("baseline", 0),
          "typed perf_regression event journaled with baseline/observed/"
          "threshold")
    rows = ledger.read()
    check(rows and rows[-1]["verdict"] == "fail",
          "failed row lands in the ledger marked fail (excluded from "
          "future baselines)")

    print("-- phase 3: blessing re-anchors the baseline --")
    out = gate_result(ledger, metric, slow_ms, bless=True, **gate_kw)
    check(out["verdict"] == "blessed", "--bless lands without gating")
    out = gate_result(ledger, metric, slow_ms * 1.02, **gate_kw)
    check(out["verdict"] == "pass",
          f"post-bless run at the new level passes ({out['verdict']})")

    print("-- phase 4: corrupt ledger rows quarantine --")
    with open(ledger_path, "a") as f:
        f.write('{"metric": "tampered", "value": 1, "crc": 123}\n')
        f.write("not json at all\n")
    n_before = len(ledger.read())  # quarantines the two bad lines
    check(os.path.exists(ledger.quarantine_path)
          and len(read_jsonl(ledger.quarantine_path)) >= 1,
          "corrupt rows moved to the quarantine file")
    check(len(ledger.read()) == n_before,
          "ledger re-reads clean after quarantine")

    print("-- phase 5: journal validates --strict --")
    from tools.check_journal import check_journal

    errs = check_journal(journal_path, strict=True)
    check(not errs, "check_journal --strict accepts the perf_regression "
          + (f"event: {errs[:2]}" if errs else "event"))

    print("-- phase 6: sharded ViT collective inventory vs grad tree --")
    _smoke_vit_inventory(check)

    if failures:
        print(f"\nperf-gate: {len(failures)} contract(s) FAILED:")
        for what in failures:
            print("  - " + what)
        return 1
    print("\nperf-gate: all contracts held")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
