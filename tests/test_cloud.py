"""Artifact-upload hook (the Hourglass GCS cloud-run analog), local backend."""
import os

import pytest

from deep_vision_tpu.tools.cloud import upload_artifact


def test_upload_file_local(tmp_path):
    src = tmp_path / "model.bin"
    src.write_bytes(b"weights")
    dest = tmp_path / "bucket"
    manifest = tmp_path / "output.txt"
    uri = upload_artifact(str(src), str(dest), manifest_path=str(manifest))
    assert open(uri, "rb").read() == b"weights"
    assert manifest.read_text().strip() == uri


def test_upload_directory_recursive(tmp_path):
    ck = tmp_path / "ck" / "00000010"
    ck.mkdir(parents=True)
    (ck / "state.msgpack").write_bytes(b"x" * 10)
    dest = tmp_path / "store"
    uri = upload_artifact(str(tmp_path / "ck"), f"file://{dest}",
                          manifest_path=str(tmp_path / "m.txt"))
    assert os.path.exists(os.path.join(uri, "00000010", "state.msgpack"))


@pytest.mark.slow
def test_cli_upload_after_training(tmp_path, capsys):
    from deep_vision_tpu.train_cli import main

    dest = tmp_path / "artifacts"
    rc = main(["-m", "lenet5", "--fake-data", "--epochs", "1",
               "--batch-size", "16", "--fake-batches", "2",
               "--ckpt-dir", str(tmp_path / "ck"),
               "--upload-to", str(dest)])
    assert rc == 0
    assert "uploaded checkpoints to" in capsys.readouterr().out
    assert os.path.isdir(dest / "ck")
