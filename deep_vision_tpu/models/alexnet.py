"""AlexNet V1 (Krizhevsky 2012) and V2 ("one weird trick", 2014).

Parity targets: AlexNet/pytorch/models/alexnet_v1.py:33-89 (one-tower
original with LocalResponseNorm after conv1/conv2) and alexnet_v2.py:12-40
(single-column simplification, no LRN); Keras twin
AlexNet/tensorflow/models/alexnet_v2.py. 227x227x3 (v1) / 224x224x3 (v2)
inputs, 1000-way logits, dropout 0.5 in the classifier.
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import LocalResponseNorm


class AlexNetV1(nn.Module):
    num_classes: int = 1000
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(96, (11, 11), strides=(4, 4), padding="VALID")(x)
        x = nn.relu(x)
        x = LocalResponseNorm()(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(256, (5, 5), padding=[(2, 2), (2, 2)])(x)
        x = nn.relu(x)
        x = LocalResponseNorm()(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.Conv(384, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        return nn.Dense(self.num_classes)(x)


class AlexNetV2(nn.Module):
    num_classes: int = 1000
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(64, (11, 11), strides=(4, 4), padding=[(2, 2), (2, 2)])(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(192, (5, 5), padding=[(2, 2), (2, 2)])(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(nn.Conv(384, (3, 3), padding="SAME")(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding="SAME")(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding="SAME")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096)(x))
        return nn.Dense(self.num_classes)(x)


@register_model("alexnet1")
def alexnet_v1(num_classes: int = 1000, **_):
    return AlexNetV1(num_classes=num_classes)


@register_model("alexnet2")
def alexnet_v2(num_classes: int = 1000, **_):
    return AlexNetV2(num_classes=num_classes)
