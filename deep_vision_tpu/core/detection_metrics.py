"""Detection mAP (VOC/COCO-style) and pose PCKh — host-side numpy metrics.

SURVEY.md §6 names mAP as the reference's intended-but-unshipped capability
(YOLO/tensorflow/README.md:28-31 'working in progress'); PCKh likewise for
pose. These run on the host over accumulated predictions, outside jit: metric
aggregation over a full eval epoch is inherently dynamic-shape and belongs on
CPU, with only the fixed-shape per-batch inference on the TPU.

Inputs use the predictor output convention (deep_vision_tpu/inference.py):
padded fixed-size arrays with class -1 / score 0 marking padding.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence

import numpy as np


def _iou_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(N,4) x (M,4) xyxy -> (N,M) IoU."""
    if len(a) == 0 or len(b) == 0:
        return np.zeros((len(a), len(b)), np.float32)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:4], b[None, :, 2:4])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = np.clip(a[:, 2] - a[:, 0], 0, None) * np.clip(a[:, 3] - a[:, 1], 0, None)
    area_b = np.clip(b[:, 2] - b[:, 0], 0, None) * np.clip(b[:, 3] - b[:, 1], 0, None)
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / np.maximum(union, 1e-9)


def _average_precision(recall: np.ndarray, precision: np.ndarray,
                       interpolation: str) -> float:
    if interpolation == "11point":
        # VOC2007 11-point interpolation
        ap = 0.0
        for t in np.linspace(0, 1, 11):
            p = precision[recall >= t].max() if np.any(recall >= t) else 0.0
            ap += p / 11.0
        return float(ap)
    # all-point (VOC2010+/COCO style): area under the monotone precision envelope
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    mpre = np.maximum.accumulate(mpre[::-1])[::-1]
    changed = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[changed + 1] - mrec[changed]) * mpre[changed + 1]))


class DetectionEvaluator:
    """Accumulates per-image detections + ground truth, computes mAP.

    Usage:
        ev = DetectionEvaluator(num_classes)
        for each image: ev.add(pred_boxes, pred_scores, pred_classes,
                               gt_boxes, gt_classes)
        result = ev.compute(iou_threshold=0.5)  # {'mAP': ..., 'ap_per_class': ...}
    """

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        # per class: list of (score, image_id, box)
        self._dets: Dict[int, List] = defaultdict(list)
        # per (class, image_id): gt boxes
        self._gts: Dict[tuple, List] = defaultdict(list)
        self._n_images = 0

    def add(self, pred_boxes, pred_scores, pred_classes,
            gt_boxes, gt_classes) -> None:
        """One image. Padded preds (class < 0 or score <= 0) and padded GT
        rows (all-zero boxes) are dropped here."""
        img = self._n_images
        self._n_images += 1
        pred_boxes = np.asarray(pred_boxes, np.float32).reshape(-1, 4)
        pred_scores = np.asarray(pred_scores, np.float32).reshape(-1)
        pred_classes = np.asarray(pred_classes).reshape(-1)
        keep = (pred_classes >= 0) & (pred_scores > 0)
        for b, s, c in zip(pred_boxes[keep], pred_scores[keep], pred_classes[keep]):
            self._dets[int(c)].append((float(s), img, b))
        gt_boxes = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
        gt_classes = np.asarray(gt_classes).reshape(-1)
        gt_keep = np.any(gt_boxes != 0, axis=-1)
        for b, c in zip(gt_boxes[gt_keep], gt_classes[gt_keep]):
            self._gts[(int(c), img)].append(b)

    def compute(self, iou_threshold: float = 0.5,
                interpolation: str = "all") -> Dict:
        """Greedy score-ordered matching per class (the standard VOC protocol)."""
        ap_per_class = {}
        for c in range(self.num_classes):
            n_gt = sum(
                len(v) for (cc, _), v in self._gts.items() if cc == c
            )
            dets = sorted(self._dets.get(c, []), key=lambda t: -t[0])
            if n_gt == 0:
                # VOC/COCO protocol: classes absent from the ground truth are
                # excluded from the mean (their FPs are not scoreable)
                continue
            matched: Dict[int, np.ndarray] = {}
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            for i, (_, img, box) in enumerate(dets):
                gts = self._gts.get((c, img), [])
                if not gts:
                    fp[i] = 1
                    continue
                gt_arr = np.stack(gts)
                used = matched.setdefault(img, np.zeros(len(gts), bool))
                ious = _iou_matrix(box[None], gt_arr)[0]
                best = int(np.argmax(ious))
                if ious[best] >= iou_threshold and not used[best]:
                    tp[i] = 1
                    used[best] = True
                else:
                    fp[i] = 1
            ctp, cfp = np.cumsum(tp), np.cumsum(fp)
            recall = ctp / n_gt
            precision = ctp / np.maximum(ctp + cfp, 1e-9)
            ap_per_class[c] = _average_precision(recall, precision, interpolation)
        aps = list(ap_per_class.values())
        return {
            "mAP": float(np.mean(aps)) if aps else 0.0,
            "ap_per_class": ap_per_class,
            "num_images": self._n_images,
        }

    def compute_coco(self) -> Dict:
        """COCO headline metric: mAP averaged over IoU .5:.05:.95."""
        aps = [
            self.compute(iou_threshold=t)["mAP"]
            for t in np.arange(0.5, 1.0, 0.05)
        ]
        return {"mAP@[.5:.95]": float(np.mean(aps)), "mAP@.5": aps[0]}


def pck(
    pred_kpts,
    gt_kpts,
    visible,
    norm_lengths,
    alpha: float = 0.5,
) -> Dict:
    """PCK: fraction of visible keypoints within alpha * norm of ground truth.

    pred_kpts/gt_kpts: (N, J, 2) in consistent coordinates; visible: (N, J)
    boolean; norm_lengths: (N,) per-sample normalization (head segment length
    for MPII's PCKh, torso diagonal for PCK@torso).
    Returns overall PCK plus per-joint breakdown.
    """
    pred = np.asarray(pred_kpts, np.float32)[..., :2]
    gt = np.asarray(gt_kpts, np.float32)[..., :2]
    vis = np.asarray(visible, bool)
    norm = np.asarray(norm_lengths, np.float32).reshape(-1, 1)
    dist = np.linalg.norm(pred - gt, axis=-1)  # (N, J)
    correct = (dist <= alpha * np.maximum(norm, 1e-9)) & vis
    total = vis.sum()
    per_joint = []
    for j in range(gt.shape[1]):
        vj = vis[:, j].sum()
        per_joint.append(float(correct[:, j].sum() / vj) if vj else float("nan"))
    return {
        f"PCK@{alpha}": float(correct.sum() / total) if total else 0.0,
        "per_joint": per_joint,
        "num_visible": int(total),
    }


def pckh(pred_kpts, gt_kpts, visible, head_sizes, alpha: float = 0.5) -> Dict:
    """MPII PCKh: PCK normalized by head segment length (standard alpha=0.5)."""
    out = pck(pred_kpts, gt_kpts, visible, head_sizes, alpha)
    out[f"PCKh@{alpha}"] = out.pop(f"PCK@{alpha}")
    return out
