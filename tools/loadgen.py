"""Load generator + the fleet smoke: serving resilience at fleet shape.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/loadgen.py \
        [--workdir artifacts/fleet_smoke] [--replicas 3] [--rps 150]

The CI teeth behind the fleet layer of serve/ (`make fleet-smoke`, a
`make verify` prerequisite after serve-smoke): one in-process
ReplicaPool over N toy-model replicas on CPU, driven by a seeded
load generator through every fleet failure mode. `LoadGen` is also a
library — tests and future TPU runs reuse the same arrival pattern.

  1. warmup      N replicas warm their engines; every (model, bucket)
                 pair is AOT-compiled (the backend compile cache may
                 dedupe identical computations across replicas — the
                 assertion is the pair count plus a nonzero delta, and
                 ZERO compiles anywhere after this phase, asserted at
                 the end across everything below).
  2. death       sustained seeded RPS with `serve.replica:io_error@N`
                 injected: one replica dies mid-stream; ONLY its
                 in-flight requests fail (request-scoped), the journal
                 carries typed replica_lost/replica_recovered, the pool
                 respawns the replica over the surviving warmed engine,
                 and the p99 of admitted traffic holds the SLO through
                 the episode.
  3. promote     a canary weight swap under live traffic: new weights
                 load via the cross-mesh checkpoint restore, shadow-warm
                 on the SHARED executables, canary x% of real requests,
                 auto-promote; responses prove the new weights serve.
  4. rollback    a poisoned checkpoint (finite on the zeros probe,
                 overflow on real traffic — exactly the failure a
                 synthetic probe cannot catch): the canary's abort
                 health policy turns it into request errors, the
                 verdict fails, auto-rollback; the promoted weights
                 never stop serving and the base stream never sees it.
  5. shed        admission tightened (token budget + bounded queue),
                 then an overload blast: excess traffic sheds by policy
                 with typed serve_shed events (client ShedError count ==
                 journal count == counter), offered == ok+err+shed, and
                 the p99 of ADMITTED traffic still holds — overload
                 degrades by policy, not by latency collapse.
  6. drain       clean close: every admitted request flushed, the pool's
                 aggregated serve_drain balances, journals pass
                 check_journal --strict, obs_report renders the fleet
                 section, locksmith (armed since startup) reports zero
                 violations, and the flight dir is EMPTY.

Exit status 0 = every contract held; 1 = something broke.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.smoke_util import read_jsonl  # noqa: E402

IMG = (4, 4, 1)
BUCKETS = (1, 2, 4)
SLO_MS = 2000.0  # the held-through-chaos promise; generous for CI boxes


class Failures:
    def __init__(self):
        self.errors: List[str] = []

    def check(self, ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}")
        if not ok:
            self.errors.append(what)
        return ok


# -- the load generator (library surface) -------------------------------------

class LoadGen:
    """Seeded open-loop load: `n_requests` at a fixed `rps` cadence.

    `submit(model, image) -> Future` is the pool front door; a
    `ShedError` counts as shed, a `ServerClosed`/`ServeError` at submit
    as refused. The arrival pattern (request index -> model choice +
    image bytes) is fully determined by `seed`, so a canary diversion
    or a shed episode samples the exact same requests run over run.
    `rps=None` blasts with no pacing (the overload shape).
    """

    def __init__(self, submit: Callable, models: List[str],
                 rps: Optional[float], n_requests: int, seed: int = 0,
                 timeout_s: float = 120.0):
        self.submit = submit
        self.models = list(models)
        self.rps = rps
        self.n_requests = int(n_requests)
        self.seed = int(seed)
        self.timeout_s = float(timeout_s)

    def run(self) -> dict:
        import numpy as np

        from deep_vision_tpu.serve import ShedError

        rng = np.random.RandomState(self.seed)
        inter = (1.0 / self.rps) if self.rps else 0.0
        futs = []
        # completion stamped by done-callback (dispatcher thread), not by
        # the sequential result() collection below — otherwise client
        # latency would include collection-loop queueing and the /varz
        # cross-check would read pure fiction
        done_at: dict = {}
        shed = refused = 0
        t0 = time.perf_counter()
        for i in range(self.n_requests):
            if inter:
                target = t0 + i * inter
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            model = self.models[int(rng.randint(len(self.models)))]
            image = rng.rand(*IMG).astype(np.float32)
            t_sub = time.perf_counter()
            try:
                fut = self.submit(model, image)
            except ShedError:
                shed += 1
                continue
            except Exception:
                refused += 1
                continue
            fut.add_done_callback(
                lambda f, i=i: done_at.__setitem__(i, time.perf_counter()))
            futs.append((i, t_sub, fut))
        ok_lat: List[float] = []
        errors = 0
        deadline = time.perf_counter() + self.timeout_s
        for i, t_sub, fut in futs:
            try:
                fut.result(timeout=max(0.1, deadline - time.perf_counter()))
                t_done = done_at.get(i, time.perf_counter())
                ok_lat.append((t_done - t_sub) * 1e3)
            except ShedError:
                # socket mode sheds ASYNCHRONOUSLY: the verdict rides
                # the response (429/503), not the submit call — it is
                # still a shed, not an error
                shed += 1
            except Exception:
                errors += 1
        wall_s = time.perf_counter() - t0
        ok_lat.sort()

        def pct(q: float) -> float:
            if not ok_lat:
                return 0.0
            return ok_lat[min(len(ok_lat) - 1,
                              int(round(q * (len(ok_lat) - 1))))]

        return {
            "offered": self.n_requests, "ok": len(ok_lat),
            "errors": errors, "shed": shed, "refused": refused,
            "wall_s": round(wall_s, 3),
            "offered_rps": round(self.n_requests / wall_s, 1) if wall_s else 0,
            "p50_ms": round(pct(0.50), 3),
            "p95_ms": round(pct(0.95), 3),
            "p99_ms": round(pct(0.99), 3),
        }


def crosscheck_varz(stats: dict, host: str, port: int, models,
                    tol_ratio: float = 4.0, tol_abs_ms: float = 100.0) -> dict:
    """Client-observed latency percentiles vs the server's /varz SLO
    histograms (serve/slo.py `serve_request_latency_ms{model=}`).

    Both sides time nearly the same span (submit -> result), but the
    server's quantiles are bucket-resolution on a log scale (~2.2x per
    bucket at 3/decade), so the tolerance is a ratio band around the
    per-model min/max plus an absolute floor. Skew beyond it prints a
    LOUD warning and lands in the returned dict — reported, not fatal:
    it means one side's clock or histogram is lying, which is exactly
    what an operator should go investigate.
    """
    import urllib.request

    with urllib.request.urlopen(f"http://{host}:{port}/varz",
                                timeout=5) as resp:
        varz = json.loads(resp.read().decode("utf-8"))
    out = {"checked": [], "skewed": []}
    for q in ("p50", "p99"):
        client = float(stats.get(f"{q}_ms") or 0.0)
        server_vals = {}
        for model in models:
            snap = varz.get('serve_request_latency_ms{model="%s"}' % model)
            if isinstance(snap, dict) and snap.get(q) is not None:
                server_vals[model] = float(snap[q])
        if not server_vals or client <= 0:
            continue
        lo = min(server_vals.values())
        hi = max(server_vals.values())
        entry = {"q": q, "client_ms": client,
                 "server_ms": {m: round(v, 3)
                               for m, v in server_vals.items()}}
        out["checked"].append(entry)
        if not (lo / tol_ratio - tol_abs_ms <= client
                <= hi * tol_ratio + tol_abs_ms):
            out["skewed"].append(entry)
            print(f"  WARNING: client {q} {client:.1f}ms outside the "
                  f"server band [{lo:.1f}, {hi:.1f}]ms x{tol_ratio:g} "
                  f"+/-{tol_abs_ms:g}ms — clock or histogram skew "
                  f"(server {entry['server_ms']})", flush=True)
    return out


# -- real-socket mode ---------------------------------------------------------

class HttpLoadClient:
    """LoadGen's front door over a REAL socket: POST /v1/<model> against
    a serve/transport.py endpoint, `submit(model, image) -> Future`.

    Retries ride `resilience.RetryPolicy` primitives — transient
    failures (connection loss, 429, 503) back off and go again, and a
    429/503 response's `Retry-After` header is HONORED: the client
    sleeps at least that long before the retry, whatever the policy's
    own schedule says. Terminal verdicts surface typed: ShedError when
    the budget runs out on sheds, DeadlineExceeded on 504 (never
    retried — the CLIENT's budget expired, retrying cannot help),
    ServeError otherwise. `counts` tracks retries and how often
    Retry-After set the pace, so a smoke can assert the header actually
    steered the client.
    """

    def __init__(self, host: str, port: int,
                 deadline_ms: Optional[float] = None,
                 retry=None, journal=None, registry=None,
                 max_inflight: int = 32, timeout_s: float = 30.0):
        from concurrent.futures import ThreadPoolExecutor

        from deep_vision_tpu.resilience import RetryPolicy
        from deep_vision_tpu.serve import ReplicaLost, ShedError

        self.host = host
        self.port = int(port)
        self.deadline_ms = deadline_ms
        self.timeout_s = float(timeout_s)
        # what is worth another try over the wire: sheds (the server
        # said "later", and told us when) and lost connections — NOT
        # DeadlineExceeded (the client's own budget expired) and NOT
        # application errors
        self.retry = retry or RetryPolicy(
            name="loadgen.http", max_attempts=4, base_delay_s=0.02,
            multiplier=2.0, max_delay_s=0.5, jitter=0.25,
            retry_on=(ShedError, ReplicaLost, ConnectionError,
                      TimeoutError),
            journal=journal, registry=registry)
        self._pool = ThreadPoolExecutor(max_workers=int(max_inflight),
                                        thread_name_prefix="loadgen-http")
        self._lock = threading.Lock()
        self.counts = {"offered": 0, "ok": 0, "shed": 0, "deadline": 0,
                       "error": 0, "retries": 0, "retry_after_honored": 0}

    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def submit(self, model: str, image):
        from concurrent.futures import Future

        fut: Future = Future()
        with self._lock:
            self.counts["offered"] += 1
        self._pool.submit(self._run_one, model, image, fut)
        return fut

    def _bump(self, key: str) -> None:
        with self._lock:
            self.counts[key] += 1

    def _run_one(self, model: str, image, fut) -> None:
        if not fut.set_running_or_notify_cancel():
            return
        attempt = 0
        while True:
            try:
                fut.set_result(self._post(model, image))
                self._bump("ok")
                return
            except Exception as e:
                attempt += 1
                retry_after_s = getattr(e, "retry_after_s", None)
                if not self.retry.should_retry(attempt, e):
                    self.retry.note(attempt, e, "gave_up")
                    self._bump(self._outcome_key(e))
                    fut.set_exception(e)
                    return
                # the server's Retry-After is a FLOOR under the
                # policy's own backoff: the server knows its queue
                delay = self.retry.delay(attempt)
                if retry_after_s is not None and retry_after_s > delay:
                    delay = retry_after_s
                    self._bump("retry_after_honored")
                self.retry.note(attempt, e, "retrying", delay_s=delay)
                self._bump("retries")
                if delay > 0:
                    time.sleep(delay)

    @staticmethod
    def _outcome_key(e: Exception) -> str:
        from deep_vision_tpu.serve import DeadlineExceeded, ShedError

        if isinstance(e, ShedError):
            return "shed"
        if isinstance(e, DeadlineExceeded):
            return "deadline"
        return "error"

    def _post(self, model: str, image) -> dict:
        import http.client

        from deep_vision_tpu.obs import propagate
        from deep_vision_tpu.serve import (
            DeadlineExceeded,
            ReplicaLost,
            ServeError,
            ShedError,
        )

        body = json.dumps(
            {"image": image.tolist() if hasattr(image, "tolist")
             else image}).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        if self.deadline_ms is not None:
            headers["X-DVT-Deadline-Ms"] = f"{self.deadline_ms:.3f}"
        ctx = propagate.current()
        if ctx is not None:
            headers["traceparent"] = ctx.to_traceparent()
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)
        try:
            try:
                conn.request("POST", f"/v1/{model}", body=body,
                             headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
            except (OSError, http.client.HTTPException) as e:
                raise ReplicaLost(
                    f"connection to {self.host}:{self.port} lost "
                    f"({type(e).__name__}: {e})")
            try:
                payload = json.loads(raw.decode("utf-8"))
            except ValueError:
                raise ReplicaLost(
                    f"torn response from {self.host}:{self.port} "
                    f"({len(raw)} bytes, not JSON)")
            if resp.status == 200:
                return payload.get("outputs", payload)
            retry_after = resp.getheader("Retry-After")
            if resp.status in (429, 503):
                reason = payload.get("reason")
                # a reason names a POLICY shed; a reasonless 503 is a
                # fleet failure (ReplicaLost behind the front door) —
                # typed differently so client ledgers never conflate
                # "turned away" with "died under me"
                e = (ShedError(model, reason) if reason
                     else ReplicaLost(payload.get("detail")
                                      or "fleet error behind the edge"))
                if retry_after is not None:
                    try:
                        e.retry_after_s = float(retry_after)
                    except ValueError:
                        pass
                raise e
            if resp.status == 504:
                raise DeadlineExceeded(
                    f"deadline shed at {payload.get('stage', '?')}")
            raise ServeError(
                f"{self.host}:{self.port} answered {resp.status}: "
                f"{payload.get('detail', payload)}")
        finally:
            conn.close()


def fleet_builder(journal=None, registry=None, excache=None):
    """Module-level engine builder (spawn pickles it BY REFERENCE, so it
    must live at module scope): the two-toy-model engine every
    ProcReplicaPool child — and the parent's template — builds."""
    from deep_vision_tpu.serve import Engine

    eng = Engine(journal=journal, registry=registry, excache=excache)
    eng.register("toy", toy_fn, toy_variables(), input_shape=IMG,
                 buckets=BUCKETS)
    eng.register("aux", aux_fn, aux_variables(), input_shape=IMG,
                 buckets=BUCKETS)
    return eng


# -- the fleet-smoke scenario -------------------------------------------------

def toy_fn(variables, images):
    flat = images.reshape((images.shape[0], -1))
    return {"scores": flat @ variables["w"],
            "mean": images.mean(axis=(1, 2, 3))}


def aux_fn(variables, images):
    flat = images.reshape((images.shape[0], -1))
    return {"logits": flat @ variables["w"] + variables["b"]}


def toy_variables(scale: float = 1.0, seed: int = 0):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(16, 3).astype(np.float32) * scale)}


def aux_variables(seed: int = 1):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(16, 5).astype(np.float32)),
            "b": jnp.asarray(rng.randn(5).astype(np.float32))}


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/fleet_smoke")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--rps", type=float, default=150.0)
    p.add_argument("--requests", type=int, default=150,
                   help="requests in the sustained-load episode")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.checkpoint import CheckpointManager
    from deep_vision_tpu.obs import (
        FlightRecorder,
        RunJournal,
        Tracer,
        locksmith,
        set_flight,
        set_tracer,
    )
    from deep_vision_tpu.obs.registry import Registry
    from deep_vision_tpu.obs.stepclock import recompile_count
    from deep_vision_tpu.resilience import faults
    from deep_vision_tpu.serve import (
        AdmissionController,
        Engine,
        ReplicaPool,
        ShedError,
        SwapController,
    )

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work)
    f = Failures()
    j_path = os.path.join(work, "journal.jsonl")
    t_path = os.path.join(work, "trace.json")
    flight_dir = os.path.join(work, "flight")

    journal = RunJournal(j_path, kind="serve")
    journal.manifest(config={"name": "fleet_smoke", "task": "serving"})
    tracer = Tracer(t_path, run_id=journal.run_id)
    set_tracer(tracer)
    flight = FlightRecorder(flight_dir, run_id=journal.run_id)
    flight.attach(journal)
    set_flight(flight)
    # the lock sanitizer rides the WHOLE fleet lifecycle: warmup, load,
    # replica death + respawn, both swaps, the shed episode, and drain —
    # phase 6 asserts zero lock-order violations across all of it
    locksmith.arm(journal=journal)
    registry = Registry()
    # live telemetry plane (obs/telemetry.py): the fleet's /metrics +
    # /healthz + /statusz, scraped under load below and cross-checked
    # against the client-observed percentiles after the death episode
    from deep_vision_tpu.obs.telemetry import TelemetryServer, \
        validate_prometheus

    tele = TelemetryServer(port=0, role="serve", registry=registry,
                           journal=journal, flight=flight,
                           discovery_dir=work)
    tele.start()

    # persistent executable cache (core/excache.py): replica 0 compiles
    # and stores, every later warmup — including the FRESH-ENGINE respawn
    # in phase 2 — loads instead of compiling
    from deep_vision_tpu.core.excache import ExecutableCache

    excache = ExecutableCache(os.path.join(work, "excache"),
                              journal=journal, registry=registry)

    def build_engine(rid: str) -> Engine:
        eng = Engine(journal=journal, registry=registry, excache=excache)
        eng.register("toy", toy_fn, toy_variables(), input_shape=IMG,
                     buckets=BUCKETS)
        eng.register("aux", aux_fn, aux_variables(), input_shape=IMG,
                     buckets=BUCKETS)
        return eng

    # -- phase 1: fleet warmup ------------------------------------------
    print(f"phase 1: {args.replicas} replicas warm their engines (AOT)")
    # respawn_fresh: a dead replica rebuilds its ENGINE too — the
    # fresh-device model, where nothing warm survives to borrow and the
    # executable cache is the only thing between recovery and the
    # compiler (phase 2 asserts the respawned warmup compiled NOTHING)
    pool = ReplicaPool(build_engine, replicas=args.replicas,
                       journal=journal, registry=registry,
                       max_wait_ms=4.0, slo_ms=SLO_MS,
                       respawn_fresh=True, telemetry=tele)
    pool.start()
    pairs = args.replicas * 2 * len(BUCKETS)
    f.check(pool.warmup_stats["pairs"] == pairs,
            f"warmed {pool.warmup_stats['pairs']}/{pairs} "
            "(replica, model, bucket) pairs")
    f.check(pool.warmup_stats["backend_compiles"] >= 2 * len(BUCKETS),
            f"warmup compiled every unique computation "
            f"({pool.warmup_stats['backend_compiles']} backend compiles; "
            "the cache may dedupe across replicas)")
    f.check(pool.warmup_stats["backend_compiles"] == 2 * len(BUCKETS),
            "executable cache deduped warmup across replicas: exactly one "
            f"compile per unique (model, bucket) pair "
            f"({pool.warmup_stats['backend_compiles']} compiles for "
            f"{pairs} pairs)")
    # prep for phases 3/4 BEFORE the compile baseline: eager host-side
    # reference math and orbax saves compile their own tiny executables,
    # and the zero-compile contract below is about the SERVING path —
    # death, respawn, canary, promote, rollback, shed, drain
    ckpt_dir = os.path.join(work, "ckpt")
    mgr = CheckpointManager(ckpt_dir, journal=journal)
    new_toy = {"toy": toy_variables(scale=2.0, seed=7)}
    mgr.save_tree(1, new_toy)
    # finite on the zeros probe, overflow on real [0,1) traffic: the
    # poison a synthetic warm probe CANNOT catch — the canary must
    poisoned = {"toy": {"w": jnp.full((16, 3), 1e38, jnp.float32)}}
    mgr.save_tree(2, poisoned)
    mgr.wait()
    probe = np.random.RandomState(9).rand(*IMG).astype(np.float32)
    ref = jax.device_get(toy_fn(new_toy["toy"], jnp.asarray(probe[None])))
    c0 = recompile_count()  # NOTHING below may move this

    # -- phase 2: sustained load through a replica death ----------------
    print("phase 2: replica death under sustained load is request-scoped")
    faults.install_spec("serve.replica:io_error@7", seed=13,
                        journal=journal, export_env=False)
    gen = LoadGen(pool.submit, ["toy", "aux"], rps=args.rps,
                  n_requests=args.requests, seed=42)
    stats = gen.run()
    faults.install(None)
    print(f"  load: {stats}")
    f.check(stats["ok"] + stats["errors"] + stats["shed"]
            + stats["refused"] == stats["offered"],
            f"every offered request accounted "
            f"(ok={stats['ok']} err={stats['errors']} shed={stats['shed']})")
    f.check(1 <= stats["errors"] <= 3 * max(BUCKETS),
            f"only the dead replica's in-flight window failed "
            f"({stats['errors']} errors; bound = a few batches on one "
            "replica, never the stream)")
    f.check(stats["p99_ms"] <= SLO_MS,
            f"p99 of admitted traffic held the SLO through the death "
            f"({stats['p99_ms']:.1f}ms <= {SLO_MS:g}ms)")
    deadline = time.time() + 15
    while time.time() < deadline and not all(
            s == "serving" for s in pool.replica_states().values()):
        time.sleep(0.05)
    f.check(all(s == "serving" for s in pool.replica_states().values()),
            f"pool back to full strength ({pool.replica_states()})")
    f.check(pool.submit("toy", np.random.RandomState(5).rand(*IMG)
                        .astype(np.float32)).result(timeout=60) is not None,
            "pool answers after the respawn")
    fresh_notes = [e for e in read_jsonl(j_path)
                   if e.get("event") == "note"
                   and e.get("note") == "replica_respawn_fresh"]
    f.check(len(fresh_notes) == 1
            and fresh_notes[0].get("backend_compiles") == 0
            and fresh_notes[0].get("cache_hits")
            == fresh_notes[0].get("pairs"),
            "fresh-engine respawn warmed ENTIRELY from the executable "
            "cache (zero backend compiles, "
            f"{fresh_notes[0].get('cache_hits') if fresh_notes else '?'}"
            f"/{fresh_notes[0].get('pairs') if fresh_notes else '?'} "
            "pairs cache-hit)")
    # the telemetry plane under a fleet that just lost + respawned a
    # replica: /healthz answers 200 (the respawned _ReplicaServer
    # re-registered its health source by name), /metrics parses, and
    # the client-observed percentiles agree with the server's /varz
    # SLO histograms within bucket-resolution tolerance
    import urllib.request as _url

    with _url.urlopen(f"http://{tele.address}/healthz", timeout=5) as r:
        hz = json.loads(r.read().decode("utf-8"))
    f.check(r.status == 200 and hz.get("ok") is True,
            "/healthz answers 200 with the fleet at full strength "
            "(respawned replica re-registered its health source)")
    with _url.urlopen(f"http://{tele.address}/metrics", timeout=5) as r:
        metrics_text = r.read().decode("utf-8")
    prom_problems = validate_prometheus(metrics_text)
    f.check(not prom_problems,
            "live /metrics parses as Prometheus text exposition"
            + ("" if not prom_problems else f" ({prom_problems[0]})"))
    xc = crosscheck_varz(stats, tele.host, tele.port, ["toy", "aux"])
    f.check(len(xc["checked"]) == 2,
            "client p50+p99 cross-checked against /varz "
            "serve_request_latency_ms histograms "
            f"({len(xc['skewed'])} skew warning(s))")

    # -- phase 3: canary swap, auto-promote -----------------------------
    print("phase 3: canary weight swap promotes under live traffic")
    stop = threading.Event()

    def traffic(seed: int):
        rng = np.random.RandomState(seed)
        while not stop.is_set():
            try:
                pool.submit("toy", rng.rand(*IMG).astype(np.float32))
            except Exception:
                pass
            time.sleep(0.004)

    t = threading.Thread(target=traffic, args=(3,), daemon=True)
    t.start()
    swapper = SwapController(pool, journal=journal, canary_pct=50,
                             min_canary_requests=6, slo_ms=SLO_MS,
                             canary_timeout_s=60.0)
    verdict = swapper.swap(mgr, step=1, models=("toy",))
    f.check(verdict["outcome"] == "promoted",
            "good weights promoted ("
            + " -> ".join(f"{t_['phase']}:{t_['outcome']}"
                          for t_ in verdict["timeline"]) + ")")
    row = pool.submit("toy", probe).result(timeout=60)
    f.check(bool(np.allclose(row["scores"], ref["scores"][0], rtol=1e-5)),
            "responses serve the PROMOTED weights")

    # -- phase 4: poisoned canary, auto-rollback ------------------------
    print("phase 4: poisoned weights roll back; the base stream never "
          "sees them")
    verdict = swapper.swap(mgr, step=2, models=("toy",))
    f.check(verdict["outcome"] == "rolled_back",
            f"poisoned weights rolled back ({verdict.get('reason')}: "
            + " -> ".join(f"{t_['phase']}:{t_['outcome']}"
                          for t_ in verdict["timeline"]) + ")")
    stop.set()
    t.join(timeout=10)
    row = pool.submit("toy", probe).result(timeout=60)
    f.check(bool(np.allclose(row["scores"], ref["scores"][0], rtol=1e-5)),
            "base replicas still serve the phase-3 weights after rollback")

    # -- phase 5: overload sheds by policy ------------------------------
    print("phase 5: overload blast sheds by policy, p99 of admitted held")
    pool.admission = AdmissionController(max_queue_depth=16,
                                         rate_per_s=0.0, burst=30)
    blast = LoadGen(pool.submit, ["toy"], rps=None, n_requests=120,
                    seed=77)
    stats = blast.run()
    print(f"  blast: {stats}")
    f.check(stats["shed"] >= 90 and stats["ok"] + stats["errors"] <= 30,
            f"token budget admitted <= 30 of 120, shed the rest "
            f"(shed={stats['shed']})")
    f.check(stats["ok"] + stats["errors"] + stats["shed"]
            + stats["refused"] == stats["offered"],
            "overload accounting balances (offered == ok+err+shed)")
    f.check(stats["p99_ms"] <= SLO_MS,
            f"p99 of ADMITTED traffic held through the overload "
            f"({stats['p99_ms']:.1f}ms)")
    slo_rep = pool.slo.report().get("toy", {})
    f.check(slo_rep.get("offered", 0) > slo_rep.get("admitted", 0),
            f"SLO report shows offered {slo_rep.get('offered')} > admitted "
            f"{slo_rep.get('admitted')} — shed traffic cannot flatter p99")

    # -- phase 6: clean drain, artifacts validate -----------------------
    print("phase 6: clean drain; strict journals; zero violations; "
          "no stray bundles; zero compiles since warmup")
    summary = pool.drain("close")
    f.check(summary["outcome"] == "flushed" and summary["pending"] == 0,
            f"pool drained everything ({summary})")
    f.check(summary["accepted"] == summary["completed"] + summary["errors"]
            + summary["cancelled"],
            "fleet ledger balances across death, swaps, and shed "
            f"(accepted={summary['accepted']})")
    f.check(summary["offered"] == summary["accepted"] + summary["shed"]
            + summary["refused"],
            f"offered == accepted + shed + refused "
            f"({summary['offered']} == {summary['accepted']} + "
            f"{summary['shed']} + {summary['refused']})")
    f.check(recompile_count() == c0,
            "ZERO additional compilations since warmup — through the "
            "death, the respawn, and BOTH swaps")
    lock_report = locksmith.report()
    f.check(not lock_report["violations"],
            "locksmith: zero lock-order violations across the fleet "
            "lifecycle"
            + ("" if not lock_report["violations"]
               else f" ({lock_report['violations'][0]})"))
    locksmith.disarm()
    # a drained fleet must read UNHEALTHY: /healthz flips to 503, and the
    # discovery file vanishes with the server (tools/obs_poll.py's
    # liveness contract)
    try:
        with _url.urlopen(f"http://{tele.address}/healthz", timeout=5):
            drained_status = 200
    except _url.HTTPError as e:
        drained_status = e.code
    f.check(drained_status == 503,
            f"/healthz flips to 503 once the fleet drains "
            f"(got {drained_status})")
    f.check(any(p.startswith("telemetry-") for p in os.listdir(work)),
            "discovery file present while the telemetry server lives")
    tele.close()
    f.check(not any(p.startswith("telemetry-") for p in os.listdir(work)),
            "discovery file removed on telemetry close")
    mgr.close()
    tracer.close()
    set_tracer(None)
    flight.close()
    set_flight(None)
    journal.close()
    f.check(not os.listdir(flight_dir) if os.path.isdir(flight_dir)
            else True, "clean run left no flight bundle")

    ev = read_jsonl(j_path)
    losts = [e for e in ev if e.get("event") == "replica_lost"]
    recs = [e for e in ev if e.get("event") == "replica_recovered"]
    f.check(len(losts) == 1 and len(recs) == 1
            and losts[0].get("replica") == recs[0].get("replica"),
            f"exactly one replica_lost + replica_recovered pair "
            f"({[e.get('replica') for e in losts]})")
    shed_events = [e for e in ev if e.get("event") == "serve_shed"]
    f.check(len(shed_events) == summary["shed"],
            f"serve_shed events ({len(shed_events)}) == shed counter "
            f"({summary['shed']})")
    swaps = [e for e in ev if e.get("event") == "serve_swap"]
    phases = [(e.get("phase"), e.get("outcome")) for e in swaps]
    f.check(("promote", "ok") in phases and ("rollback", "ok") in phases
            and ("canary", "failed") in phases,
            f"swap timeline journaled promote AND forced rollback "
            f"({phases})")

    cmd = [sys.executable, os.path.join(ROOT, "tools", "check_journal.py"),
           j_path, "--strict", "--trace", t_path]
    f.check(subprocess.run(cmd, cwd=ROOT,
                           env=dict(os.environ, PYTHONPATH=ROOT)
                           ).returncode == 0,
            "check_journal --strict accepts the fleet journal + trace")
    rep = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_report.py"),
         j_path],
        cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
        stdout=subprocess.PIPE, text=True)
    f.check(rep.returncode == 0 and "replica r0" in rep.stdout
            and "swap #" in rep.stdout and "shed toy" in rep.stdout
            and "pool latency" in rep.stdout,
            "obs_report renders the fleet section (replicas, swaps, "
            "shed, pool tail)")

    if f.errors:
        print(f"\nfleet-smoke: {len(f.errors)} contract(s) BROKEN "
              f"(artifacts in {work})")
        return 1
    print(f"\nfleet-smoke: all fleet contracts held (artifacts in {work})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
