"""Dataset -> sharded record conversion with process-parallel shard writers.

Parity targets (field names byte-compatible, so shards interop both ways):
- VOC: XML parse + normalized-bbox Example (Datasets/VOC2007/tfrecords.py:
  38-95,124-155), train/val/test splits from ImageSets (:163-175).
- COCO: JSON -> per-image grouped annotations (Datasets/MSCOCO/tfrecords.py:
  135+), 64/8 shard convention (:13-14).
- MPII: joints x/y normalized + visibility (Datasets/MPII/
  tfrecords_mpii.py:54-84).
- ImageNet: synset label from folder/filename + label index Example
  (Datasets/ILSVRC2012/build_imagenet_tfrecord.py:184+, 1024/128 shards).
- CycleGAN: image-only Examples, one file per split
  (CycleGAN/tensorflow/tfrecords.py).

The reference fans out with Ray (`@ray.remote build_single_tfrecord`,
VOC2007/tfrecords.py:98-107) or threads (ImageNet). Here:
`multiprocessing.Pool` over shard chunks — same parallelism, stdlib only.
Spawn context, not fork: converters run in processes that have usually
imported jax already (the data pipeline's _proc_samples makes the same
call for the same reason), and forking a multithreaded process is a
deadlock lottery that CPython now warns about at every fork().
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import re
import shutil
import xml.etree.ElementTree as ET
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from deep_vision_tpu.data.example_codec import encode_example
from deep_vision_tpu.data.records import RecordWriter

VOC_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


def chunkify(items: Sequence, n_chunks: int) -> List[List]:
    """Split into n roughly-equal chunks (chunkify, VOC2007/tfrecords.py:20-28)."""
    if not items:
        return []
    n_chunks = max(1, min(n_chunks, len(items)))
    size = -(-len(items) // n_chunks)
    return [list(items[i:i + size]) for i in range(0, len(items), size)]


def _write_shard(args) -> int:
    chunk, path, make_example = args
    n = 0
    with RecordWriter(path) as w:
        for anno in chunk:
            ex = make_example(anno)
            if ex is not None:
                w.write(encode_example(ex))
                n += 1
    return n


def build_shards(
    annotations: Sequence,
    make_example: Callable[[dict], Optional[dict]],
    out_dir: str,
    prefix: str,
    num_shards: int,
    num_workers: Optional[int] = None,
) -> List[str]:
    """Fan annotation chunks out to worker processes, one shard file each.

    Shard naming mirrors the reference: `{prefix}_{i:04d}_of_{n:04d}.tfrecord`.
    """
    os.makedirs(out_dir, exist_ok=True)
    chunks = chunkify(annotations, num_shards)
    jobs = [
        (
            chunk,
            os.path.join(
                out_dir, f"{prefix}_{i:04d}_of_{len(chunks):04d}.tfrecord"
            ),
            make_example,
        )
        for i, chunk in enumerate(chunks)
    ]
    if num_workers is None:
        num_workers = min(len(jobs), os.cpu_count() or 1)
    if num_workers <= 1 or len(jobs) == 1:
        counts = [_write_shard(j) for j in jobs]
    else:
        with mp.get_context("spawn").Pool(num_workers) as pool:
            counts = pool.map(_write_shard, jobs)
    print(f"wrote {sum(counts)} examples to {len(jobs)} shards in {out_dir}")
    return [j[1] for j in jobs]


# -- VOC ---------------------------------------------------------------------

def voc_annotations(voc_root: str, split: str = "train") -> List[dict]:
    """Parse VOCdevkit annotations for an ImageSets/Main split
    (VOC2007/tfrecords.py:124-175)."""
    split_file = os.path.join(voc_root, "ImageSets", "Main", f"{split}.txt")
    with open(split_file) as f:
        ids = [line.strip().split()[0] for line in f if line.strip()]
    annos = []
    for image_id in ids:
        xml_path = os.path.join(voc_root, "Annotations", f"{image_id}.xml")
        root = ET.parse(xml_path).getroot()
        size = root.find("size")
        anno = {
            "filename": f"{image_id}.jpg",
            "filepath": os.path.join(voc_root, "JPEGImages", f"{image_id}.jpg"),
            "width": int(size.find("width").text),
            "height": int(size.find("height").text),
            "depth": int(size.find("depth").text or 3),
            "bboxes": [],
        }
        for obj in root.iter("object"):
            name = obj.find("name").text
            box = obj.find("bndbox")
            anno["bboxes"].append(
                {
                    "class_id": VOC_CLASSES.index(name),
                    "class_text": name,
                    "xmin": float(box.find("xmin").text),
                    "ymin": float(box.find("ymin").text),
                    "xmax": float(box.find("xmax").text),
                    "ymax": float(box.find("ymax").text),
                }
            )
        annos.append(anno)
    return annos


def detection_example(anno: dict) -> Optional[dict]:
    """Normalized-bbox Example, exact field names of VOC2007/tfrecords.py:69-93."""
    with open(anno["filepath"], "rb") as f:
        content = f.read()
    w, h = anno["width"], anno["height"]
    xmins, ymins, xmaxs, ymaxs, ids, texts = [], [], [], [], [], []
    for b in anno["bboxes"]:
        xmin, ymin = b["xmin"] / w, b["ymin"] / h
        xmax, ymax = b["xmax"] / w, b["ymax"] / h
        if not all(0.0 <= v <= 1.0 for v in (xmin, ymin, xmax, ymax)):
            # reference hard-asserts (tfrecords.py:61-64); tolerate + clamp
            xmin, ymin = max(0.0, min(1.0, xmin)), max(0.0, min(1.0, ymin))
            xmax, ymax = max(0.0, min(1.0, xmax)), max(0.0, min(1.0, ymax))
        xmins.append(xmin)
        ymins.append(ymin)
        xmaxs.append(xmax)
        ymaxs.append(ymax)
        ids.append(int(b["class_id"]))
        texts.append(b["class_text"].encode())
    return {
        "image/height": [anno["height"]],
        "image/width": [anno["width"]],
        "image/depth": [anno.get("depth", 3)],
        "image/object/bbox/xmin": xmins,
        "image/object/bbox/ymin": ymins,
        "image/object/bbox/xmax": xmaxs,
        "image/object/bbox/ymax": ymaxs,
        "image/object/class/label": ids,
        "image/object/class/text": texts,
        "image/encoded": [content],
        "image/filename": [anno["filename"].encode()],
    }


# -- COCO --------------------------------------------------------------------

def coco_annotations(instances_json: str, images_dir: str) -> List[dict]:
    """COCO instances JSON -> per-image grouped annos
    (Datasets/MSCOCO/tfrecords.py:135+). Category ids are remapped to a dense
    0..C-1 range sorted by original id (COCO ids have holes)."""
    with open(instances_json) as f:
        coco = json.load(f)
    cat_ids = sorted(c["id"] for c in coco["categories"])
    cat_index = {cid: i for i, cid in enumerate(cat_ids)}
    cat_name = {c["id"]: c["name"] for c in coco["categories"]}
    by_image: Dict[int, List[dict]] = {}
    for a in coco.get("annotations", []):
        if a.get("iscrowd"):
            continue
        by_image.setdefault(a["image_id"], []).append(a)
    annos = []
    for img in coco["images"]:
        boxes = []
        for a in by_image.get(img["id"], ()):
            x, y, bw, bh = a["bbox"]  # COCO xywh absolute
            boxes.append(
                {
                    "class_id": cat_index[a["category_id"]],
                    "class_text": cat_name[a["category_id"]],
                    "xmin": x,
                    "ymin": y,
                    "xmax": x + bw,
                    "ymax": y + bh,
                }
            )
        annos.append(
            {
                "filename": img["file_name"],
                "filepath": os.path.join(images_dir, img["file_name"]),
                "width": img["width"],
                "height": img["height"],
                "depth": 3,
                "bboxes": boxes,
            }
        )
    return annos


# -- MPII --------------------------------------------------------------------

def mpii_annotations(json_path: str, images_dir: str) -> List[dict]:
    """Preprocessed MPII train/validation.json (the input format the
    reference consumes, Datasets/MPII/tfrecords_mpii.py)."""
    with open(json_path) as f:
        people = json.load(f)
    annos = []
    for p in people:
        annos.append(
            {
                "filename": p["image"],
                "filepath": os.path.join(images_dir, p["image"]),
                "joints": p["joints"],  # [[x, y] * 16] absolute
                "joints_vis": p["joints_vis"],
                # MPII person center/scale (scale x 200 px = body height),
                # consumed by the CropRoi transform; optional in older
                # preprocessed jsons
                "center": p.get("center"),
                "scale": p.get("scale"),
            }
        )
    return annos


def mpii_example(anno: dict) -> Optional[dict]:
    """Keypoint Example (tfrecords_mpii.py:65-84): normalized x/y + visibility."""
    from deep_vision_tpu.data.datasets import decode_image

    with open(anno["filepath"], "rb") as f:
        content = f.read()
    img = decode_image(content)
    h, w = img.shape[:2]
    xs = [float(j[0]) / w for j in anno["joints"]]
    ys = [float(j[1]) / h for j in anno["joints"]]
    vis = [int(v) for v in anno["joints_vis"]]
    ex = {
        "image/height": [h],
        "image/width": [w],
        "image/person/keypoints/x": xs,
        "image/person/keypoints/y": ys,
        "image/person/keypoints/visibility": vis,
        "image/encoded": [content],
        "image/filename": [anno["filename"].encode()],
    }
    # person scale (image/object/scale at Datasets/MPII/tfrecords_mpii.py):
    # drives the CropRoi body-height pad (scale x 200 px). center is written
    # for record-schema parity with the reference only — its crop_roi reads
    # but never uses it (preprocess.py:52-53), and neither does CropRoi.
    if anno.get("scale") is not None:
        ex["image/person/scale"] = [float(anno["scale"])]
    if anno.get("center") is not None:
        cx, cy = anno["center"]
        ex["image/person/center/x"] = [float(cx) / w]
        ex["image/person/center/y"] = [float(cy) / h]
    return ex


# -- ImageNet ----------------------------------------------------------------

def imagenet_bbox_csv(xml_dir: str, out_csv: str,
                      synsets_path: Optional[str] = None) -> dict:
    """ImageNet bbox XMLs -> one CSV line per box: `file,xmin,ymin,xmax,ymax`.

    The process_bounding_boxes.py analog
    (Datasets/ILSVRC2012/process_bounding_boxes.py:1-264): walks
    `<xml_dir>/nXXXXXXXX/nXXXXXXXX_YYYY.xml` (or a flat dir of XMLs), reads
    each PASCAL-style annotation, normalizes pixel boxes by the annotator's
    displayed <size> (which differs from the downloadable image's size — the
    reason the CSV stores RELATIVE coords), clamps to [0, 1], swaps
    inverted min/max (both fixups human annotations need), and optionally
    filters to the challenge synsets. Returns counters matching the
    reference's stderr summary.
    """
    import csv
    import glob as _glob
    import xml.etree.ElementTree as ET

    keep = None
    if synsets_path:
        with open(synsets_path) as f:
            keep = {line.strip().split()[0] for line in f if line.strip()}
    xmls = sorted(
        _glob.glob(os.path.join(xml_dir, "*", "*.xml"))
        + _glob.glob(os.path.join(xml_dir, "*.xml"))
    )
    n_files = n_boxes = n_skipped_files = n_skipped_boxes = 0
    n_malformed = 0
    os.makedirs(os.path.dirname(os.path.abspath(out_csv)), exist_ok=True)
    with open(out_csv, "w", newline="") as out:
        w = csv.writer(out)
        for path in xmls:
            n_files += 1
            synset = os.path.basename(path).split("_")[0]
            if keep is not None and synset not in keep:
                n_skipped_files += 1
                continue
            # a handful of the ~500k human annotations are malformed
            # (missing <size>, zero dims, non-numeric fields): count and
            # continue, as the reference tool does — one bad XML must not
            # kill the whole build
            try:
                root = ET.parse(path).getroot()
                size = root.find("size")
                width = float(size.findtext("width"))
                height = float(size.findtext("height"))
                if width <= 0 or height <= 0:
                    raise ValueError(f"degenerate size {width}x{height}")
                # some annotation XMLs lack <filename>: fall back to the
                # XML's own basename (which mirrors the image name)
                fname = (root.findtext("filename")
                         or os.path.splitext(os.path.basename(path))[0])
                if not fname.lower().endswith((".jpeg", ".jpg")):
                    fname += ".JPEG"
                rows = []
                for obj in root.iter("object"):
                    name = obj.findtext("name")
                    if keep is not None and name not in keep:
                        n_skipped_boxes += 1
                        continue
                    bb = obj.find("bndbox")
                    x1 = min(max(float(bb.findtext("xmin")) / width, 0.0), 1.0)
                    y1 = min(max(float(bb.findtext("ymin")) / height, 0.0), 1.0)
                    x2 = min(max(float(bb.findtext("xmax")) / width, 0.0), 1.0)
                    y2 = min(max(float(bb.findtext("ymax")) / height, 0.0), 1.0)
                    if x1 > x2:  # inverted human annotation
                        x1, x2 = x2, x1
                    if y1 > y2:
                        y1, y2 = y2, y1
                    rows.append([fname, f"{x1:.4f}", f"{y1:.4f}",
                                 f"{x2:.4f}", f"{y2:.4f}"])
            except Exception as e:
                n_malformed += 1
                print(f"imagenet_bbox_csv: skipping malformed {path}: "
                      f"{type(e).__name__}: {e}")
                continue
            for row in rows:
                w.writerow(row)
                n_boxes += 1
    return {
        "files": n_files,
        "boxes": n_boxes,
        "skipped_files": n_skipped_files,
        "skipped_boxes": n_skipped_boxes,
        "malformed_files": n_malformed,
    }


def load_bbox_csv(csv_path: str) -> dict:
    """CSV from `imagenet_bbox_csv` -> {filename stem: [[x1,y1,x2,y2], ...]}.

    Keyed on the extensionless stem: the CSV stamps '.JPEG' (the reference's
    convention) while datasets on disk may use .jpg/.png — an extension
    mismatch must not silently drop every box."""
    import csv
    from collections import defaultdict

    boxes = defaultdict(list)
    with open(csv_path, newline="") as f:
        for row in csv.reader(f):
            if len(row) != 5:
                continue
            stem = os.path.splitext(row[0])[0]
            boxes[stem].append([float(v) for v in row[1:]])
    return dict(boxes)


def _place(src: str, dst: str, move: bool) -> None:
    """Hardlink (same filesystem, zero extra disk) -> move -> copy."""
    if move:
        shutil.move(src, dst)
        return
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


def prepare_imagenet(out_dir: str,
                     train_tars: Optional[str] = None,
                     train_dir: Optional[str] = None,
                     val_dir: Optional[str] = None,
                     val_synsets: Optional[str] = None,
                     move: bool = False) -> Dict[str, int]:
    """Raw ILSVRC2012 download -> the flattened layout the converter eats.

    The analog of the reference's three shell scripts
    (Datasets/ILSVRC2012/untar-script.sh, flatten-script.sh,
    flatten-val-script.sh), minus their double disk copy:

    - `train_tars`: directory of per-synset `nXXXXXXXX.tar` files (the
      inner tars of ILSVRC2012_img_train.tar). Members are already named
      `nXXXXXXXX_*.JPEG`, so they extract STRAIGHT into
      `<out_dir>/train_flatten/` — untar + flatten in one pass.
    - `train_dir`: alternatively, an already-untarred tree with per-synset
      subdirectories; files are hardlinked (or moved with `move=True`)
      into `train_flatten/` (flatten-script.sh).
    - `val_dir` + `val_synsets`: the flat `ILSVRC2012_val_*.JPEG` folder
      plus imagenet_2012_validation_synset_labels.txt (line i = synset of
      val image i, sorted order). Files land in `<out_dir>/val_flatten/`
      renamed `<synset>_<origname>` so the converter's synset-prefix
      convention (imagenet_annotations) applies — what
      flatten-val-script.sh achieves by prefixing directory names.

    Returns counts per split. Idempotent: existing destinations are kept.
    """
    import tarfile

    stats = {"train": 0, "val": 0}
    if train_tars or train_dir:
        tdst = os.path.join(out_dir, "train_flatten")
        os.makedirs(tdst, exist_ok=True)
    if train_tars:
        tars = sorted(t for t in os.listdir(train_tars)
                      if t.endswith(".tar"))
        for t in tars:
            with tarfile.open(os.path.join(train_tars, t)) as tf:
                for m in tf.getmembers():
                    if not m.isfile():
                        continue
                    name = os.path.basename(m.name)
                    dst = os.path.join(tdst, name)
                    if not os.path.exists(dst):
                        with tf.extractfile(m) as src, open(dst, "wb") as f:
                            shutil.copyfileobj(src, f)
                    stats["train"] += 1
    if train_dir:
        for synset in sorted(os.listdir(train_dir)):
            sdir = os.path.join(train_dir, synset)
            if not os.path.isdir(sdir):
                continue
            for name in sorted(os.listdir(sdir)):
                dst = os.path.join(tdst, name)
                if not os.path.exists(dst):
                    _place(os.path.join(sdir, name), dst, move)
                stats["train"] += 1
    if val_dir:
        if not val_synsets:
            raise ValueError(
                "val_dir requires val_synsets "
                "(imagenet_2012_validation_synset_labels.txt)"
            )
        with open(val_synsets) as f:
            labels = [line.strip() for line in f if line.strip()]
        vdst = os.path.join(out_dir, "val_flatten")
        os.makedirs(vdst, exist_ok=True)
        names = [n for n in os.listdir(val_dir)
                 if n.lower().endswith((".jpeg", ".jpg", ".png"))]

        # The label file is ordered by validation INDEX (line i = image
        # ILSVRC2012_val_{i+1:08d}), so pair by the parsed index, never by
        # lexicographic order: a renamed file that still matches the
        # extension filter would silently shift every label after it while
        # keeping the counts equal.
        def _val_index(name: str) -> int:
            m = re.match(r"ILSVRC2012_val_(\d{8})\.", name)
            if not m:
                raise ValueError(
                    f"unrecognized validation image name {name!r} in "
                    f"{val_dir}: expected ILSVRC2012_val_NNNNNNNN.<ext>; "
                    "refusing to pair images with synset labels"
                )
            return int(m.group(1))

        names.sort(key=_val_index)
        if len(names) != len(labels):
            raise ValueError(
                f"{len(names)} val images but {len(labels)} synset labels"
            )
        for i, name in enumerate(names):
            if _val_index(name) != i + 1:
                raise ValueError(
                    f"validation set has a gap: expected index {i + 1}, "
                    f"found {name!r} — labels would misalign from here on"
                )
        for name, synset in zip(names, labels):
            dst = os.path.join(vdst, f"{synset}_{name}")
            if not os.path.exists(dst):
                _place(os.path.join(val_dir, name), dst, move)
            stats["val"] += 1
    return stats


def imagenet_annotations(root: str, synsets_path: str,
                         bbox_csv: Optional[str] = None) -> List[dict]:
    """Flattened `nXXXXXXXX_*.JPEG` folder -> annotations with 1-based labels
    (0 reserved for background, build_imagenet_tfrecord.py convention).
    With `bbox_csv` (from imagenet_bbox_csv), boxes attach per filename and
    land in the Example's image/object/bbox/* fields."""
    with open(synsets_path) as f:
        synsets = [line.strip().split()[0] for line in f if line.strip()]
    label_of = {s: i + 1 for i, s in enumerate(synsets)}
    boxes_of = load_bbox_csv(bbox_csv) if bbox_csv else {}
    annos = []
    for name in sorted(os.listdir(root)):
        if not name.lower().endswith((".jpeg", ".jpg", ".png")):
            continue
        synset = name.split("_")[0]
        annos.append(
            {
                "filename": name,
                "filepath": os.path.join(root, name),
                "synset": synset,
                "label": label_of[synset],
                # stem-keyed: .jpg/.png datasets still match the CSV's .JPEG
                "bboxes": boxes_of.get(os.path.splitext(name)[0], []),
            }
        )
    return annos


def imagenet_example(anno: dict) -> Optional[dict]:
    """Colorspace/synset/label Example (build_imagenet_tfrecord.py:184+);
    non-JPEG/non-RGB inputs (PNG, CMYK jpegs) are re-encoded to RGB JPEG so
    the stamped format/colorspace metadata is truthful — the reference's
    PNG/CMYK fixups (:256-308)."""
    import io

    from PIL import Image

    with open(anno["filepath"], "rb") as f:
        content = f.read()
    img = Image.open(io.BytesIO(content))
    if img.format != "JPEG" or img.mode != "RGB":
        buf = io.BytesIO()
        img.convert("RGB").save(buf, format="JPEG", quality=95)
        content = buf.getvalue()
    ex = {
        "image/colorspace": [b"RGB"],
        "image/channels": [3],
        "image/class/label": [anno["label"]],
        "image/class/synset": [anno["synset"].encode()],
        "image/format": [b"JPEG"],
        "image/filename": [anno["filename"].encode()],
        "image/encoded": [content],
    }
    # bbox fields (build_imagenet_tfrecord.py:184-254): parallel min/max
    # float lists + one label per box (all boxes carry the image label).
    # Written only when the run attached a bbox CSV — like the reference,
    # the classifier READ path ignores them; they exist to inform
    # Inception-style distorted-bbox crops and for tooling parity.
    if anno.get("bboxes"):
        bbs = anno["bboxes"]
        ex["image/object/bbox/xmin"] = [float(b[0]) for b in bbs]
        ex["image/object/bbox/ymin"] = [float(b[1]) for b in bbs]
        ex["image/object/bbox/xmax"] = [float(b[2]) for b in bbs]
        ex["image/object/bbox/ymax"] = [float(b[3]) for b in bbs]
        ex["image/object/bbox/label"] = [anno["label"]] * len(bbs)
    return ex


# -- CycleGAN ----------------------------------------------------------------

def cyclegan_examples(images_dir: str) -> Iterable[dict]:
    """Image-only annos for one domain split (CycleGAN/tensorflow/tfrecords.py)."""
    return [
        {"filepath": os.path.join(images_dir, n), "filename": n}
        for n in sorted(os.listdir(images_dir))
        if n.lower().endswith((".jpg", ".jpeg", ".png"))
    ]


def image_only_example(anno: dict) -> Optional[dict]:
    with open(anno["filepath"], "rb") as f:
        content = f.read()
    return {
        "image/encoded": [content],
        "image/filename": [anno["filename"].encode()],
    }


def celeba_split(
    attr_file: str,
    images_dir: str,
    out_dir: str,
    attribute: str = "Male",
    copy: bool = True,
) -> Tuple[int, int]:
    """Split CelebA into trainA/trainB domain folders by a binary attribute.

    The CycleGAN data story's first step (CycleGAN/tensorflow/celeba.py:1-24,
    which hardcodes byte offsets into list_attr_celeba.txt for the gender
    column); here the attribute is looked up by name from the header so any
    of the 40 CelebA attributes works. +1 -> trainA, -1 -> trainB.

    Returns (n_trainA, n_trainB). Missing image files are skipped.
    """
    with open(attr_file) as fp:
        fp.readline()  # line 1: image count
        names = fp.readline().split()  # line 2: attribute names
        if attribute not in names:
            raise ValueError(f"attribute {attribute!r} not in {names}")
        col = names.index(attribute)
        rows = [line.split() for line in fp if line.strip()]

    dir_a = os.path.join(out_dir, "trainA")
    dir_b = os.path.join(out_dir, "trainB")
    os.makedirs(dir_a, exist_ok=True)
    os.makedirs(dir_b, exist_ok=True)
    counts = [0, 0]
    n_skipped = 0
    for row in rows:
        filename, flags = row[0], row[1:]
        value = int(flags[col])
        if value not in (-1, 1):
            raise ValueError(f"bad attribute value {value} for {filename}")
        src = os.path.join(images_dir, filename)
        if not os.path.exists(src):
            n_skipped += 1
            continue
        dst_dir = dir_a if value == 1 else dir_b
        if copy:
            shutil.copyfile(src, os.path.join(dst_dir, filename))
        counts[0 if value == 1 else 1] += 1
    if rows and not (counts[0] or counts[1]):
        raise FileNotFoundError(
            f"none of the {len(rows)} listed images exist under {images_dir!r}"
            " — wrong --images-dir?"
        )
    if n_skipped:
        print(f"celeba_split: skipped {n_skipped} rows with missing images")
    return counts[0], counts[1]
