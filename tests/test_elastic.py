"""Elastic, preemption-native training (resilience/elastic.py + the
cross-mesh checkpoint path + the Trainer's SIGTERM escalation and
backend rebuild-replay).

The failure modes under test are the repo's own artifacts: BENCH_r02's
dropped backend connection, r04/r05's dead-tunnel hangs, and
MULTICHIP_r01's libtpu client/terminal version skew. Cross-mesh restore
is proven the way the issue specifies: save under an 8-device CPU mesh
(conftest forces --xla_force_host_platform_device_count=8), restore
under meshes over 4 and 1 of those devices, assert bit-identical leaves
and correct re-placement.
"""
import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deep_vision_tpu.resilience import elastic
from deep_vision_tpu.resilience.elastic import (
    BACKEND_LOST_KINDS,
    BackendSupervisor,
    classify_backend_error,
)
from deep_vision_tpu.resilience.retry import RetryPolicy

# the exact string MULTICHIP_r01 died on, 4 minutes into its compile
_R01_SKEW = (
    'FAILED_PRECONDITION: libtpu version mismatch: terminal has "TFRT TPU '
    'v5 lite ... cl/831091709", client AOT libtpu has "... cl/854318611". '
    "Client and terminal must use the same libtpu build"
)


def _no_sleep_policy(**kw) -> RetryPolicy:
    kw.setdefault("name", "test.backend")
    kw.setdefault("max_attempts", 4)
    kw.setdefault("base_delay_s", 0.0)
    kw.setdefault("jitter", 0.0)
    kw.setdefault("retry_on", Exception)
    return RetryPolicy(**kw)


class _Journal:
    def __init__(self):
        self.rows = []

    def write(self, event, **fields):
        self.rows.append({"event": event, **fields})

    def step(self, step, **fields):  # StepClock's per-step funnel
        self.rows.append({"event": "step", "step": step, **fields})

    def add_tap(self, fn):  # observer hooks (GoodputMeter, AlertEngine):
        pass                # inert here — these tests assert row trails

    def add_closer(self, fn):
        pass


# -- classification -----------------------------------------------------------

class TestClassification:
    def test_version_skew_from_the_r01_artifact(self):
        assert classify_backend_error(
            jax.errors.JaxRuntimeError(_R01_SKEW)) == "version_skew"
        assert classify_backend_error(_R01_SKEW) == "version_skew"

    def test_connection_loss_signatures(self):
        # BENCH_r02's shape, plus the usual transport endings
        for msg in ("INTERNAL: remote_compile: body closed",
                    "socket closed: UNAVAILABLE",
                    "the backend connection was dropped",
                    "Broken pipe"):
            assert classify_backend_error(
                RuntimeError(msg)) == "connection_lost", msg

    def test_timeout_signatures(self):
        for msg in ("DEADLINE_EXCEEDED: collective timed out",
                    "heartbeat missed",
                    "backend liveness probe still blocked after 180s "
                    "(dead tunnel?)"):
            assert classify_backend_error(msg) == "timeout", msg

    def test_non_transport_exceptions_stay_unknown(self):
        # a message can LOOK transient; the exception type gates it
        assert classify_backend_error(
            ValueError("shape mismatch in timeout_config.py")) == "unknown"
        assert classify_backend_error(
            FloatingPointError("diverged")) == "unknown"
        assert classify_backend_error(KeyboardInterrupt()) == "unknown"
        assert classify_backend_error(RuntimeError("boring bug")) == "unknown"
        # raw OSError/ConnectionError is the STORAGE layer's weather (its
        # own RetryPolicy owns it): it must NOT trigger a backend teardown
        assert classify_backend_error(
            ConnectionResetError("Connection reset by peer")) == "unknown"
        assert classify_backend_error(
            TimeoutError("read timed out")) == "unknown"

    def test_kinds_enum_matches_check_journal(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_journal", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools", "check_journal.py"))
        cj = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cj)
        assert set(BACKEND_LOST_KINDS) == cj.BACKEND_LOST_KINDS


# -- supervisor ---------------------------------------------------------------

class TestBackendSupervisor:
    def test_retryable_kinds_and_budget(self):
        sup = BackendSupervisor(policy=_no_sleep_policy(max_attempts=3))
        e = RuntimeError("socket closed: UNAVAILABLE")
        assert sup.should_retry(1, e) and sup.should_retry(2, e)
        assert not sup.should_retry(3, e)  # budget: 2 retries + first try

    def test_version_skew_never_retried(self):
        sup = BackendSupervisor(policy=_no_sleep_policy(),
                                retry_unclassified=True)
        assert not sup.should_retry(1, RuntimeError(_R01_SKEW))

    def test_unknown_gated_by_retry_unclassified(self):
        bug = RuntimeError("a plain bug")
        assert not BackendSupervisor(
            policy=_no_sleep_policy()).should_retry(1, bug)
        # bench's stance: a window is a replayable pure computation
        assert BackendSupervisor(
            policy=_no_sleep_policy(),
            retry_unclassified=True).should_retry(1, bug)

    def test_journals_typed_events(self):
        j = _Journal()
        sup = BackendSupervisor(policy=_no_sleep_policy(), journal=j)
        retrying = sup.on_failure(
            1, RuntimeError("DEADLINE_EXCEEDED: dead tunnel"), step=42,
            context="train/fit")
        assert retrying
        sup.on_recovered(1, step=43)
        lost = [r for r in j.rows if r["event"] == "backend_lost"]
        rec = [r for r in j.rows if r["event"] == "backend_recovered"]
        assert len(lost) == 1 and lost[0]["kind"] == "timeout"
        assert lost[0]["attempt"] == 1 and lost[0]["retrying"] is True
        assert lost[0]["step"] == 42 and lost[0]["context"] == "train/fit"
        assert len(rec) == 1 and rec[0]["attempt"] == 1
        # the shared retry event rides along for the existing dashboards
        assert any(r["event"] == "retry" and r["outcome"] == "retrying"
                   for r in j.rows)

    def test_backoff_jitter_rng_advances_per_draw(self):
        # the _ACTIVE_POLICY regression this design removes: a re-seeded
        # policy would re-draw the SAME "jittered" delay every retry
        slept = []
        sup = BackendSupervisor(policy=_no_sleep_policy(
            base_delay_s=1.0, jitter=0.5, multiplier=1.0,
            sleep=slept.append), clear_caches_after=99)
        sup.recover(1)
        sup.recover(1)
        assert len(slept) == 2 and slept[0] != slept[1]


# -- cross-mesh sharding metadata --------------------------------------------

def _tp_tree(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return {
        "w": jax.device_put(
            jnp.arange(32 * 16, dtype=jnp.float32).reshape(32, 16),
            NamedSharding(mesh, P(None, "model"))),
        "b": jax.device_put(jnp.arange(16, dtype=jnp.float32),
                            NamedSharding(mesh, P())),
    }


class TestShardingMeta:
    def test_meta_is_json_serializable_and_complete(self, mesh4x2):
        meta = elastic.sharding_meta(_tp_tree(mesh4x2))
        meta2 = json.loads(json.dumps(meta))  # the sidecar round trip
        assert meta2["mesh"] == {"data": 4, "model": 2}
        assert meta2["device_count"] == 8
        assert len(meta2["leaves"]) == 2
        w = [v for k, v in meta2["leaves"].items() if "'w'" in k][0]
        assert w == [None, "model"]

    def test_replace_preserves_spec_on_a_compatible_smaller_mesh(
            self, mesh4x2):
        from deep_vision_tpu.parallel.mesh import create_mesh

        tree = _tp_tree(mesh4x2)
        meta = json.loads(json.dumps(elastic.sharding_meta(tree)))
        mesh22 = create_mesh(devices=jax.devices()[:4], data=2, model=2)
        placed, stats = elastic.replace_on_mesh(
            jax.tree_util.tree_map(np.asarray, tree), meta, mesh22)
        assert "model" in str(placed["w"].sharding.spec)
        assert len(placed["w"].sharding.device_set) == 4
        assert stats["resharded"] == 1
        np.testing.assert_array_equal(np.asarray(placed["w"]),
                                      np.asarray(tree["w"]))

    def test_replace_drops_axes_the_new_mesh_cannot_honor(self, mesh4x2):
        from jax.sharding import Mesh

        tree = _tp_tree(mesh4x2)
        meta = json.loads(json.dumps(elastic.sharding_meta(tree)))
        # a mesh with NO model axis at all: the spec entry must drop
        data_only = Mesh(np.asarray(jax.devices()[:2]), ("data",))
        placed, stats = elastic.replace_on_mesh(
            jax.tree_util.tree_map(np.asarray, tree), meta, data_only)
        assert tuple(placed["w"].sharding.spec) == ()
        assert stats["dropped_dims"] == 1

    def test_replace_drops_indivisible_dims(self, mesh4x2):
        from jax.sharding import Mesh

        tree = _tp_tree(mesh4x2)
        meta = json.loads(json.dumps(elastic.sharding_meta(tree)))
        # model axis of 3 does not divide the 16-wide dim: replicate it
        mesh3 = Mesh(np.asarray(jax.devices()[:3]).reshape(1, 3),
                     ("data", "model"))
        placed, _ = elastic.replace_on_mesh(
            jax.tree_util.tree_map(np.asarray, tree), meta, mesh3)
        assert tuple(placed["w"].sharding.spec) == ()

    def test_none_meta_places_replicated(self, mesh8):
        placed, stats = elastic.replace_on_mesh(
            {"w": np.ones((4, 4), np.float32)}, None, mesh8)
        assert len(placed["w"].sharding.device_set) == 8
        assert tuple(placed["w"].sharding.spec) == ()
        assert stats["resharded"] == 0


# -- cross-mesh checkpoint restore (the tentpole proof) -----------------------

def _tiny_state(mesh):
    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.parallel.mesh import replicated
    from deep_vision_tpu.train.optimizers import build_optimizer

    state = create_train_state(
        get_model("lenet5", num_classes=10),
        build_optimizer("sgd", learning_rate=0.1),
        jnp.ones((2, 32, 32, 1), jnp.float32))
    return jax.device_put(state, replicated(mesh))


class TestCrossMeshRestore:
    @pytest.mark.slow
    def test_save_on_8_restore_on_4_and_1(self, mesh8, tmp_path):
        """The issue's proof: checkpoint under 8 devices, restore under 4
        and 1 — bit-identical leaves, re-placed on the current mesh."""
        from deep_vision_tpu.core import CheckpointManager
        from deep_vision_tpu.parallel.mesh import create_mesh

        state = _tiny_state(mesh8).replace(step=jnp.asarray(9, jnp.int32))
        cm = CheckpointManager(str(tmp_path))
        assert cm.save(9, state, host_state={"epoch": 4})
        cm.close()
        want = jax.tree_util.tree_leaves(
            jax.device_get({"p": state.params, "o": state.opt_state}))
        for nd in (4, 1):
            mesh = create_mesh(devices=jax.devices()[:nd])
            cm2 = CheckpointManager(str(tmp_path))
            restored, host = cm2.restore(_tiny_state(mesh), mesh=mesh)
            cm2.close()
            assert host == {"epoch": 4}  # sharding meta stripped
            assert cm2.last_restore_placed
            assert int(restored.step) == 9
            got = jax.tree_util.tree_leaves(
                jax.device_get({"p": restored.params,
                                "o": restored.opt_state}))
            for a, b in zip(got, want):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            placements = {len(x.sharding.device_set)
                          for x in jax.tree_util.tree_leaves(
                              restored.params)}
            assert placements == {nd}

    def test_tree_roundtrip_keeps_tp_layout_across_meshes(self, mesh4x2,
                                                          tmp_path):
        from deep_vision_tpu.core import CheckpointManager
        from deep_vision_tpu.parallel.mesh import create_mesh, replicated

        tree = _tp_tree(mesh4x2)
        cm = CheckpointManager(str(tmp_path))
        assert cm.save_tree(1, tree)  # no host_state: sidecar still written
        cm.close()
        assert os.path.exists(os.path.join(str(tmp_path),
                                           "host_state_1.json"))
        mesh22 = create_mesh(devices=jax.devices()[:4], data=2, model=2)
        cm2 = CheckpointManager(str(tmp_path))
        template = {k: jax.device_put(jnp.zeros_like(v), replicated(mesh22))
                    for k, v in tree.items()}
        out, host = cm2.restore_tree(template, mesh=mesh22)
        cm2.close()
        assert host == {}  # only the reserved key was in the sidecar
        assert "model" in str(out["w"].sharding.spec)
        np.testing.assert_array_equal(np.asarray(out["w"]),
                                      np.asarray(tree["w"]))

    def test_legacy_restore_without_mesh_unchanged(self, mesh8, tmp_path):
        from deep_vision_tpu.core import CheckpointManager

        state = _tiny_state(mesh8)
        cm = CheckpointManager(str(tmp_path))
        assert cm.save(1, state, host_state={"epoch": 0})
        cm.wait()
        restored, host = cm.restore(_tiny_state(mesh8))
        cm.close()
        assert host == {"epoch": 0}
        assert not cm.last_restore_placed
        assert int(restored.step) == 0  # saved at a fresh step


# -- preflight ----------------------------------------------------------------

class TestPreflight:
    def test_mesh_shape_pass_and_fail(self):
        from deep_vision_tpu.tools import preflight as pf

        assert pf.check_mesh_shape(8, data=4, model=2).ok
        assert not pf.check_mesh_shape(8, data=4, model=3).ok
        r = pf.check_mesh_shape(6, expect_devices=8)
        assert not r.ok and "degraded" in r.detail

    def test_client_versions_pass_and_skew(self):
        from deep_vision_tpu.tools import preflight as pf

        assert pf.check_client_versions("0.4.37", "0.4.36").ok  # patch drift
        r = pf.check_client_versions("0.5.0", "0.4.30")
        assert not r.ok and r.kind == "version_skew"

    def test_ckpt_dir_pass_and_fail(self, tmp_path):
        from deep_vision_tpu.tools import preflight as pf

        assert pf.check_ckpt_dir(str(tmp_path / "ok")).ok
        # leftover probe files are cleaned up
        assert not [p for p in os.listdir(str(tmp_path / "ok"))
                    if p.startswith(".preflight")]
        blocker = tmp_path / "not_a_dir"
        blocker.write_text("file where a dir must go")
        assert not pf.check_ckpt_dir(str(blocker)).ok

    def test_backend_probe_classifies_the_r01_skew(self):
        from deep_vision_tpu.tools import preflight as pf

        def skewed_probe():
            raise jax.errors.JaxRuntimeError(_R01_SKEW)

        r = pf.check_backend(budget_s=10.0, probe=skewed_probe)
        assert not r.ok and r.kind == "version_skew"

    def test_backend_probe_reports_dead_tunnel_as_timeout(self):
        import time

        from deep_vision_tpu.tools import preflight as pf

        r = pf.check_backend(budget_s=0.2, probe=lambda: time.sleep(60))
        assert not r.ok and r.kind == "timeout"

    def test_run_preflight_passes_on_cpu(self, tmp_path):
        from deep_vision_tpu.tools import preflight as pf

        j = _Journal()
        ok, results = pf.run_preflight(ckpt_dir=str(tmp_path / "ck"),
                                       budget_s=60.0, journal=j)
        assert ok, [(r.name, r.detail) for r in results if not r.ok]
        assert [r.name for r in results] == [
            "client_versions", "backend", "mesh_shape",
            "sharding_tables", "ckpt_dir"]
        assert any(r["event"] == "note" and r.get("note") == "preflight"
                   for r in j.rows)

    def test_failed_backend_skips_downstream_checks(self):
        from deep_vision_tpu.tools import preflight as pf

        def dead():
            raise RuntimeError("socket closed: UNAVAILABLE")

        ok, results = pf.run_preflight(probe=dead, budget_s=10.0,
                                       shard_tables=False)
        assert not ok
        assert [r.name for r in results] == ["client_versions", "backend"]

    def test_cli_pass_and_fail(self, tmp_path, capsys):
        from deep_vision_tpu.tools import preflight as pf

        assert pf.main(["--ckpt-dir", str(tmp_path / "ck"), "--json"]) == 0
        line = capsys.readouterr().out.strip().splitlines()[-1]
        doc = json.loads(line)
        assert doc["ok"] and len(doc["checks"]) == 5
        assert "sharding_tables" in [c["name"] for c in doc["checks"]]
        assert pf.main(["--expect-devices", "999",
                        "--no-shard-check"]) == 1


# -- SIGTERM escalation: checkpoint-now-and-requeue ---------------------------

def _synthetic_batches(n=3, bs=16):
    rng = np.random.RandomState(0)
    return [{"image": rng.rand(bs, 32, 32, 1).astype(np.float32),
             "label": rng.randint(0, 10, (bs,)).astype(np.int32)}
            for _ in range(n)]


def _make_trainer(mesh, tmp_path, journal=None, **kw):
    from deep_vision_tpu.core import CheckpointManager
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train import Trainer, build_optimizer

    return Trainer(
        get_model("lenet5", num_classes=10),
        build_optimizer("adam", 1e-3),
        classification_loss_fn,
        sample_input=jnp.zeros((8, 32, 32, 1)),
        mesh=mesh,
        checkpoint_manager=CheckpointManager(str(tmp_path)),
        journal=journal,
        **kw,
    )


class TestPreemptEscalation:
    def test_sigterm_checkpoints_journals_and_requests_requeue(
            self, mesh8, tmp_path):
        from deep_vision_tpu.obs import flight

        flight.clear_requeue()
        j = _Journal()
        trainer = _make_trainer(mesh8, tmp_path, journal=j)
        data = _synthetic_batches()

        def preempting():
            for i, b in enumerate(data):
                if i == 1:
                    os.kill(os.getpid(), signal.SIGTERM)
                yield b

        try:
            trainer.fit(preempting, epochs=3)
            assert trainer.preempted
            assert flight.requeue_requested()
            pc = [r for r in j.rows if r["event"] == "preempt_checkpoint"]
            assert len(pc) == 1
            assert pc[0]["saved"] is True
            assert pc[0]["step"] == int(trainer.state.step)
            assert pc[0]["dir"] == trainer.ckpt.directory
            # ordering: the checkpoint event precedes preempt_checkpoint
            events = [r["event"] for r in j.rows]
            assert events.index("checkpoint") < events.index(
                "preempt_checkpoint")
        finally:
            flight.clear_requeue()
            trainer.close()

    def test_requeue_latch_set_even_without_checkpoint_manager(
            self, mesh8):
        from deep_vision_tpu.losses.classification import (
            classification_loss_fn,
        )
        from deep_vision_tpu.models import get_model
        from deep_vision_tpu.obs import flight
        from deep_vision_tpu.train import Trainer, build_optimizer

        flight.clear_requeue()
        j = _Journal()
        trainer = Trainer(
            get_model("lenet5", num_classes=10),
            build_optimizer("adam", 1e-3), classification_loss_fn,
            sample_input=jnp.zeros((8, 32, 32, 1)), mesh=mesh8, journal=j)
        data = _synthetic_batches()

        def preempting():
            for i, b in enumerate(data):
                if i == 1:
                    os.kill(os.getpid(), signal.SIGTERM)
                yield b

        try:
            trainer.fit(preempting, epochs=2)
            assert flight.requeue_requested()
            pc = [r for r in j.rows if r["event"] == "preempt_checkpoint"]
            assert len(pc) == 1 and pc[0]["saved"] is False
        finally:
            flight.clear_requeue()
            trainer.close()


# -- Trainer backend-loss rebuild-replay --------------------------------------

class TestTrainerRebuildReplay:
    @pytest.mark.slow
    def test_backend_loss_mid_run_resumes_from_checkpoint(self, mesh8,
                                                          tmp_path):
        """Epoch 0 checkpoints; the first step of epoch 1 dies with a
        connection-loss signature. The supervisor must rebuild the jitted
        step, restore the epoch-0 checkpoint, replay, and finish — with
        typed backend_lost/backend_recovered events bracketing it."""
        j = _Journal()
        sup = BackendSupervisor(policy=_no_sleep_policy(), journal=j)
        trainer = _make_trainer(mesh8, tmp_path, journal=j,
                                backend_supervisor=sup)
        data = _synthetic_batches(n=3)
        steps_per_epoch = len(data)

        orig = trainer._train_step
        fired = {"n": 0}

        def flaky(state, batch):
            # the wrapper dies ONCE, at the first step of epoch 1; the
            # recovery path re-creates _train_step so the sabotage is gone
            # exactly the way a rebuilt client replaces a dead one
            fired["n"] += 1
            if fired["n"] == steps_per_epoch + 1:
                raise RuntimeError("INTERNAL: remote_compile: body closed")
            return orig(state, batch)

        trainer._train_step = flaky
        try:
            trainer.fit(lambda: data, epochs=2)
            assert int(trainer.state.step) == 2 * steps_per_epoch
            lost = [r for r in j.rows if r["event"] == "backend_lost"]
            rec = [r for r in j.rows if r["event"] == "backend_recovered"]
            assert len(lost) == 1 and lost[0]["kind"] == "connection_lost"
            assert len(rec) == 1 and rec[0]["step"] == 2 * steps_per_epoch
            assert any(r["event"] == "note" and r.get("note") == "resumed"
                       for r in j.rows)
            # the rebuilt step is a REAL jitted callable, not the sabotage
            assert trainer._train_step is not flaky
        finally:
            trainer.close()

    def test_unclassified_and_skew_failures_propagate(self, mesh8,
                                                      tmp_path):
        sup = BackendSupervisor(policy=_no_sleep_policy())
        trainer = _make_trainer(mesh8, tmp_path, backend_supervisor=sup)
        data = _synthetic_batches(n=2)

        def bug(state, batch):
            raise RuntimeError(_R01_SKEW)

        trainer._train_step = bug
        try:
            with pytest.raises(RuntimeError, match="libtpu"):
                trainer.fit(lambda: data, epochs=1)
        finally:
            trainer.close()

    def test_no_supervisor_keeps_failfast_behavior(self, mesh8, tmp_path):
        trainer = _make_trainer(mesh8, tmp_path)
        data = _synthetic_batches(n=2)

        def dead(state, batch):
            raise RuntimeError("socket closed: UNAVAILABLE")

        trainer._train_step = dead
        try:
            with pytest.raises(RuntimeError, match="socket closed"):
                trainer.fit(lambda: data, epochs=1)
        finally:
            trainer.close()


# -- sharding-coverage hard check ---------------------------------------------

class TestShardingCoverage:
    def test_counts_and_gauges(self, mesh4x2):
        from deep_vision_tpu.obs.registry import Registry
        from deep_vision_tpu.parallel.mesh import (
            assert_sharding_coverage,
            infer_tp_sharding,
        )

        tree = {"big": jnp.ones((64, 64), jnp.float32),
                "bias": jnp.ones((8,), jnp.float32),
                "step": jnp.asarray(1, jnp.int32)}
        sh = infer_tp_sharding(tree, mesh4x2, min_size=64)
        reg = Registry()
        stats = assert_sharding_coverage(tree, sh, mesh4x2, min_sharded=1,
                                         registry=reg)
        # replicated_paths names the leaves that fell back to
        # replication (the floor-failure message uses them — the 108->34
        # incident was undebuggable from bare counts)
        assert stats == {"float_leaves": 2, "sharded": 1, "replicated": 1,
                         "replicated_paths": ["['bias']"],
                         "unmatched": []}
        assert reg.gauge("parallel_sharded_leaves").value == 1
        assert reg.gauge("parallel_float_leaves").value == 2

    def test_regression_below_floor_fails_loudly(self, mesh4x2):
        from deep_vision_tpu.parallel.mesh import (
            ShardingCoverageError,
            assert_sharding_coverage,
            infer_tp_sharding,
        )

        tree = {"big": jnp.ones((64, 64), jnp.float32)}
        sh = infer_tp_sharding(tree, mesh4x2, min_size=10**9)  # all repl.
        with pytest.raises(ShardingCoverageError, match="regressed"):
            assert_sharding_coverage(tree, sh, mesh4x2, min_sharded=1,
                                     registry=None)

    def test_unmatched_float_leaf_fails_with_its_path(self, mesh4x2):
        from deep_vision_tpu.parallel.mesh import (
            ShardingCoverageError,
            assert_sharding_coverage,
            infer_tp_sharding,
        )

        tree = {"a": jnp.ones((4, 4), jnp.float32),
                "b": jnp.ones((4, 4), jnp.float32)}
        sh = dict(infer_tp_sharding(tree, mesh4x2))
        del sh["b"]  # a rule that stopped matching
        with pytest.raises(ShardingCoverageError, match="'b'"):
            assert_sharding_coverage(tree, sh, mesh4x2)


# -- requeue latch ------------------------------------------------------------

def test_requeue_latch_roundtrip():
    from deep_vision_tpu.obs import flight

    flight.clear_requeue()
    assert not flight.requeue_requested()
    flight.request_requeue()
    assert flight.requeue_requested()
    flight.clear_requeue()
    assert not flight.requeue_requested()
    assert flight.REQUEUE_EXIT_CODE == 75  # EX_TEMPFAIL, the requeue code
