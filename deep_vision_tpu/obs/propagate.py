"""Cross-process trace-context propagation (W3C traceparent style).

One request's life crosses process boundaries: loadgen client ->
serve router -> engine, or trainer -> data-service worker. Each hop
already journals typed events, but nothing ties the client's view of a
request to the server's — the merged timeline (obs/merge.py) can order
events by time, not by cause. A `TraceContext` is the causal thread:

    trace_id        32 lowercase hex chars — one per request/batch,
                    minted at ingress and constant across every hop
    span_id         16 lowercase hex chars — one per hop
    parent_span_id  the span this hop was born from (None at the root)

The wire form is the W3C `traceparent` header, version 00:

    00-<trace_id>-<span_id>-01

which travels as a string feature over the data-service frame protocol
and rides the in-process serve Request object. Journal events written
while a context is installed (`use(ctx)`) are stamped with
trace_id/span_id/parent_span_id automatically (obs/journal.py), and
trace spans carry the ids as args (obs/trace.py), so `obs_report
--merged` can group a merged timeline's events by trace_id into one
causal, cross-process request timeline.

Design constraints, same as the rest of obs/:
- stdlib only, no jax at import time (data workers import this);
- malformed wire contexts parse to None, never raise — propagation is
  telemetry, and telemetry must degrade rather than kill the request
  it is describing;
- the installed context is thread-local: the serve dispatcher thread
  and the submit thread are different threads, so the serve path
  carries the context explicitly on the Request instead of relying on
  the ambient slot.
"""
from __future__ import annotations

import contextlib
import os
import re
import threading
from typing import Iterator, Optional

__all__ = [
    "TraceContext",
    "new_trace",
    "from_traceparent",
    "current",
    "use",
]

TRACEPARENT_VERSION = "00"
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")
_SPAN_ID_RE = re.compile(r"^[0-9a-f]{16}$")
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

# journal field names, shared with check_journal's schema
TRACE_FIELDS = ("trace_id", "span_id", "parent_span_id")


class TraceContext:
    """One hop of one request: ids only, no timing (timing lives in the
    journal events and trace spans the ids are stamped onto)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id

    def child(self) -> "TraceContext":
        """A new hop of the same request: fresh span, this one as parent."""
        return TraceContext(self.trace_id, _new_span_id(), self.span_id)

    def to_traceparent(self) -> str:
        return f"{TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}-01"

    def fields(self) -> dict:
        """The journal-event stamping: {trace_id, span_id[, parent_span_id]}."""
        out = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_span_id:
            out["parent_span_id"] = self.parent_span_id
        return out

    def __repr__(self) -> str:  # debugging aid, not a wire format
        return (f"TraceContext({self.trace_id[:8]}../{self.span_id}"
                f"{' <- ' + self.parent_span_id if self.parent_span_id else ''})")

    def __eq__(self, other) -> bool:
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.parent_span_id == other.parent_span_id)


def _new_span_id() -> str:
    return os.urandom(8).hex()


def new_trace() -> TraceContext:
    """Mint a root context — call at request/batch ingress."""
    return TraceContext(os.urandom(16).hex(), _new_span_id(), None)


def from_traceparent(value) -> Optional[TraceContext]:
    """Parse a wire `traceparent`; None on anything malformed.

    The parsed context's span becomes the PARENT of the receiving hop:
    callers should `.child()` it before stamping local events, so the
    two sides of the wire stay distinct spans of one trace.
    """
    if isinstance(value, bytes):
        try:
            value = value.decode("ascii")
        except UnicodeDecodeError:
            return None
    if not isinstance(value, str):
        return None
    # lowercase-only by the W3C spec: an uppercase-hex producer is
    # malformed, and silently lowercasing would make our journal ids
    # disagree with what actually crossed the wire
    m = _TRACEPARENT_RE.match(value.strip())
    if not m:
        return None
    version, trace_id, span_id, _flags = m.groups()
    if version == "ff":  # forbidden by the W3C spec
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id, None)


def valid_trace_id(value) -> bool:
    return isinstance(value, str) and bool(_TRACE_ID_RE.match(value))


def valid_span_id(value) -> bool:
    return isinstance(value, str) and bool(_SPAN_ID_RE.match(value))


# -- the ambient (thread-local) context ------------------------------------

_tls = threading.local()


def current() -> Optional[TraceContext]:
    """The context installed on THIS thread, or None."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """Install `ctx` as this thread's ambient context for the block.

    Journal writes inside the block are stamped with the context's ids;
    nesting restores the outer context on exit. `use(None)` masks an
    outer context (e.g. a maintenance write inside a traced region).
    """
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev
