"""CLI: `python -m deep_vision_tpu.tools.convert <dataset> ...` — offline
dataset -> sharded record conversion (the `Datasets/*/tfrecords*.py` scripts
unified; shard counts default to the reference's conventions)."""
from __future__ import annotations

import argparse

from deep_vision_tpu.tools import converters as C


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="dataset", required=True)

    voc = sub.add_parser("voc", help="VOCdevkit/VOC2007|2012 -> records")
    voc.add_argument("--voc-root", required=True)
    voc.add_argument("--split", default="train",
                     choices=["train", "val", "trainval", "test"])
    voc.add_argument("--out-dir", required=True)
    # VOC2007/tfrecords.py:15-18: 15 train / 5 val shards
    voc.add_argument("--num-shards", type=int, default=15)

    coco = sub.add_parser("coco", help="MSCOCO instances json -> records")
    coco.add_argument("--instances-json", required=True)
    coco.add_argument("--images-dir", required=True)
    coco.add_argument("--out-dir", required=True)
    coco.add_argument("--prefix", default="train")
    # MSCOCO/tfrecords.py:13-14: 64 train / 8 val shards
    coco.add_argument("--num-shards", type=int, default=64)

    mpii = sub.add_parser("mpii", help="MPII preprocessed json -> records")
    mpii.add_argument("--json", required=True)
    mpii.add_argument("--images-dir", required=True)
    mpii.add_argument("--out-dir", required=True)
    mpii.add_argument("--prefix", default="train")
    mpii.add_argument("--num-shards", type=int, default=16)

    imagenet = sub.add_parser("imagenet", help="flattened ImageNet -> records")
    imagenet.add_argument("--root", required=True)
    imagenet.add_argument("--synsets", required=True)
    imagenet.add_argument("--out-dir", required=True)
    imagenet.add_argument("--prefix", default="train")
    # build_imagenet_tfrecord.py:104-160: 1024 train / 128 val shards
    imagenet.add_argument("--num-shards", type=int, default=1024)
    imagenet.add_argument("--bbox-csv", default=None,
                          help="CSV from `imagenet_bboxes`; attaches "
                               "image/object/bbox/* fields per filename")

    prep = sub.add_parser(
        "prepare-imagenet",
        help="raw ILSVRC2012 download -> flattened train/val layout "
             "(untar-script.sh + flatten-script.sh + flatten-val-script.sh "
             "analog)",
    )
    prep.add_argument("--out-dir", required=True)
    prep.add_argument("--train-tars", default=None,
                      help="dir of per-synset nXXXXXXXX.tar files")
    prep.add_argument("--train-dir", default=None,
                      help="already-untarred per-synset tree")
    prep.add_argument("--val-dir", default=None,
                      help="flat ILSVRC2012_val_*.JPEG folder")
    prep.add_argument("--val-synsets", default=None,
                      help="imagenet_2012_validation_synset_labels.txt")
    prep.add_argument("--move", action="store_true",
                      help="move instead of hardlink/copy")

    inbb = sub.add_parser(
        "imagenet_bboxes",
        help="ImageNet bbox XMLs -> relative-coords CSV "
             "(process_bounding_boxes.py analog)",
    )
    inbb.add_argument("--xml-dir", required=True)
    inbb.add_argument("--out-csv", required=True)
    inbb.add_argument("--synsets", default=None,
                      help="restrict to challenge synsets (one id per line)")

    cyc = sub.add_parser("cyclegan", help="image folder -> one record file")
    cyc.add_argument("--images-dir", required=True)
    cyc.add_argument("--out-dir", required=True)
    cyc.add_argument("--prefix", default="trainA")

    celeba = sub.add_parser(
        "celeba", help="CelebA attribute -> trainA/trainB domain split"
    )
    celeba.add_argument("--attr-file", required=True,
                        help="path to list_attr_celeba.txt")
    celeba.add_argument("--images-dir", required=True)
    celeba.add_argument("--out-dir", required=True)
    celeba.add_argument("--attribute", default="Male",
                        help="any of the 40 CelebA attribute names")

    common = dict(num_workers=None)
    for sp in (voc, coco, mpii, imagenet, cyc):
        sp.add_argument("--workers", type=int, default=None)
    args = p.parse_args(argv)
    common["num_workers"] = getattr(args, "workers", None)

    if args.dataset == "voc":
        annos = C.voc_annotations(args.voc_root, args.split)
        C.build_shards(annos, C.detection_example, args.out_dir, args.split,
                       args.num_shards, **common)
    elif args.dataset == "coco":
        annos = C.coco_annotations(args.instances_json, args.images_dir)
        C.build_shards(annos, C.detection_example, args.out_dir, args.prefix,
                       args.num_shards, **common)
    elif args.dataset == "mpii":
        annos = C.mpii_annotations(args.json, args.images_dir)
        C.build_shards(annos, C.mpii_example, args.out_dir, args.prefix,
                       args.num_shards, **common)
    elif args.dataset == "imagenet":
        annos = C.imagenet_annotations(args.root, args.synsets,
                                       bbox_csv=args.bbox_csv)
        C.build_shards(annos, C.imagenet_example, args.out_dir, args.prefix,
                       args.num_shards, **common)
    elif args.dataset == "prepare-imagenet":
        stats = C.prepare_imagenet(
            args.out_dir, train_tars=args.train_tars,
            train_dir=args.train_dir, val_dir=args.val_dir,
            val_synsets=args.val_synsets, move=args.move,
        )
        parts = []
        if args.train_tars or args.train_dir:
            parts.append(f"{stats['train']} train -> "
                         f"{args.out_dir}/train_flatten")
        if args.val_dir:
            parts.append(f"{stats['val']} val -> {args.out_dir}/val_flatten")
        print("prepare-imagenet: " + ", ".join(parts))
    elif args.dataset == "imagenet_bboxes":
        stats = C.imagenet_bbox_csv(args.xml_dir, args.out_csv, args.synsets)
        annotated = (stats["files"] - stats["skipped_files"]
                     - stats["malformed_files"])
        print(f"Finished processing {stats['files']} XML files.\n"
              f"Skipped {stats['skipped_files']} XML files not in ImageNet "
              f"Challenge.\n"
              f"Skipped {stats['skipped_boxes']} bounding boxes not in "
              f"ImageNet Challenge.\n"
              f"Skipped {stats['malformed_files']} malformed XML files.\n"
              f"Wrote {stats['boxes']} bounding boxes from "
              f"{annotated} annotated images.")
    elif args.dataset == "cyclegan":
        annos = C.cyclegan_examples(args.images_dir)
        C.build_shards(annos, C.image_only_example, args.out_dir, args.prefix,
                       num_shards=1, **common)
    elif args.dataset == "celeba":
        n_a, n_b = C.celeba_split(
            args.attr_file, args.images_dir, args.out_dir, args.attribute
        )
        print(f"celeba: {n_a} -> trainA, {n_b} -> trainB")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
