"""Darknet-53 backbone + YOLOv3 multi-scale detection head (Redmon 2018).

Parity targets: YOLO/tensorflow/yolov3.py — DarknetConv (:23-41, LeakyReLU 0.1
+ BN), DarknetResidual (:44-51), Darknet backbone returning 3 scales (:54-92),
YoloV3 head with upsample+concat FPN-style necks (:95-235). In training mode
returns raw per-scale tensors (B, g, g, 3, 5+C) exactly like yolov3.py:221-222;
box decode to absolute coordinates lives in ops/boxes.py (the eval-mode Lambda
appendix at yolov3.py:224-235).
"""
from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import ConvBN

_leaky = lambda x: nn.leaky_relu(x, 0.1)


class DarknetConv(nn.Module):
    features: int
    kernel: int = 3
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        # stride-2 darknet convs use top-left asymmetric padding (yolov3.py:30-33)
        pad = "SAME" if self.strides == 1 else [(1, 0), (1, 0)]
        return ConvBN(
            self.features,
            (self.kernel, self.kernel),
            strides=(self.strides, self.strides),
            padding=pad,
            act=_leaky,
        )(x, train)


class DarknetResidual(nn.Module):
    features: int  # block output channels

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = DarknetConv(self.features // 2, 1)(x, train)
        y = DarknetConv(self.features, 3)(y, train)
        return x + y


class Darknet53(nn.Module):
    """Backbone; returns (C3, C4, C5) feature maps at /8, /16, /32."""

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = DarknetConv(32, 3)(x, train)
        x = DarknetConv(64, 3, strides=2)(x, train)
        x = DarknetResidual(64)(x, train)
        x = DarknetConv(128, 3, strides=2)(x, train)
        for _ in range(2):
            x = DarknetResidual(128)(x, train)
        x = DarknetConv(256, 3, strides=2)(x, train)
        for _ in range(8):
            x = DarknetResidual(256)(x, train)
        c3 = x
        x = DarknetConv(512, 3, strides=2)(x, train)
        for _ in range(8):
            x = DarknetResidual(512)(x, train)
        c4 = x
        x = DarknetConv(1024, 3, strides=2)(x, train)
        for _ in range(4):
            x = DarknetResidual(1024)(x, train)
        c5 = x
        return c3, c4, c5


class YoloNeck(nn.Module):
    """5-conv block producing the scale's feature + the upsample branch input."""

    features: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = DarknetConv(self.features, 1)(x, train)
        x = DarknetConv(self.features * 2, 3)(x, train)
        x = DarknetConv(self.features, 1)(x, train)
        x = DarknetConv(self.features * 2, 3)(x, train)
        x = DarknetConv(self.features, 1)(x, train)
        return x


class YoloHead(nn.Module):
    features: int
    num_anchors: int
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = DarknetConv(self.features * 2, 3)(x, train)
        x = nn.Conv(self.num_anchors * (5 + self.num_classes), (1, 1))(x)
        b, g1, g2, _ = x.shape
        return x.reshape(b, g1, g2, self.num_anchors, 5 + self.num_classes)


def _upsample2x(x):
    b, h, w, c = x.shape
    return jax.image.resize(x, (b, h * 2, w * 2, c), method="nearest")


class YoloV3(nn.Module):
    """Returns 3 raw scale outputs (large->small stride): shapes
    (B, s/32, s/32, 3, 5+C), (B, s/16, ...), (B, s/8, ...)."""

    num_classes: int = 80

    @nn.compact
    def __call__(self, x, train: bool = True):
        c3, c4, c5 = Darknet53()(x, train)
        n5 = YoloNeck(512)(c5, train)
        out_large = YoloHead(512, 3, self.num_classes)(n5, train)

        u5 = DarknetConv(256, 1)(n5, train)
        n4 = YoloNeck(256)(jnp.concatenate([_upsample2x(u5), c4], -1), train)
        out_medium = YoloHead(256, 3, self.num_classes)(n4, train)

        u4 = DarknetConv(128, 1)(n4, train)
        n3 = YoloNeck(128)(jnp.concatenate([_upsample2x(u4), c3], -1), train)
        out_small = YoloHead(128, 3, self.num_classes)(n3, train)
        return out_large, out_medium, out_small


@register_model("yolov3")
def yolov3(num_classes: int = 80, **_):
    return YoloV3(num_classes=num_classes)


@register_model("darknet53")
def darknet53(**_):
    return Darknet53()
