"""Model export: serialize any registered model to portable StableHLO.

The TPU-native analog of the reference's TFLite conversion
(CycleGAN/tensorflow/convert.py:1-15, SavedModel -> TFLiteConverter):
`jax.export` lowers the jitted eval-mode apply to StableHLO with the trained
variables baked in as constants, and serializes it with shape/dtype calling
conventions attached. The artifact reloads with `load_exported` and runs on
any JAX backend (CPU/TPU) without the model's Python class — the same
"frozen inference artifact" role TFLite plays in the reference.

CLI:
    python -m deep_vision_tpu.tools.export -m resnet50 -o resnet50.stablehlo \
        [-c checkpoints/resnet50] [--batch 8]

GAN configs export the generator (the deployable half, matching
CycleGAN/tensorflow/inference.py:11-70 which restores only generator_a2b).
"""
from __future__ import annotations

import argparse
from typing import Optional

import numpy as np


def export_model(model, variables, sample_input, *, train: bool = False):
    """Returns a `jax.export.Exported` of eval-mode `model.apply`."""
    import jax
    from jax import export as jexport

    def infer(x):
        return model.apply(variables, x, train=train)

    return jexport.export(jax.jit(infer))(
        jax.ShapeDtypeStruct(np.shape(sample_input), sample_input.dtype)
    )


def save_exported(exported, path: str) -> None:
    with open(path, "wb") as f:
        f.write(exported.serialize())


def load_exported(path: str):
    """Load a serialized artifact; returns an object with `.call(x)`."""
    from jax import export as jexport

    with open(path, "rb") as f:
        return jexport.deserialize(f.read())


def export_config(name: str, out_path: str, ckpt_dir: Optional[str] = None,
                  batch: int = 8) -> str:
    """Export a registry config's model (GANs: the generator)."""
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.configs import get_config
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train_cli import model_input_shape

    cfg = get_config(name)
    rngs = {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)}
    if cfg.task == "dcgan":
        model = get_model("dcgan_generator")
        sample = jnp.zeros((batch, 100), jnp.float32)
    elif cfg.task == "cyclegan":
        model = get_model("cyclegan_generator")
        sample = jnp.zeros((batch, *cfg.input_shape), jnp.float32)
    else:
        kwargs = dict(cfg.model_kwargs)
        if cfg.task != "pose":
            kwargs["num_classes"] = cfg.num_classes
        model = get_model(cfg.model, **kwargs)
        sample = jnp.zeros((batch, *model_input_shape(cfg)), jnp.float32)
    variables = model.init(rngs, sample, train=False)

    if ckpt_dir:
        # template-free restore: export must not reconstruct the trainer's
        # optimizer/schedule state tree (raises FileNotFoundError when the
        # dir has no checkpoint — never silently export fresh-init weights)
        from deep_vision_tpu.core.checkpoint import CheckpointManager

        variables = CheckpointManager(ckpt_dir).restore_variables()

    exported = export_model(model, variables, sample)
    save_exported(exported, out_path)
    return out_path


def main(argv=None) -> int:
    from deep_vision_tpu.configs import CONFIG_REGISTRY

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model", required=True, choices=sorted(CONFIG_REGISTRY))
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-c", "--checkpoint", default=None, help="checkpoint dir")
    p.add_argument("--batch", type=int, default=8)
    args = p.parse_args(argv)
    path = export_config(args.model, args.output, args.checkpoint, args.batch)
    import os

    print(f"exported {args.model} -> {path} ({os.path.getsize(path):,} bytes)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
