"""[tool.jaxlint] config from pyproject.toml.

Python 3.10 has no tomllib, and the container must not grow a toml dep,
so when tomllib is unavailable we fall back to a minimal section parser
that understands exactly the value shapes jaxlint's keys use: strings
and (possibly multi-line) string arrays — both of which are also valid
Python literals.
"""
from __future__ import annotations

import ast
import os
import re
from typing import List, Optional

DEFAULTS = {
    "paths": ["deep_vision_tpu", "tools", "train.py"],
    "exclude": [],
    "baseline": ".jaxlint-baseline.json",
    "disable": [],
}


def find_pyproject(start: str) -> Optional[str]:
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        candidate = os.path.join(cur, "pyproject.toml")
        if os.path.isfile(candidate):
            return candidate
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def _parse_section_fallback(text: str) -> dict:
    m = re.search(r"^\[tool\.jaxlint\]\s*$", text, re.M)
    if m is None:
        return {}
    body = text[m.end():]
    stop = re.search(r"^\[", body, re.M)
    if stop is not None:
        body = body[:stop.start()]
    out = {}
    # join multi-line arrays, strip full-line comments
    lines = [ln for ln in body.splitlines()
             if not ln.lstrip().startswith("#")]
    joined = "\n".join(lines)
    for key, raw in re.findall(
            r"^([A-Za-z_][\w-]*)\s*=\s*((?:\[[^\]]*\])|(?:\"[^\"]*\")|"
            r"(?:'[^']*'))", joined, re.M | re.S):
        try:
            out[key] = ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            # silently falling back to defaults would make the same bad
            # config lint differently per Python version (tomllib raises)
            raise ValueError(
                f"unparseable value for {key!r}: {raw.strip()!r}") from None
    return out


def load_config(pyproject_path: Optional[str]) -> dict:
    cfg = dict(DEFAULTS)
    if pyproject_path is None or not os.path.isfile(pyproject_path):
        return cfg
    with open(pyproject_path, "rb") as f:
        raw = f.read()
    section = {}
    try:
        import tomllib  # py311+

        section = tomllib.loads(raw.decode()).get("tool", {}).get(
            "jaxlint", {})
    except ModuleNotFoundError:
        section = _parse_section_fallback(raw.decode())
    for key in DEFAULTS:
        if key in section:
            cfg[key] = section[key]
    cfg["root"] = os.path.dirname(os.path.abspath(pyproject_path))
    return cfg


def resolve_paths(cfg: dict, explicit: List[str]) -> List[str]:
    """CLI paths win; otherwise config paths, relative to the config root."""
    if explicit:
        return explicit
    root = cfg.get("root", os.getcwd())
    return [os.path.join(root, p) for p in cfg["paths"]]
