"""deep_vision_tpu: a TPU-native (JAX/XLA/pjit/Pallas) computer-vision training framework.

A from-scratch rebuild of the capabilities of the `deep-vision` model zoo
(reference: darveenvijayan/deep-vision) as one layered library:

- ``core``      mesh-aware train state, rng, dtypes, checkpoint, metrics
- ``parallel``  device mesh + sharding rules, ring attention, collectives
- ``nn``        flax modules shared by all models (conv/bn/lrn/depthwise/...)
- ``ops``       vectorized vision ops (iou, nms, anchors, heatmaps)
- ``losses``    task losses (ce+aux, yolo, heatmap mse, focal+l1, gan)
- ``models``    the model zoo (lenet ... cyclegan)
- ``data``      record IO, dataset schemas, augmentations, device feed
- ``train``     the single Trainer (+ GAN variant), optimizers, schedules
- ``configs``   named experiment registry + CLI entry
"""

__version__ = "0.1.0"
