"""Double-buffered DEVICE prefetch: H2D transfer overlapped with compute.

The host-thread prefetch in data/pipeline.py hides decode/augment latency,
but the batch still crosses PCIe/ICI *inside* the step: Trainer.train_step
called `shard_batch` (a `jax.device_put`) on the critical path, so every
step paid the H2D transfer before it could dispatch — the ~5% wall-vs-device
gap BENCH_r03 measured. This module moves the device_put OFF the critical
path: a producer thread pads/shards the NEXT batch(es) onto the mesh while
the device executes the current step. jax's async dispatch makes the
transfer itself non-blocking, so a depth-2 buffer is enough for full
overlap; by the time the training loop asks for the batch, its buffers are
on (or streaming onto) the accelerator and `data_wait` collapses to a queue
get.

Observability rides the existing registry, next to the host-prefetch
gauges (data_prefetch_* in pipeline.py):

    device_prefetch_depth          placed batches ready at the consumer get
    device_prefetch_starved_total  gets that found the buffer empty
    device_prefetch_batches_total  placed batches handed to the step loop

With `group > 1` (the scan-multistep Trainer) the producer coalesces G host
batches into one stacked device batch per dispatch; a short tail (fewer
than G batches left in the epoch) is emitted as single-step items so the
stacked executable never sees a ragged shape (no recompiles).
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional


class PlacedBatch:
    """A device-resident batch + the host-side metadata the loop needs
    without a device fetch: `data` (the sharded pytree), `n` (valid
    examples, padding excluded), `group` (microsteps this item carries —
    1, or the multistep G for a stacked superstep batch)."""

    __slots__ = ("data", "n", "group")

    def __init__(self, data, n: int, group: int = 1):
        self.data = data
        self.n = int(n)
        self.group = int(group)


class DevicePrefetcher:
    """Wrap a host-batch iterable; yield `PlacedBatch`es placed ahead of
    consumption.

    place_one(batch)    -> PlacedBatch(group=1)
    place_group(batches)-> PlacedBatch(group=len(batches)); required when
                           group > 1, used for full groups only.

    Placement runs on the producer thread — `jax.device_put` dispatch is
    thread-safe and asynchronous, so the transfer overlaps both the host
    pipeline and device compute.
    """

    def __init__(self, place_one: Callable, depth: int = 2,
                 group: int = 1, place_group: Optional[Callable] = None,
                 name: str = "train", registry=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if group > 1 and place_group is None:
            raise ValueError("group > 1 requires place_group")
        self.place_one = place_one
        self.place_group = place_group
        self.depth = int(depth)
        self.group = max(1, int(group))
        self.name = name
        if registry is None:
            from deep_vision_tpu.obs.registry import get_registry

            registry = get_registry()
        labels = {"loader": name}
        self._g_depth = registry.gauge(
            "device_prefetch_depth",
            "device-placed batches ready when the consumer asked",
            labels=labels)
        self._c_starved = registry.counter(
            "device_prefetch_starved_total",
            "consumer gets that found no placed batch ready",
            labels=labels)
        self._c_batches = registry.counter(
            "device_prefetch_batches_total",
            "device-placed batches yielded", labels=labels)

    def __call__(self, source: Iterable) -> Iterator[PlacedBatch]:
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        sentinel = object()
        stop = threading.Event()
        err: list = []

        def put(item) -> bool:
            # bounded put that keeps observing stop: an abandoned consumer
            # (preemption broke the loop) leaves the queue full, and a
            # plain put would pin this thread forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                pending = []
                for batch in source:
                    pending.append(batch)
                    if len(pending) < self.group:
                        continue
                    if self.group > 1:
                        placed = self.place_group(pending)
                    else:
                        placed = self.place_one(pending[0])
                    pending = []
                    if not put(placed):
                        return
                # tail: short of a full group — single-step items so the
                # stacked executable never compiles a ragged shape
                for batch in pending:
                    if not put(self.place_one(batch)):
                        return
            except BaseException as e:  # surfaced at the consumer's get
                err.append(e)
            finally:
                put(sentinel)

        t = threading.Thread(target=producer, daemon=True,
                             name=f"device-prefetch-{self.name}")
        t.start()
        first = True
        try:
            while True:
                depth = q.qsize()
                item = q.get()
                if item is sentinel:
                    break
                self._g_depth.set(depth)
                # the first get races the producer's warm-up fill and would
                # stamp phantom starvation on every healthy epoch
                if depth == 0 and not first:
                    self._c_starved.inc()
                first = False
                self._c_batches.inc()
                yield item
        finally:
            stop.set()
            try:  # unblock a producer stuck in put()
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5)
        if err:
            raise err[0]
