"""Shard smoke: declarative sharding must be real, cheap, and loud.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/shard_smoke.py \
        [--workdir artifacts/shard_smoke]

The CI teeth behind parallel/shardmap.py (`make shard-smoke`, a `make
verify` prerequisite), on a forced 8-device virtual-CPU mesh
(data=4, model=2):

  A. vit        a depth-2 ViT trains GENUINELY SHARDED multi-step
                (Trainer(sharding_rules=VIT_RULES, multistep=2,
                device_prefetch=2)): params/moments placed per the
                table (model-axis specs on device, shards smaller than
                the global array), `tp_sharded_leaves` at or above the
                family's declared floor AND above the infer_tp_sharding
                heuristic's count, a typed `sharding_resolved` event in
                the journal, and ZERO recompiles across the second
                epoch (superstep + epoch-tail single step both warmed).
  B. moe        the V-MoE variant (experts stacked on the leading E
                axis) with MOE_RULES: expert weights sharded over the
                MODEL axis, router replicated, same floor/heuristic/
                zero-recompile assertions.
  C. gutted     a deliberately gutted table (catch-all only, floor
                kept) must FAIL AT STARTUP with a
                ShardingCoverageError that NAMES the replicated leaf
                paths — the 108 -> 34 regression signature, now
                debuggable from the message; and a table missing its
                catch-all must refuse at construction.
  D. scaling    tools/scaling.py measures throughput at data={1,2,4,8}
                sub-meshes (the `bench.py --multichip` measurement) and
                the rows land as a typed `bench` event, each carrying
                the compiled step's predicted comm bytes next to the
                measured step-time delta vs the 1-device baseline.
  E. artifacts  journals pass `check_journal --strict`
                (sharding_resolved schema included) and obs_report
                renders the sharding section with rule hit counts and
                the scaling-efficiency rows.

Exit status 0 = every contract held; 1 = something broke.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List

# the 8-device virtual mesh MUST be configured before jax's first
# backend init (conftest.py does the same for the test tier)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


class Failures:
    def __init__(self):
        self.errors: List[str] = []

    def check(self, ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}")
        if not ok:
            self.errors.append(what)
        return ok


def _batches(n: int, batch: int, classes: int, seed: int = 0):
    import numpy as np

    rng = np.random.RandomState(seed)
    return [
        {"image": rng.rand(batch, 16, 16, 3).astype(np.float32),
         "label": rng.randint(0, classes, (batch,)).astype(np.int32)}
        for _ in range(n)
    ]


def _train_phase(f: Failures, name: str, model, rules, journal_path: str):
    """One sharded multi-step training run; returns the journal events."""
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.obs.journal import RunJournal
    from deep_vision_tpu.obs.stepclock import recompile_count
    from deep_vision_tpu.parallel.mesh import create_mesh
    from deep_vision_tpu.parallel.shardmap import HeuristicRules
    from deep_vision_tpu.train.optimizers import build_optimizer
    from deep_vision_tpu.train.trainer import Trainer
    from tools.smoke_util import read_jsonl

    mesh = create_mesh(data=4, model=2)
    journal = RunJournal(journal_path, kind="shard_smoke")
    journal.manifest(config={"tool": "shard_smoke", "phase": name})
    tx = build_optimizer("sgd", learning_rate=0.05, momentum=0.9)
    trainer = Trainer(
        model, tx, classification_loss_fn,
        jnp.ones((2, 16, 16, 3), jnp.float32), mesh=mesh,
        journal=journal, sharding_rules=rules,
        multistep=2, device_prefetch=2,
    )
    # 9 batches = 4 supersteps + 1 tail single step per epoch, so BOTH
    # executables compile in epoch 0 and epoch 1 must compile nothing
    data = _batches(9, batch=8, classes=8)
    trainer.fit(lambda: data, epochs=1)
    warm = recompile_count()
    trainer.fit(lambda: data, epochs=1)
    f.check(recompile_count() - warm == 0,
            f"{name}: zero recompiles across the post-warmup epoch "
            f"(delta {recompile_count() - warm})")

    # genuinely sharded: the table's model-axis layout is on the device,
    # with per-device shards smaller than the global array
    probe = trainer.state.params
    leaf = None
    for path in (("ViTBlock_0", "Attention_0", "qkv", "kernel"),):
        node = probe
        try:
            for k in path:
                node = node[k]
            leaf = node
        except (KeyError, TypeError):
            pass
    f.check(leaf is not None, f"{name}: probe leaf found")
    if leaf is not None:
        spec_axes = {a for e in leaf.sharding.spec
                     for a in ((e,) if isinstance(e, str) else (e or ()))}
        shard_size = leaf.addressable_shards[0].data.size
        f.check("model" in spec_axes,
                f"{name}: qkv kernel sharded over the model axis "
                f"({leaf.sharding.spec})")
        f.check(shard_size * 2 == leaf.size,
                f"{name}: per-device shard is half the global array "
                f"({shard_size} vs {leaf.size})")

    # coverage: at/above the family floor via the TABLE, and above the
    # size heuristic the table replaces
    _, table_report = rules.resolve(trainer.state, mesh)
    _, heur_report = HeuristicRules(min_size=1024).resolve(
        trainer.state, mesh)
    floor = rules.floor_for(mesh)
    f.check(table_report["sharded_leaves"] >= floor > 0,
            f"{name}: tp_sharded_leaves {table_report['sharded_leaves']} "
            f">= declared floor {floor}")
    f.check(table_report["sharded_leaves"] > heur_report["sharded_leaves"],
            f"{name}: table shards more than the heuristic "
            f"({table_report['sharded_leaves']} vs "
            f"{heur_report['sharded_leaves']})")
    f.check(bool(jnp.isfinite(
        trainer.state.params["Dense_0"]["kernel"]).all()),
            f"{name}: params finite after sharded training")
    # perf attribution (obs/perfwatch): the compiled step's collective
    # inventory must NAME the partitioner's comm — a sharded step whose
    # HLO shows zero all-reduces isn't reducing gradients at all. (The
    # byte-vs-grad-tree equality check lives in perf_gate's smoke on the
    # pure-DP mesh, where no tensor-parallel activation collectives mix
    # into the bill.) Runs AFTER the recompile assertions: the probe's
    # non-donating AOT lowering owns one compile of its own.
    prof = trainer.profile_step(data[0])
    f.check(prof is not None and prof["collective_bytes"] > 0
            and any(c["kind"] == "all-reduce" for c in prof["collectives"]),
            f"{name}: compiled-step collective inventory names its "
            f"all-reduces ({0 if prof is None else prof['collective_bytes']}"
            " bytes)")
    trainer.close()
    journal.close()
    events = read_jsonl(journal_path)
    resolved = [e for e in events if e.get("event") == "sharding_resolved"]
    f.check(len(resolved) == 1
            and resolved[0].get("model") == rules.name
            and resolved[0].get("sharded_leaves", -1) >= floor,
            f"{name}: one sharding_resolved event with the table's "
            "ledger")
    steps = [e for e in events if e.get("event") == "step"]
    f.check(any(e.get("multistep") == 2 for e in steps),
            f"{name}: superstep dispatches journaled with multistep=2")
    profiles = [e for e in events if e.get("event") == "perf_profile"]
    f.check(any(e.get("collective_count", 0) > 0 for e in profiles),
            f"{name}: typed perf_profile event journaled with the "
            "collective roll-up")
    return events


def _gutted_phase(f: Failures):
    import jax.numpy as jnp

    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models.vit import ViT
    from deep_vision_tpu.parallel.mesh import (
        ShardingCoverageError,
        create_mesh,
    )
    from deep_vision_tpu.parallel.shardmap import (
        ShardingRuleError,
        ShardingRules,
    )
    from deep_vision_tpu.train.optimizers import build_optimizer
    from deep_vision_tpu.train.trainer import Trainer

    mesh = create_mesh(data=4, model=2)
    model = ViT(depth=2, dim=16, num_heads=2, patch=8, num_classes=8)
    tx = build_optimizer("sgd", learning_rate=0.05, momentum=0.9)
    gutted = ShardingRules(name="vit", rules=(("*", ()),), min_sharded=12)
    err = None
    try:
        Trainer(model, tx, classification_loss_fn,
                jnp.ones((2, 16, 16, 3), jnp.float32), mesh=mesh,
                sharding_rules=gutted)
    except ShardingCoverageError as e:
        err = str(e)
    f.check(err is not None,
            "gutted table fails AT STARTUP (Trainer construction)")
    f.check(err is not None and "replicated float leaves" in err
            and "ViTBlock" in err,
            "gutted-table failure NAMES the replicated leaf paths")
    try:
        # jaxlint: disable=DV205 -- deliberately malformed test subject
        ShardingRules(name="bad", rules=(
            ("*.Attention_*.qkv.kernel", (None, None, "model", None)),))
        f.check(False, "missing catch-all refused at construction")
    except ShardingRuleError:
        f.check(True, "missing catch-all refused at construction")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/shard_smoke")
    args = p.parse_args(argv)
    import shutil

    shutil.rmtree(args.workdir, ignore_errors=True)
    os.makedirs(args.workdir, exist_ok=True)
    f = Failures()

    import jax

    # the env var alone is read too early when a sitecustomize imported
    # jax at interpreter startup (conftest.py precedent): pin the config
    # too, then hard-check the forced device count actually took
    jax.config.update("jax_platforms", "cpu")
    n = len(jax.devices())
    f.check(n == 8, f"forced 8-device CPU mesh up (have {n})")

    from deep_vision_tpu.models.vit import ViT
    from deep_vision_tpu.parallel.shardmap import MOE_RULES, VIT_RULES

    print("-- phase A: ViT sharded multistep training --")
    vit_journal = os.path.join(args.workdir, "vit_journal.jsonl")
    vit_events = _train_phase(
        f, "vit", ViT(depth=2, dim=16, num_heads=2, patch=8, num_classes=8),
        VIT_RULES, vit_journal)

    print("-- phase B: MoE sharded multistep training --")
    moe_journal = os.path.join(args.workdir, "moe_journal.jsonl")
    moe_events = _train_phase(
        f, "moe", ViT(depth=2, dim=16, num_heads=2, patch=8, num_classes=8,
                      num_experts=4),
        MOE_RULES, moe_journal)
    moe_resolved = [e for e in moe_events
                    if e.get("event") == "sharding_resolved"]
    if moe_resolved:
        hits = moe_resolved[0].get("rules", {})
        f.check(hits.get("*.MoeMlp_*.w1", 0) > 0
                and hits.get("*.MoeMlp_*.router", 0) > 0,
                "moe: expert weights sharded, router replicated "
                "(rule hits journaled)")

    print("-- phase C: gutted table fails at startup --")
    _gutted_phase(f)

    print("-- phase D: scaling efficiency at data={1,2,4,8} --")
    from deep_vision_tpu.obs.journal import RunJournal
    from deep_vision_tpu.tools.scaling import (
        format_rows,
        measure_scaling,
        scaling_result,
    )

    bench_journal = os.path.join(args.workdir, "bench_journal.jsonl")
    journal = RunJournal(bench_journal, kind="shard_smoke")
    journal.manifest(config={"tool": "shard_smoke", "phase": "scaling"})
    rows = measure_scaling(batch_per_device=4, steps=4, warmup=1)
    print(format_rows(rows))
    journal.bench("multichip_scaling", scaling_result(rows))
    journal.close()
    f.check(len(rows) == 4 and [r["data"] for r in rows] == [1, 2, 4, 8],
            "scaling rows cover data={1,2,4,8}")
    f.check(all(r["examples_per_sec"] > 0 for r in rows)
            and rows[0]["efficiency"] == 1.0,
            "scaling rows well-formed (positive throughput, 1-device "
            "anchor at 1.0)")
    f.check(rows[0]["predicted_comm_bytes"] == 0
            and all(r["predicted_comm_bytes"] > 0 for r in rows[1:]),
            "scaling rows carry the predicted comm bill (0 at data=1, "
            "positive on every multi-device sub-mesh)")

    print("-- phase E: artifacts validate --")
    from tools.check_journal import check_journal

    for path in (vit_journal, moe_journal, bench_journal):
        errs = check_journal(path, strict=True)
        f.check(not errs, f"check_journal --strict {os.path.basename(path)}"
                + (f": {errs[:2]}" if errs else ""))
    from tools.obs_report import render, summarize_run
    from tools.smoke_util import read_jsonl

    text = render(summarize_run(read_jsonl(vit_journal)))
    f.check("sharding vit" in text and "rule" in text,
            "obs_report renders the sharding section with rule hits")
    text_b = render(summarize_run(read_jsonl(bench_journal)))
    f.check("scaling data=8" in text_b and "efficiency" in text_b,
            "obs_report renders the scaling-efficiency rows")

    if f.errors:
        print(f"\nshard-smoke: {len(f.errors)} FAILURE(S)")
        for e in f.errors:
            print("  - " + e)
        return 1
    print("\nshard-smoke: all contracts held")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
