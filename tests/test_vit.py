"""ViT / V-MoE family: shapes, gradients, aux-loss plumbing, trainability."""
import jax
import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.losses.classification import classification_loss_fn
from deep_vision_tpu.models import get_model
from deep_vision_tpu.models.vit import ViT
import pytest

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)


def _tiny(num_experts=0):
    return ViT(depth=2, dim=32, num_heads=2, patch=8, num_classes=10,
               num_experts=num_experts)


def test_vit_forward_shapes():
    model = _tiny()
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_vit_train_mode_dense_returns_logits_only():
    model = _tiny()
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    out = model.apply(variables, x, train=True)
    assert not isinstance(out, tuple)


def test_vmoe_aux_loss_plumbed_through_classification_loss():
    model = _tiny(num_experts=4)
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    out = model.apply(variables, x, train=True)
    assert isinstance(out, tuple) and "moe_aux" in out[1]
    batch = {"label": jnp.array([1, 2])}
    loss, metrics = classification_loss_fn(out, batch)
    assert "moe_aux" in metrics
    # aux >= 1 by construction; the weighted sum must exceed plain CE
    plain, _ = classification_loss_fn(out[0], batch)
    assert float(loss) > float(plain)
    assert float(metrics["moe_aux"]) >= 1.0 - 1e-4
    # eval mode: logits only (no aux tuple to confuse inference paths)
    assert not isinstance(model.apply(variables, x, train=False), tuple)


def test_vmoe_gradients_flow_to_experts_and_router():
    model = _tiny(num_experts=4)
    x = jnp.asarray(np.random.RandomState(1).rand(2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    batch = {"label": jnp.array([3, 7])}

    def loss_fn(params):
        out = model.apply({"params": params}, x, train=True)
        return classification_loss_fn(out, batch)[0]

    grads = jax.grad(loss_fn)(variables["params"])
    flat = jax.tree_util.tree_leaves_with_path(grads)
    moe_grads = [
        (jax.tree_util.keystr(p), g) for p, g in flat if "MoeMlp" in str(p)
    ]
    assert moe_grads, "no MoE params found"
    # the router always gets gradient (via prob weighting + aux loss)
    router = [g for p, g in moe_grads if "router" in p]
    assert router and all(float(jnp.abs(g).max()) > 0 for g in router)


def test_moemlp_matches_moe_ffn_dense():
    """MoeMlp (in-model dense routing) must equal parallel.moe.moe_ffn_dense
    given the same weights — the contract that lets a vmoe checkpoint deploy
    expert-parallel via moe_ffn unchanged. Biases forced nonzero: the
    regression this guards is unselected experts leaking gelu(b1[e])."""
    from deep_vision_tpu.models.vit import MoeMlp
    from deep_vision_tpu.parallel.moe import moe_ffn_dense

    rng = np.random.RandomState(0)
    b, t, d, h, e = 2, 8, 16, 32, 4
    x = jnp.asarray(rng.randn(b, t, d), jnp.float32)
    module = MoeMlp(num_experts=e, hidden=h)
    variables = module.init(jax.random.PRNGKey(0), x)
    params = dict(variables["params"])
    params["b1"] = jnp.asarray(rng.randn(e, h), jnp.float32)
    params["b2"] = jnp.asarray(rng.randn(e, d), jnp.float32)
    out, gates = module.apply({"params": params}, x)
    ref = moe_ffn_dense(
        params["router"],
        {k: params[k] for k in ("w1", "b1", "w2", "b2")},
        x.reshape(b * t, d),
    )
    np.testing.assert_allclose(
        np.asarray(out).reshape(b * t, d), np.asarray(ref),
        rtol=1e-5, atol=1e-6,
    )


def test_remat_is_exact_and_checkpoint_compatible():
    """remat=True recomputes activations in backward: identical outputs AND
    gradients from the SAME param tree (variable paths pinned, so
    checkpoints move freely between the two memory modes)."""
    x = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3), jnp.float32)
    a = _tiny()
    b = ViT(depth=2, dim=32, num_heads=2, patch=8, num_classes=10,
            remat=True)
    va = a.init(jax.random.PRNGKey(0), x, train=False)

    def loss(model, p):
        return jnp.sum(model.apply({"params": p}, x, train=False) ** 2)

    np.testing.assert_array_equal(
        np.asarray(a.apply(va, x, train=False)),
        np.asarray(b.apply(va, x, train=False)),
    )
    ga = jax.grad(lambda p: loss(a, p))(va["params"])
    gb = jax.grad(lambda p: loss(b, p))(va["params"])
    for u, v in zip(jax.tree_util.tree_leaves(ga),
                    jax.tree_util.tree_leaves(gb)):
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v))


def test_vit_registry_and_config():
    from deep_vision_tpu.configs import get_config

    model = get_model("vit_s16", num_classes=10)
    assert model.dim == 384
    cfg = get_config("vmoe_s16")
    assert cfg.model == "vmoe_s16"
    assert cfg.schedule["kind"] == "cosine"


def test_pipeline_vit_trunk_matches_sequential():
    """The GPipe-pipelined ViT trunk must equal running the blocks in order."""
    from deep_vision_tpu.models.vit import ViTBlock, pipeline_vit_trunk
    from deep_vision_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(data=2, model=4)
    model = ViT(depth=8, dim=32, num_heads=2, patch=8, num_classes=10)
    x_img = jnp.asarray(
        np.random.RandomState(0).rand(4, 32, 32, 3), jnp.float32
    )
    variables = model.init(jax.random.PRNGKey(0), x_img, train=False)
    tokens = jnp.asarray(
        np.random.RandomState(1).randn(4, 16, 32), jnp.float32
    )
    out = pipeline_vit_trunk(model, variables, tokens, mesh,
                             num_microbatches=2)
    block = ViTBlock(model.num_heads, model.mlp_ratio)
    ref = tokens
    for i in range(model.depth):
        ref, _ = block.apply(
            {"params": variables["params"][f"ViTBlock_{i}"]}, ref
        )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_vit_short_training_reduces_loss():
    # 1-patch-class toy problem: ViT must fit it in a few steps
    import optax

    model = _tiny()
    rng = np.random.RandomState(0)
    x = rng.rand(64, 32, 32, 3).astype(np.float32) * 0.1
    y = rng.randint(0, 4, size=64)
    for i, l in enumerate(y):
        r, c = divmod(l, 2)
        x[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16, :] += 0.9
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:2]),
                           train=True)
    tx = optax.adam(1e-3)
    params = variables["params"]
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def lf(p):
            logits = model.apply({"params": p}, jnp.asarray(x), train=True)
            return classification_loss_fn(logits, {"label": jnp.asarray(y)})[0]

        loss, g = jax.value_and_grad(lf)(params)
        updates, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.5, (first, float(loss))
