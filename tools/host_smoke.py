"""Host-churn smoke: lose a host mid-epoch, keep the run (`make host-smoke`).

The executable proof behind resilience/rendezvous.py — the multi-host
half of the elastic arc, the way chaos-smoke phase 7 proved the
single-host half. Three REAL processes (forced 2-device CPU worlds,
like chaos phase 7) join a file-backed rendezvous, initialize
jax.distributed at world 3 (6-device global mesh, gloo CPU
collectives), and train a real Trainer with checkpoints. Then the
parent SIGKILLs one host mid-epoch and asserts the contract ROADMAP
item 1 demands:

  1. the survivors DETECT the loss within the heartbeat deadline —
     typed `host_lost` events, no indefinite collective hang, no
     watchdog dump;
  2. they re-rendezvous at generation 1 with world 2 (typed
     `world_resized{from:3, to:2}`), re-enter jax.distributed at the
     new size (process-image replacement — see the rendezvous module
     docstring for why a rank wedged in a dead collective cannot
     re-init in place), and rebuild the 4-device mesh;
  3. training RESUMES at the exact checkpointed step (first post-resume
     step event == resume_step + 1, losses continuing), riding the
     PR 10 cross-mesh restore;
  4. the input pipeline re-derives a disjoint+covering host-shard
     assignment over the survivors (typed `data_reshard`);
  5. every surviving host's journal passes `check_journal --strict`,
     the locksmith is armed throughout with ZERO lock-order violations,
     and `obs_report` renders the membership timeline;
  6. the goodput ledger (obs/goodput.py) covers every wall-clock second
     within 2%, bills the kill -> first-post-resize-step window to the
     named failure buckets (host_loss_recovery / rendezvous_wait /
     checkpoint / compile, not overhead), and lands `goodput_frac` as a
     MAD-gated row in artifacts/perf_ledger.jsonl.

Worker mode (`--host N`) is the host agent: rendezvous first (pure
stdlib, so a re-exec'd survivor re-arms its lease BEFORE paying the
jax import), then jax, then Trainer.fit under HostSupervisor; a
WorldResized from fit re-execs this same process into the next
generation. Exit status 0 = every contract held; 1 = one is broken.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from deep_vision_tpu.core import knobs  # noqa: E402
from tools.smoke_util import read_jsonl  # noqa: E402

HOSTS = 3
DEVICES_PER_HOST = 2
GLOBAL_BS = 12           # divisible by 3 hosts, 2 hosts, and both meshes
STEPS_PER_EPOCH = 8
EPOCHS = 8               # CPU steps are ~100ms: enough epochs that the
#                          parent's kill window (post-checkpoint, mid-
#                          epoch) is seconds wide, with real post-resume
#                          training left to prove losses continue
VICTIM = 1               # a MIDDLE host: the survivor behind it must
#                          re-rank (h2: rank 2 -> 1), exercising the
#                          dense re-assignment, not just a tail trim
HEARTBEAT_S = 0.5
LEASE_S = 3.0
#: detection must beat this bound by construction (lease + one poll +
#: slack); a hang would instead ride to the subprocess timeout
DETECT_BOUND_S = 30.0


# -- worker: the host agent ----------------------------------------------------

def worker_main(args) -> int:
    host = f"h{args.host}"
    workdir = args.workdir
    if knobs.get_flag("DVT_HOST_SMOKE_DEBUG"):
        import faulthandler

        faulthandler.dump_traceback_later(
            20, repeat=True,
            file=open(os.path.join(workdir, f"stacks_{host}.txt"), "w"))
    # rendezvous BEFORE jax: stdlib-only, so the lease is armed within
    # ~100ms of process start — a re-exec'd survivor's absence stays far
    # inside the other survivors' lease deadline
    from deep_vision_tpu.resilience.rendezvous import (
        ENV_GENERATION,
        HostSupervisor,
        Rendezvous,
        WorldResized,
    )

    rdzv = Rendezvous(
        os.path.join(workdir, "rdzv"), host,
        heartbeat_s=HEARTBEAT_S, lease_s=LEASE_S, poll_s=0.02,
        client_version="host-smoke-1",  # identical fleet: handshake passes
    )
    attached = knobs.get_int(ENV_GENERATION) is not None
    if attached:
        view = rdzv.attach(timeout_s=300)
    else:
        view = rdzv.join(expect_hosts=HOSTS, timeout_s=180)
    print(f"[{host}] generation {view.generation} world {view.hosts} "
          f"rank {view.rank}", flush=True)

    # now the heavy half: jax at this generation's world size
    import numpy as np  # noqa: E402

    from deep_vision_tpu.core import CheckpointManager
    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.obs import locksmith
    from deep_vision_tpu.obs.journal import RunJournal
    from deep_vision_tpu.parallel import multihost as mh
    from deep_vision_tpu.train import Trainer, build_optimizer

    mh.install_world(view, rdzv)
    mh.initialize_from_world(view)
    import jax
    import jax.numpy as jnp

    mesh = mh.global_mesh()
    # one journal file per HOST for its whole life: append mode carries
    # it across the re-exec (per_process=False — the generation changes
    # this host's rank, and a rank-suffixed path would strand the
    # pre-resize history in a terminal-less file --strict rejects)
    journal = RunJournal(os.path.join(workdir, f"journal_{host}.jsonl"),
                         per_process=False, writer=True, kind="host-smoke")
    locksmith.arm_from_env(journal=journal)
    journal.write("note", note="mesh_shape", generation=view.generation,
                  mesh_shape={str(k): int(v) for k, v in mesh.shape.items()},
                  world=view.world_size, rank=view.rank)
    sup = HostSupervisor(rdzv, journal=journal)

    # identical deterministic dataset on every host; each host feeds its
    # generation-derived slice of every global batch
    rng = np.random.RandomState(0)
    n = GLOBAL_BS * STEPS_PER_EPOCH
    images = rng.rand(n, 32, 32, 1).astype(np.float32) * 0.1
    labels = rng.randint(0, 4, size=n)
    for i, lab in enumerate(labels):
        r, c = divmod(int(lab), 2)
        images[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16, 0] += 0.9
    labels = labels.astype(np.int32)

    trainer = Trainer(
        get_model("lenet5", num_classes=4),
        build_optimizer("adam", 1e-3),
        classification_loss_fn,
        sample_input=jnp.zeros((GLOBAL_BS // view.world_size, 32, 32, 1)),
        mesh=mesh,
        checkpoint_manager=CheckpointManager(os.path.join(workdir, "ckpt"),
                                             journal=journal),
        journal=journal,
        host_supervisor=sup,
    )

    def train_data():
        rank, nh = mh.host_shard()  # generation-aware
        per = mh.per_host_batch_size(GLOBAL_BS)
        for i in range(STEPS_PER_EPOCH):
            lo = i * GLOBAL_BS + rank * per
            local = {"image": images[lo:lo + per],
                     "label": labels[lo:lo + per]}
            yield mh.form_global_array(local, mesh)

    start_epoch = 0
    if attached and trainer.ckpt.latest_step() is not None:
        start_epoch = trainer.resume()
        print(f"[{host}] resumed at step {int(trainer.state.step)}, "
              f"epoch {start_epoch}", flush=True)
    # the PRIMARY detector: a peer dying mid-step wedges this host's
    # next jit dispatch in C++ before any in-band fence runs — the
    # watchdog thread journals/resizes/re-execs regardless
    sup.arm_watchdog()
    try:
        trainer.fit(train_data, epochs=EPOCHS, start_epoch=start_epoch,
                    preemption_poll_every=4)
    except WorldResized as wr:
        # fit already journaled host_lost/world_resized/data_reshard;
        # re-enter the new generation with a fresh process image (the
        # wedged jax world dies with this one)
        print(f"[{host}] world resized -> generation "
              f"{wr.view.generation}, re-exec", flush=True)
        trainer.close()
        sup.reexec(wr.view)  # never returns
    sup.disarm_watchdog()  # a completing run must not be exec'd out
    # from under its own teardown
    final_step = int(trainer.state.step)
    trainer.close()
    journal.write("note", note="final_step", step=final_step)
    journal.close()
    # rendezvous BEFORE leaving: a survivor finishing a beat earlier
    # must not read its peer's clean departure as a lost host
    rdzv.barrier("shutdown", timeout_s=120)
    rdzv.leave()
    print(f"[{host}] DONE step={final_step}", flush=True)
    return 0


# -- parent: orchestration + assertions ----------------------------------------

def check_journal_strict(path: str) -> bool:
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_journal.py"),
         path, "--strict"],
        cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
    ).returncode
    return rc == 0


class Failures:
    def __init__(self):
        self.errors: List[str] = []

    def check(self, ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}")
        if not ok:
            self.errors.append(what)
        return ok


def spawn_host(i: int, workdir: str):
    env = dict(
        os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count"
                  f"={DEVICES_PER_HOST}",
        DVT_LOCKSMITH="1",
    )
    env.pop("DVT_RDZV_GENERATION", None)
    log = open(os.path.join(workdir, f"host{i}.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--host", str(i),
         "--workdir", workdir],
        cwd=ROOT, env=env, stdout=log, stderr=subprocess.STDOUT,
    )
    return proc, log


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/host_smoke")
    p.add_argument("--host", type=int, default=None,
                   help=argparse.SUPPRESS)  # worker mode
    args = p.parse_args(argv)
    if args.host is not None:
        return worker_main(args)

    import shutil

    workdir = os.path.abspath(args.workdir)
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    f = Failures()
    journals = {i: os.path.join(workdir, f"journal_h{i}.jsonl")
                for i in range(HOSTS)}

    print(f"host-smoke: world {HOSTS} x {DEVICES_PER_HOST} CPU devices, "
          f"SIGKILL h{VICTIM} mid-epoch, survivors must re-rendezvous "
          f"at world {HOSTS - 1}")
    procs: Dict[int, tuple] = {}
    try:
        for i in range(HOSTS):
            procs[i] = spawn_host(i, workdir)

        # -- phase 1: reach live CHECKPOINTED training at world 3 -------
        deadline = time.time() + 420
        def ready() -> bool:
            for i in range(HOSTS):
                evs = read_jsonl(journals[i])
                if not any(e.get("event") == "checkpoint" and e.get("saved")
                           for e in evs):
                    return False
                steps = [e["step"] for e in evs if e.get("event") == "step"]
                if not steps or max(steps) < STEPS_PER_EPOCH + 2:
                    return False
            return True

        while time.time() < deadline and not ready():
            if any(pr.poll() is not None for pr, _ in procs.values()):
                break
            time.sleep(0.2)
        alive = all(pr.poll() is None for pr, _ in procs.values())
        f.check(alive and ready(),
                "world-3 training is live past an epoch-0 checkpoint "
                "and into epoch 1 on every host")
        if not (alive and ready()):
            raise RuntimeError("never reached the kill window")

        # -- phase 2: SIGKILL the victim mid-epoch ----------------------
        kill_ts = time.time()
        os.kill(procs[VICTIM][0].pid, signal.SIGKILL)
        print(f"  SIGKILLed h{VICTIM} (pid {procs[VICTIM][0].pid})")

        survivors = [i for i in range(HOSTS) if i != VICTIM]
        rcs = {}
        for i in survivors:
            pr, _ = procs[i]
            try:
                rcs[i] = pr.wait(timeout=420)
            except subprocess.TimeoutExpired:
                pr.kill()
                rcs[i] = "timeout"
        procs[VICTIM][0].wait()
        for i in survivors:
            f.check(rcs[i] == 0,
                    f"survivor h{i} completed the run (rc={rcs[i]}) — "
                    "no hang, no watchdog death")

        # -- phase 3: the journaled contract ----------------------------
        resume_steps = set()
        for i in survivors:
            evs = read_jsonl(journals[i])
            lost = [e for e in evs if e.get("event") == "host_lost"]
            f.check(len(lost) >= 1
                    and lost[0].get("host") == f"h{VICTIM}"
                    and lost[0].get("generation") == 0,
                    f"h{i} journaled typed host_lost for h{VICTIM} at "
                    "generation 0")
            if lost:
                latency = float(lost[0].get("ts", 1e18)) - kill_ts
                f.check(0 <= latency <= DETECT_BOUND_S,
                        f"h{i} detected the loss within the heartbeat "
                        f"deadline ({latency:.1f}s <= {DETECT_BOUND_S}s)")
            resized = [e for e in evs if e.get("event") == "world_resized"]
            ok_resize = (len(resized) == 1
                         and resized[0].get("from") == HOSTS
                         and resized[0].get("to") == HOSTS - 1
                         and resized[0].get("generation") == 1
                         and isinstance(resized[0].get("resume_step"), int)
                         and resized[0]["resume_step"] > 0)
            f.check(ok_resize,
                    f"h{i} journaled world_resized 3 -> 2 at generation 1 "
                    f"with a real resume_step ({resized})")
            if not ok_resize:
                continue
            resume_step = resized[0]["resume_step"]
            resume_steps.add(resume_step)
            # the checkpointed step the resize promised must be the one
            # training continues FROM: first post-resize step == S + 1
            idx = evs.index(resized[0])
            post_steps = [e["step"] for e in evs[idx:]
                          if e.get("event") == "step"]
            f.check(bool(post_steps)
                    and post_steps[0] == resume_step + 1,
                    f"h{i} resumed at the exact checkpointed step "
                    f"(first post-resize step {post_steps[:1]} == "
                    f"{resume_step + 1}); losses continue, not restart")
            f.check(bool(post_steps)
                    and max(post_steps) == EPOCHS * STEPS_PER_EPOCH,
                    f"h{i} finished the full run at world 2 (last step "
                    f"{max(post_steps) if post_steps else None} == "
                    f"{EPOCHS * STEPS_PER_EPOCH})")
            meshes = [e.get("mesh_shape", {}).get("data") for e in evs
                      if e.get("event") == "note"
                      and e.get("note") == "mesh_shape"]
            f.check(meshes == [HOSTS * DEVICES_PER_HOST,
                               (HOSTS - 1) * DEVICES_PER_HOST],
                    f"h{i} rebuilt the mesh 6 -> 4 devices across the "
                    f"resize (data axis history {meshes})")
        f.check(len(resume_steps) == 1,
                f"both survivors agreed on one resume step "
                f"({sorted(resume_steps)})")

        # the re-derived host shards are disjoint and covering at world 2
        shards = {}
        for i in survivors:
            evs = read_jsonl(journals[i])
            rs = [e for e in evs if e.get("event") == "data_reshard"]
            f.check(len(rs) == 1 and rs[0].get("generation") == 1
                    and rs[0].get("from") == HOSTS
                    and rs[0].get("to") == HOSTS - 1
                    and rs[0].get("num_shards") == HOSTS - 1,
                    f"h{i} journaled data_reshard to the 2-host world")
            if rs:
                shards[i] = rs[0].get("shard_index")
        f.check(sorted(shards.values()) == list(range(HOSTS - 1)),
                f"post-resize host shards are disjoint+covering "
                f"({shards})")

        # -- phase 4: artifact validity ---------------------------------
        for i in survivors:
            f.check(check_journal_strict(journals[i]),
                    f"check_journal --strict accepts h{i}'s journal "
                    "(membership events schema-valid, clean exit)")
            evs = read_jsonl(journals[i])
            viol = [e for e in evs
                    if e.get("event") == "lock_order_violation"]
            f.check(not viol,
                    f"locksmith (armed whole-run) found zero lock-order "
                    f"violations on h{i}")
        rep = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "obs_report.py")]
            + [journals[i] for i in survivors],
            cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
            capture_output=True, text=True)
        f.check(rep.returncode == 0 and "host_lost" in rep.stdout
                and "membership" in rep.stdout,
                "obs_report renders the membership timeline")

        # -- phase 5: goodput attribution -------------------------------
        # every wall-clock second of each survivor's life must land in a
        # named bucket, and the seconds between the SIGKILL and the first
        # post-resize step must land in the FAILURE buckets — a recovery
        # that bills itself to overhead is unattributed downtime
        from deep_vision_tpu.obs.goodput import attribute_journal
        from tools.perf_gate import PerfLedger, default_env, gate_result

        fracs = []
        for i in survivors:
            evs = read_jsonl(journals[i])
            f.check(any(e.get("event") == "goodput_summary" for e in evs),
                    f"h{i}'s live GoodputMeter flushed a goodput_summary "
                    "(once per incarnation, via the journal closer)")
            acct = attribute_journal(evs)
            imb = acct.imbalance_frac()
            f.check(imb <= 0.02,
                    f"h{i} goodput buckets sum to wall clock within 2% "
                    f"(imbalance {imb * 100:.2f}%)")
            rec = acct.buckets["host_loss_recovery"]
            f.check(rec > 0,
                    f"h{i} attributed the host-loss window to "
                    f"host_loss_recovery ({rec:.2f} s)")
            lost = [e for e in evs if e.get("event") == "host_lost"]
            resized = [e for e in evs if e.get("event") == "world_resized"]
            post = [e for e in evs if e.get("event") == "step"
                    and resized and float(e["ts"]) > float(resized[0]["ts"])]
            if lost and post:
                window = float(post[0]["ts"]) - float(lost[0]["ts"])
                named = (rec + acct.buckets["rendezvous_wait"]
                         + acct.buckets["checkpoint"]
                         + acct.buckets["compile"])
                f.check(named >= 0.5 * window,
                        f"h{i}'s recovery window ({window:.1f} s) lands "
                        f"predominantly in named failure buckets "
                        f"({named:.1f} s in recovery/rendezvous/"
                        "checkpoint/compile, not overhead)")
            fracs.append(acct.goodput_frac())
        if fracs and not f.errors:
            verdict = gate_result(
                PerfLedger(os.path.join(ROOT, "artifacts",
                                        "perf_ledger.jsonl")),
                "goodput_frac", min(fracs), unit="frac",
                env=dict(default_env(), suite="host_smoke"),
                direction="higher")
            f.check(verdict["verdict"] in ("pass", "insufficient_history"),
                    f"goodput_frac {min(fracs):.3f} passes the MAD gate "
                    f"(verdict {verdict['verdict']})")
    finally:
        for pr, log in procs.values():
            if pr.poll() is None:
                pr.kill()
                pr.wait(timeout=30)
            log.close()

    if f.errors:
        print(f"host-smoke: {len(f.errors)} contract(s) BROKEN")
        for e in f.errors:
            print(f"  - {e}")
        return 1
    print("host-smoke: all contracts held")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
