"""YOLOv3 loss, fully vectorized and static-shape.

Parity target: YoloLoss at YOLO/tensorflow/yolov3.py:352-563 — per-scale loss
with (xy, wh, class, obj) breakdown, lambda_coord=5 / lambda_noobj=0.5
(:357-358), small-box weight 2 - w*h (:407), and the ignore mask computed by
broadcast IoU of decoded predictions against the ground-truth boxes
(:436-470; the reference gathers top-100 boxes out of the label grid — here
the padded GT box list rides in the batch directly, which is both cheaper and
exact).

Batch convention (built by data/detection.py):
  batch['labels']  : tuple over scales of (B, g, g, A, 5+C) target grids
                     with [x, y, w, h, obj, onehot] (absolute normalized xywh)
  batch['boxes']   : (B, max_boxes, 4) padded GT boxes, xywh normalized
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import optax

from deep_vision_tpu.ops.anchors import YOLO_ANCHOR_MASKS, YOLO_ANCHORS
from deep_vision_tpu.ops.boxes import (
    broadcast_iou,
    decode_yolo_boxes,
    encode_yolo_boxes,
    xywh_to_xyxy,
)

LAMBDA_COORD = 5.0
LAMBDA_NOOBJ = 0.5


def yolo_loss_per_scale(
    pred,
    target,
    gt_boxes,
    anchors,
    ignore_thresh: float = 0.5,
):
    """pred (B,g,g,A,5+C) raw logits; target same shape; gt_boxes (B,N,4) xywh."""
    b, gy, gx, na, _ = pred.shape
    obj_mask = target[..., 4]  # (B,g,g,A)
    true_xywh = target[..., 0:4]
    true_class = target[..., 5:]

    # regression targets in t-space (inverse of the decode)
    t_true = encode_yolo_boxes(true_xywh, anchors, gy)
    pred_xy = jax.nn.sigmoid(pred[..., 0:2])
    pred_twh = pred[..., 2:4]

    # small boxes get up-weighted (yolov3.py:407)
    box_scale = jnp.where(
        obj_mask > 0, 2.0 - true_xywh[..., 2] * true_xywh[..., 3], 0.0
    )

    xy_loss = jnp.sum(
        jnp.square(pred_xy - t_true[..., 0:2]), axis=-1
    ) * box_scale * obj_mask
    wh_loss = jnp.sum(
        jnp.square(pred_twh - t_true[..., 2:4]), axis=-1
    ) * box_scale * obj_mask

    # ignore mask: decoded predictions overlapping ANY gt box are not
    # penalized as background (yolov3.py:436-470)
    pred_boxes, pred_obj, _ = decode_yolo_boxes(pred, anchors)
    gt_xyxy = xywh_to_xyxy(gt_boxes)  # (B, N, 4)
    flat_pred = pred_boxes.reshape(b, -1, 4)
    best_iou = jnp.max(broadcast_iou(flat_pred, gt_xyxy), axis=-1)  # (B, g*g*A)
    # padded gt rows are zero-area -> IoU 0, harmless
    ignore = (best_iou > ignore_thresh).reshape(b, gy, gx, na)

    obj_bce = optax.sigmoid_binary_cross_entropy(pred[..., 4], obj_mask)
    obj_loss = obj_mask * obj_bce
    noobj_loss = (1.0 - obj_mask) * (1.0 - ignore) * obj_bce

    class_bce = optax.sigmoid_binary_cross_entropy(pred[..., 5:], true_class)
    class_loss = obj_mask * jnp.sum(class_bce, axis=-1)

    def _mean(x):  # per-image sum, batch mean (matches reduce_sum/batch)
        return jnp.mean(jnp.sum(x, axis=(1, 2, 3)))

    losses = {
        "xy": LAMBDA_COORD * _mean(xy_loss),
        "wh": LAMBDA_COORD * _mean(wh_loss),
        "obj": _mean(obj_loss),
        "noobj": LAMBDA_NOOBJ * _mean(noobj_loss),
        "class": _mean(class_loss),
    }
    losses["total"] = sum(losses.values())
    return losses


def yolo_loss_fn(
    outputs,
    batch,
    anchors=YOLO_ANCHORS,
    anchor_masks=YOLO_ANCHOR_MASKS,
    ignore_thresh: float = 0.5,
):
    """Trainer-compatible loss: sums the 3 per-scale losses (yolov3.py:81-95)."""
    anchors = jnp.asarray(anchors)
    total = 0.0
    metrics = {}
    names = ("large", "medium", "small")
    for i, (pred, target) in enumerate(zip(outputs, batch["labels"])):
        scale_anchors = anchors[jnp.asarray(anchor_masks[i])]
        losses = yolo_loss_per_scale(
            pred, target, batch["boxes"], scale_anchors, ignore_thresh
        )
        total = total + losses["total"]
        metrics[f"loss_{names[i]}"] = losses["total"]
        if i == 0:  # breakdown for one scale keeps metric volume sane
            for k in ("xy", "wh", "obj", "noobj", "class"):
                metrics[f"{names[i]}_{k}"] = losses[k]
    metrics["loss"] = total
    return total, metrics


def yolo_train_loss_fn(
    outputs,
    batch,
    grid_sizes: Sequence[int] = (13, 26, 52),
    num_classes: int = 80,
    anchors=YOLO_ANCHORS,
    anchor_masks=YOLO_ANCHOR_MASKS,
    ignore_thresh: float = 0.5,
):
    """YOLO loss with ON-DEVICE label assignment from padded GT boxes.

    The reference assigns anchors on the host inside tf.data
    (preprocess_label_for_one_scale, YOLO/tensorflow/preprocess.py:137-224,
    a TensorArray autograph loop per image). Here the data pipeline ships only
    padded `batch['boxes']` (x1y1x2y2 normalized) + `batch['classes']`, and
    the target grids are built inside the jitted train step as a vectorized
    scatter (ops/anchors.assign_anchors_to_grid) — host CPU off the critical
    path, assignment on the MXU's host-free timeline.
    """
    from deep_vision_tpu.ops.anchors import assign_anchors_to_grid
    from deep_vision_tpu.ops.boxes import xyxy_to_xywh

    xywh = xyxy_to_xywh(batch["boxes"])
    labels = jax.vmap(
        lambda b, c: tuple(
            assign_anchors_to_grid(
                b, c, grid_sizes, anchors, anchor_masks, num_classes
            )
        )
    )(xywh, batch["classes"])
    return yolo_loss_fn(
        outputs,
        {"labels": tuple(labels), "boxes": xywh},
        anchors,
        anchor_masks,
        ignore_thresh,
    )
