"""SLO accounting: the numbers an operator pages on.

Rides the existing obs registry (PR 1) rather than inventing a second
metrics surface: request latency lands in the same log-scale Histogram
type the trainer's step times use, so p50/p95/p99 come from
`Histogram.quantile` exactly like every other tail in the repo, and one
Prometheus export carries training and serving side by side.

Tracked per model:

  serve_request_latency_ms{model=}   submit -> result, histogram
  serve_queue_wait_ms{model=}        oldest-request coalescing wait
  serve_exec_ms{model=}              device execute + host fetch
  serve_requests_total{model=,outcome=}  ok / error / rejected
  serve_queue_depth{model=}          gauge, updated on every transition
  serve_batch_occupancy_pct{model=}  last batch: real rows / bucket rows
  serve_padding_waste_pct{model=}    last batch: padded rows / bucket rows
  serve_batches_total{model=}
  serve_batch_slots_total{model=} / serve_padded_slots_total{model=}
                                     lifetime aggregate occupancy
  serve_slo_violations_total{model=} requests over the p99 target
                                     (when an slo_ms target is set)
  serve_offered_total{model=}        every request the front door SAW,
                                     admitted or not (serve/pool.py)
  serve_shed_total{model=,reason=}   requests rejected by admission
                                     control (serve/admission.py)

Fleet gauges (serve/pool.py): `serve_replica_queue_depth{replica=}` —
per-replica in-flight depth, the signal load-aware routing steers by.

`report()` collapses all of it into one dict per model (the serving
summary `tools/obs_report.py` renders from the journal has the same
shape, so live metrics and postmortem journals read identically).
Pools report offered vs admitted RPS side by side: a shed request never
enters the latency histograms, so without the offered line an overloaded
server that sheds 90% of its traffic would show a flattering p99 —
`offered_rps`/`admitted_rps` make the gap explicit.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from deep_vision_tpu.obs.registry import Registry, get_registry

OUTCOMES = ("ok", "error", "rejected", "cancelled")
#: admission-control shed reasons (serve/admission.py); mirrored by
#: tools/check_journal.py's serve_shed enum
SHED_REASONS = ("queue_full", "rate_limited", "draining")


class SLOTracker:
    """Per-model serving metrics over one obs registry."""

    def __init__(self, registry: Optional[Registry] = None,
                 slo_ms: Optional[float] = None):
        self.registry = registry or get_registry()
        self.slo_ms = slo_ms
        self._models: Dict[str, dict] = {}
        self._replica_depth: Dict[str, object] = {}

    def _m(self, model: str) -> dict:
        m = self._models.get(model)
        if m is None:
            r = self.registry
            lbl = {"model": model}
            m = {
                "latency": r.histogram(
                    "serve_request_latency_ms",
                    "request latency, submit -> result", labels=lbl),
                "queue_wait": r.histogram(
                    "serve_queue_wait_ms",
                    "oldest-request wait before dispatch", labels=lbl),
                "exec": r.histogram(
                    "serve_exec_ms", "batch execute + host fetch",
                    labels=lbl),
                "requests": {o: r.counter(
                    "serve_requests_total", "requests by outcome",
                    labels={"model": model, "outcome": o})
                    for o in OUTCOMES},
                "depth": r.gauge(
                    "serve_queue_depth", "requests waiting to batch",
                    labels=lbl),
                "occupancy": r.gauge(
                    "serve_batch_occupancy_pct",
                    "last batch: real rows / bucket rows", labels=lbl),
                "waste": r.gauge(
                    "serve_padding_waste_pct",
                    "last batch: padded rows / bucket rows", labels=lbl),
                "batches": r.counter(
                    "serve_batches_total", "batches dispatched", labels=lbl),
                "slots": r.counter(
                    "serve_batch_slots_total", "bucket rows dispatched",
                    labels=lbl),
                "padded": r.counter(
                    "serve_padded_slots_total", "bucket rows that were pad",
                    labels=lbl),
                "violations": r.counter(
                    "serve_slo_violations_total",
                    "requests over the slo_ms target", labels=lbl),
                "offered": r.counter(
                    "serve_offered_total",
                    "requests offered at the front door (incl. shed)",
                    labels=lbl),
                "shed": {reason: r.counter(
                    "serve_shed_total", "requests shed by admission control",
                    labels={"model": model, "reason": reason})
                    for reason in SHED_REASONS},
                "refused": r.counter(
                    "serve_refused_total",
                    "requests refused by fleet failure (no serving "
                    "replica) — NOT policy sheds", labels=lbl),
                # wall-clock window of the offer stream, for the
                # offered/admitted RPS in report(); benign last-writer
                # races only nudge the window edges
                "t_first": None,
                "t_last": None,
            }
            self._models[model] = m
        return m

    # -- recording hooks (router + pool call these) -------------------------

    def queue_depth(self, model: str, depth: int) -> None:
        self._m(model)["depth"].set(depth)

    def replica_queue_depth(self, replica: str, depth: int) -> None:
        """Per-replica in-flight depth (serve/pool.py routing signal).
        The gauge object is cached like _m's per-model metrics: this
        runs per request inside the pool's routing lock, and a registry
        get-or-create there would serialize clients on a second lock."""
        g = self._replica_depth.get(replica)
        if g is None:
            g = self.registry.gauge(
                "serve_replica_queue_depth",
                "requests in flight on one replica",
                labels={"replica": replica})
            self._replica_depth[replica] = g
        g.set(depth)

    def offered(self, model: str) -> None:
        """Count one request at the front door, before admission. The
        offered-vs-admitted gap is the shed rate — report() exposes both
        as RPS so shedding can't silently flatter the latency tail."""
        m = self._m(model)
        m["offered"].inc()
        now = time.monotonic()
        if m["t_first"] is None:
            m["t_first"] = now
        m["t_last"] = now

    def shed(self, model: str, reason: str) -> None:
        if reason not in SHED_REASONS:
            raise ValueError(f"shed reason {reason!r} not in {SHED_REASONS}")
        self._m(model)["shed"][reason].inc()

    def refused(self, model: str) -> None:
        """An offered request the pool could not even queue (no serving
        replica). Kept apart from shed: a refusal is a fleet failure,
        and counting it as admitted would flatter admitted_rps."""
        self._m(model)["refused"].inc()

    def request_done(self, model: str, latency_ms: float,
                     outcome: str = "ok") -> None:
        m = self._m(model)
        m["requests"][outcome if outcome in OUTCOMES else "error"].inc()
        if outcome == "ok":
            m["latency"].observe(latency_ms)
            if self.slo_ms is not None and latency_ms > self.slo_ms:
                m["violations"].inc()

    def batch_done(self, model: str, bucket: int, size: int,
                   queue_wait_ms: float, exec_ms: float) -> None:
        m = self._m(model)
        m["batches"].inc()
        m["slots"].inc(bucket)
        m["padded"].inc(bucket - size)
        m["occupancy"].set(100.0 * size / bucket)
        m["waste"].set(100.0 * (bucket - size) / bucket)
        m["queue_wait"].observe(queue_wait_ms)
        m["exec"].observe(exec_ms)

    # -- reading back --------------------------------------------------------

    def report(self) -> Dict[str, dict]:
        """model -> {requests, errors, p50/p95/p99_ms, occupancy_pct,
        padding_waste_pct, batches, slo_violations}. Quantiles are
        bucket-resolution (Histogram.quantile): upper bound of the bucket
        holding the q-th observation, same contract as every other obs
        tail in the repo."""
        out: Dict[str, dict] = {}
        for model, m in sorted(self._models.items()):
            slots = m["slots"].value
            out[model] = {
                "requests": int(m["requests"]["ok"].value),
                "errors": int(m["requests"]["error"].value),
                "rejected": int(m["requests"]["rejected"].value),
                "cancelled": int(m["requests"]["cancelled"].value),
                "p50_ms": m["latency"].quantile(0.5),
                "p95_ms": m["latency"].quantile(0.95),
                "p99_ms": m["latency"].quantile(0.99),
                "mean_ms": m["latency"].mean,
                "batches": int(m["batches"].value),
                "occupancy_pct": (100.0 * (slots - m["padded"].value) / slots
                                  if slots else 0.0),
                "padding_waste_pct": (100.0 * m["padded"].value / slots
                                      if slots else 0.0),
                "slo_violations": int(m["violations"].value),
            }
            offered = int(m["offered"].value)
            if offered:
                shed = sum(int(c.value) for c in m["shed"].values())
                refused = int(m["refused"].value)
                row = out[model]
                row["offered"] = offered
                row["shed"] = shed
                if refused:
                    row["refused"] = refused
                admitted = offered - shed - refused
                row["admitted"] = admitted
                # the shed-can't-flatter-p99 accounting: quote the tail
                # next to how much traffic was allowed to produce it
                window_s = ((m["t_last"] or 0.0) - (m["t_first"] or 0.0))
                if window_s > 0:
                    row["offered_rps"] = offered / window_s
                    row["admitted_rps"] = admitted / window_s
        return out

    def render(self) -> str:
        """One aligned text block (the `serve_smoke` / operator view)."""
        rep = self.report()
        if not rep:
            return "slo: no serving traffic recorded"
        lines = []
        for model, r in rep.items():
            lines.append(
                f"{model}: {r['requests']} ok, {r['errors']} err  "
                f"latency mean {r['mean_ms']:.2f}ms "
                f"p50 {r['p50_ms']:.2f} p95 {r['p95_ms']:.2f} "
                f"p99 {r['p99_ms']:.2f}  "
                f"batches {r['batches']} "
                f"occupancy {r['occupancy_pct']:.1f}% "
                f"waste {r['padding_waste_pct']:.1f}%"
                + (f"  slo>{self.slo_ms:g}ms: {r['slo_violations']}"
                   if self.slo_ms is not None else "")
                + (f"  offered {r['offered']} shed {r['shed']}"
                   + (f" ({r['offered_rps']:.1f} -> "
                      f"{r['admitted_rps']:.1f} rps)"
                      if "offered_rps" in r else "")
                   if "offered" in r else ""))
        return "\n".join(lines)
