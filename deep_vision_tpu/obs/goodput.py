"""Goodput plane: attribute every wall-clock second to one typed bucket.

The repo instruments every failure mode — host loss (resilience/
rendezvous.py), replica SIGKILL (serve/procpool.py), compiles
(core/excache.py + the stepclock compile listener), data waits
(obs/stepclock.py), checkpoint spans (train/trainer.py) — but until
now no ledger said what fraction of wall-clock was *productive*. This
module is that ledger: a partition of the run's wall clock into the
`GOODPUT_BUCKETS`, carrying the repo's signature accounting invariant

    sum(buckets) == wall_clock        (exact, by construction)

because every gap between consecutive journal rows is fully attributed
before the cursor advances — the invariant cannot drift, only the
*labeling* of seconds can be wrong, and the smokes pin the labeling
(host-smoke: the SIGKILL recovery window lands in `host_loss_recovery`;
fleetnet-smoke: the respawn window lands in `replica_respawn`).

Two consumers, one accountant:

- **live** — `GoodputMeter` rides `RunJournal.add_tap`, folds each row
  into a `GoodputAccountant`, emits a typed `goodput_interval` event
  every `DVT_GOODPUT_INTERVAL_S` seconds and a terminal
  `goodput_summary` on close, and exposes `telemetry_status()` as a
  TelemetryServer status source (the obs_poll "gp NN%" column).
- **offline** — `attribute_journal(events)` replays any journal
  (including one stitched across re-execs, where no live meter could
  survive) through the same accountant, so post-mortem attribution and
  the live gauges can never disagree about the algorithm.

The `goodput_frac` scalar (productive_step / wall) is the one number
ROADMAP item 5 asks for; the smokes land it as a gated row in
`artifacts/perf_ledger.jsonl` so the MAD gate (tools/perf_gate.py)
watches it across PRs.

How seconds are labeled (the attribution rules):

- `step` rows split their preceding gap using the StepClock splits:
  `data_wait_ms` -> data_wait, the `compile_ms` delta -> compile, the
  remaining step wall -> productive_step, leftover -> the ambient
  bucket. A step row also *closes* a host-loss recovery window —
  recovery is not over until training steps again.
- `host_lost` opens `host_loss_recovery`; `world_resized` carves its
  `rendezvous_wait_s` stamp into rendezvous_wait and leaves the window
  open until the first post-resize step.
- `replica_lost`/`replica_recovered` (procpool) bracket
  `replica_respawn`; `serve_drain` rows carve their `drain_s` stamp
  into drain.
- `checkpoint` rows carve their `save_ms` stamp (and the resume note's
  `restore_ms`) into checkpoint.
- `excache_miss` -> `excache_store`/`excache_hit` windows are compile
  time; the step-row compile delta is credited against them so a
  cache-missed warmup compile is never counted twice (see
  `_compile_credit`).
- `transport_request` rows with outcome "ok" carve their `latency_ms`
  into productive_step — serving's productive second is a served
  request.
- Whatever no rule claims lands in `overhead` — the honest unknown.

jax-free at import (data workers and the serve parent use it).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from deep_vision_tpu.core import knobs
from deep_vision_tpu.obs import locksmith

#: The exhaustive wall-clock partition. tools/check_journal.py mirrors
#: this tuple (GOODPUT_BUCKETS) for --strict validation; a drift-guard
#: test pins the two copies together. `overhead` is the catch-all for
#: seconds no rule claims — the "unknown" bucket the smokes assert the
#: failure windows do NOT land in.
GOODPUT_BUCKETS = (
    "productive_step",
    "data_wait",
    "compile",
    "checkpoint",
    "host_loss_recovery",
    "replica_respawn",
    "rendezvous_wait",
    "drain",
    "overhead",
)

#: Events the goodput/alert plane itself emits — the accountant treats
#: them as plain rows (their gaps are ambient time), but the live meter
#: must never re-emit while observing one, or a tap would recurse.
OWN_EVENTS = ("goodput_interval", "goodput_summary",
              "alert_fired", "alert_resolved")

DEFAULT_INTERVAL_S = 30.0


def _num(row: dict, key: str) -> Optional[float]:
    v = row.get(key)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return None


class GoodputAccountant:
    """The pure attribution state machine: feed it journal rows in file
    order via `observe`, read `buckets`. Not thread-safe — GoodputMeter
    wraps it in a lock for the live tap; offline replay is single-
    threaded by nature."""

    def __init__(self) -> None:
        self.buckets: Dict[str, float] = {b: 0.0 for b in GOODPUT_BUCKETS}
        self._t0: Optional[float] = None
        self._cursor: Optional[float] = None
        # window state: which bucket owns otherwise-unclaimed seconds
        self._recovering = False        # host_lost .. first step after
        self._respawning = 0            # replica_lost depth (overlapping)
        self._compile_open = False      # excache_miss .. store/hit
        # compile seconds already attributed via an excache window since
        # the last step row — credited against that step's compile_ms
        # delta so a warmup compile is not double-counted
        self._compile_credit = 0.0

    # -- the ledger --------------------------------------------------------

    def wall_s(self) -> float:
        if self._t0 is None or self._cursor is None:
            return 0.0
        return self._cursor - self._t0

    def total_s(self) -> float:
        return sum(self.buckets.values())

    def imbalance_frac(self) -> float:
        """|sum(buckets) - wall| / wall — ~0 by construction; the smokes
        assert <= 2% so any future attribution rule that breaks the
        partition fails loudly."""
        wall = self.wall_s()
        if wall <= 0.0:
            return 0.0
        return abs(self.total_s() - wall) / wall

    def goodput_frac(self) -> float:
        wall = self.wall_s()
        if wall <= 0.0:
            return 0.0
        return self.buckets["productive_step"] / wall

    def snapshot(self) -> dict:
        return {"wall_s": round(self.wall_s(), 3),
                "goodput_frac": round(self.goodput_frac(), 4),
                "imbalance_frac": round(self.imbalance_frac(), 4),
                "buckets": {b: round(v, 3)
                            for b, v in self.buckets.items()}}

    # -- attribution -------------------------------------------------------

    def _ambient(self) -> str:
        if self._recovering:
            return "host_loss_recovery"
        if self._respawning > 0:
            return "replica_respawn"
        if self._compile_open:
            return "compile"
        return "overhead"

    def advance(self, now: float) -> None:
        """Attribute the gap from the cursor to `now` to the ambient
        bucket (interval emission / end-of-run flush)."""
        if self._t0 is None:
            self._t0 = self._cursor = now
            return
        gap = now - float(self._cursor)
        if gap <= 0.0:
            return
        self.buckets[self._ambient()] += gap
        self._cursor = now

    def observe(self, row: dict) -> None:
        """Fold one journal row in: fully attribute the gap since the
        previous row, then update the window state."""
        ts = _num(row, "ts")
        if ts is None:
            return
        if self._t0 is None:
            self._t0 = self._cursor = ts
            gap = 0.0
        else:
            gap = max(0.0, ts - float(self._cursor))
            self._cursor = max(float(self._cursor), ts)
        event = row.get("event")
        if event == "step":
            self._observe_step(row, gap)
            return
        if event in ("checkpoint", "preempt_checkpoint"):
            self._carve(gap, "checkpoint", _num(row, "save_ms"), scale=1e-3)
            return
        if event == "note" and row.get("note") == "resumed":
            self._carve(gap, "checkpoint", _num(row, "restore_ms"),
                        scale=1e-3)
            return
        if event == "host_lost":
            self.buckets[self._ambient()] += gap
            self._recovering = True
            return
        if event == "world_resized":
            rdzv = _num(row, "rendezvous_wait_s") or 0.0
            take = min(gap, max(0.0, rdzv))
            self.buckets["rendezvous_wait"] += take
            self.buckets[self._ambient()] += gap - take
            return
        if event == "replica_lost":
            self.buckets[self._ambient()] += gap
            self._respawning += 1
            return
        if event == "replica_recovered":
            self.buckets["replica_respawn"] += gap
            self._respawning = max(0, self._respawning - 1)
            return
        if event == "excache_miss":
            self.buckets[self._ambient()] += gap
            self._compile_open = True
            return
        if event in ("excache_store", "excache_hit", "excache_invalid"):
            if self._compile_open:
                self.buckets["compile"] += gap
                self._compile_credit += gap
                self._compile_open = False
            else:
                self.buckets[self._ambient()] += gap
            return
        if event == "serve_drain":
            self._carve(gap, "drain", _num(row, "drain_s"), scale=1.0)
            return
        if event == "transport_request":
            lat = _num(row, "latency_ms")
            if row.get("outcome") == "ok" and lat is not None:
                take = min(gap, max(0.0, lat * 1e-3))
                self.buckets["productive_step"] += take
                gap -= take
            self.buckets[self._ambient()] += gap
            return
        self.buckets[self._ambient()] += gap

    def _carve(self, gap: float, bucket: str, dur: Optional[float],
               scale: float) -> None:
        """Attribute min(gap, dur) to `bucket`, the rest ambient; rows
        without a duration stamp (older journals) claim the whole gap —
        they directly follow the work they describe."""
        take = gap if dur is None else min(gap, max(0.0, dur * scale))
        self.buckets[bucket] += take
        self.buckets[self._ambient()] += gap - take

    def _observe_step(self, row: dict, gap: float) -> None:
        data_wait = min(gap, max(0.0, (_num(row, "data_wait_ms") or 0.0)
                                 * 1e-3))
        rest = gap - data_wait
        compile_s = max(0.0, (_num(row, "compile_ms") or 0.0) * 1e-3
                        - self._compile_credit)
        compile_take = min(rest, compile_s)
        rest -= compile_take
        step_wall = max(0.0, (_num(row, "step_time_ms") or 0.0) * 1e-3)
        productive = min(rest, max(0.0, step_wall - data_wait
                                   - compile_take))
        rest -= productive
        self.buckets["data_wait"] += data_wait
        self.buckets["compile"] += compile_take
        self.buckets["productive_step"] += productive
        self.buckets[self._ambient()] += rest
        # a step closes every training-side window: recovery is over,
        # any open compile window resolved into this step's delta
        self._recovering = False
        self._compile_open = False
        self._compile_credit = 0.0


def attribute_journal(events: List[dict]) -> GoodputAccountant:
    """Offline attribution: replay journal rows (read_journal order —
    append order, which is time order per writer) through a fresh
    accountant. The same code path the live meter runs, so live and
    post-mortem numbers cannot diverge algorithmically."""
    acc = GoodputAccountant()
    for row in events:
        if isinstance(row, dict):
            acc.observe(row)
    return acc


class GoodputMeter:
    """The live half: a journal tap feeding a GoodputAccountant, with
    periodic `goodput_interval` events, a terminal `goodput_summary`,
    registry gauges, and a TelemetryServer status source.

    Construction installs the tap; `close()` flushes the terminal
    summary (idempotent — safe under both Trainer.close and atexit
    ordering). The tap is re-entrancy-safe: emitting an interval row
    re-invokes the tap with that row, which is observed like any other
    but can never trigger a second emission (OWN_EVENTS guard)."""

    def __init__(self, journal=None, registry=None,
                 interval_s: Optional[float] = None,
                 time_fn=time.time) -> None:
        self.journal = journal
        self.registry = registry
        self.interval_s = (knobs.get_float("DVT_GOODPUT_INTERVAL_S")
                           if interval_s is None else float(interval_s))
        self._time = time_fn
        self._lock = locksmith.lock("obs.goodput")
        self._acc = GoodputAccountant()
        self._last_emit: Optional[float] = None
        self._last_buckets: Dict[str, float] = {b: 0.0
                                                for b in GOODPUT_BUCKETS}
        self._closed = False
        if registry is not None:
            self._g_frac = registry.gauge(
                "goodput_frac", "productive fraction of wall clock")
            self._g_bucket = {
                b: registry.gauge("goodput_seconds_total",
                                  "wall-clock seconds by goodput bucket",
                                  labels={"bucket": b})
                for b in GOODPUT_BUCKETS}
        else:
            self._g_frac = None
            self._g_bucket = {}
        if journal is not None:
            journal.add_tap(self.tap)
            # closers run before the terminal exit row, so every
            # journal'd run ends with a goodput_summary even when the
            # owner never calls close() explicitly
            journal.add_closer(self.close)

    # -- the journal tap ---------------------------------------------------

    def tap(self, row: dict) -> None:
        """RunJournal tap: called with every written row, outside the
        journal lock. Folds the row in; every `interval_s` seconds of
        event time, emits one `goodput_interval` delta row."""
        emit = None
        with self._lock:
            if self._closed:
                return
            self._acc.observe(row)
            now = _num(row, "ts")
            if now is None:
                return
            if self._last_emit is None:
                self._last_emit = now
            elif (row.get("event") not in OWN_EVENTS
                  and now - self._last_emit >= self.interval_s):
                emit = self._interval_row(now)
        if emit is not None and self.journal is not None:
            self.journal.write("goodput_interval", **emit)

    def _interval_row(self, now: float) -> dict:
        """Build one interval delta row; caller holds the lock."""
        delta = {}
        for b in GOODPUT_BUCKETS:
            delta[b] = round(self._acc.buckets[b] - self._last_buckets[b], 3)
            self._last_buckets[b] = self._acc.buckets[b]
        dur = now - float(self._last_emit)
        self._last_emit = now
        self._update_gauges()
        return {"dur_s": round(dur, 3), "buckets": delta,
                "goodput_frac": round(self._acc.goodput_frac(), 4)}

    def _update_gauges(self) -> None:
        if self._g_frac is not None:
            self._g_frac.set(self._acc.goodput_frac())
        for b, g in self._g_bucket.items():
            g.set(round(self._acc.buckets[b], 3))

    # -- reading -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return self._acc.snapshot()

    def telemetry_status(self) -> dict:
        """TelemetryServer status source ("goodput" section of /statusz;
        obs_poll renders goodput_frac as the gp column)."""
        snap = self.snapshot()
        return {"goodput_frac": snap["goodput_frac"],
                "wall_s": snap["wall_s"],
                "imbalance_frac": snap["imbalance_frac"],
                "buckets": snap["buckets"]}

    # -- terminal ----------------------------------------------------------

    def close(self) -> Optional[dict]:
        """Advance to now, write the terminal `goodput_summary`, update
        the gauges one last time. Idempotent; returns the summary."""
        with self._lock:
            if self._closed:
                return None
            self._closed = True
            self._acc.advance(self._time())
            self._update_gauges()
            snap = self._acc.snapshot()
        if self.journal is not None:
            self.journal.write("goodput_summary", **snap)
        return snap
