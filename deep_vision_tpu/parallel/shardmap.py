"""Declarative pattern -> PartitionSpec sharding tables.

The `infer_tp_sharding` size heuristic (parallel/mesh.py) decides tensor
parallelism from leaf shapes alone — which is exactly how the
`tp_sharded_leaves` count silently regressed 108 -> 34 between MULTICHIP
r03 and r05: a model refactor changed shapes, the heuristic changed its
mind, and nothing could say WHICH leaves it dropped or WHY. PR 10 turned
the regression into a hard startup failure; this module makes the
sharding itself an auditable artifact instead of an emergent property.

A `ShardingRules` table is an ORDERED list of (pattern, spec) pairs in
the GSPMD/pjit tradition (the `"layers.*.attention.wo.weight":
('fsdp', 'tp')` style):

- leaf paths are flattened to dotted names (`params.ViTBlock_0.
  Attention_0.qkv.kernel`) and NORMALIZED: pure-integer path tokens
  become `*` (optimizer-state tuple indices, torch-style `layers.11.`);
  flax's `Name_N` suffixes stay LITERAL — `Mlp_0.Dense_0` vs
  `Mlp_0.Dense_1` distinguishes the column- from the row-parallel
  projection — and the pattern's glob (`ViTBlock_*`) generalizes over
  layer indices, so one table covers every depth of a model family;
- patterns are glob-style (`fnmatch`) over the normalized path;
  FIRST MATCH WINS, so specific rules shadow general ones by order;
- every table must end in a catch-all `"*"` rule — a leaf that no rule
  covers is a construction-time error, never a silent replicate;
- a spec is a tuple of per-dimension entries (None, an axis name, or a
  tuple of axis names — `PartitionSpec` semantics). Unknown mesh axes
  and specs longer than the leaf's rank REFUSE at resolve time; an axis
  that does not divide the dimension is dropped (replicating that dim,
  the `elastic.replace_on_mesh` convention) and counted in the report.

`resolve(tree, mesh)` returns a full `NamedSharding` tree for the state
(params, optimizer momentum — whose paths carry the param path as a
suffix, so the same leading-`*` rules match — BN stats, rng, counters)
plus a rule -> leaf resolution report that the Trainer journals as a
typed `sharding_resolved` event and `assert_sharding_coverage` audits
against the family's declared floor at startup.
"""
from __future__ import annotations

import dataclasses
import fnmatch
import re
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deep_vision_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    infer_tp_sharding,
    sharding_coverage,
)

__all__ = [
    "ShardingRuleError",
    "ShardingRules",
    "HeuristicRules",
    "VIT_RULES",
    "MOE_RULES",
    "RESNET_RULES",
    "FAMILY_RULES",
    "rules_for",
    "get_rules",
    "leaf_path",
    "normalize_path",
    "resolution_event_fields",
]


class ShardingRuleError(ValueError):
    """A sharding table is malformed (missing catch-all, bad spec), or a
    rule cannot apply to the leaf it matched (unknown mesh axis, spec
    longer than the leaf's rank). Raised at table construction or at
    startup resolve — never mid-run."""


_INT_TOKEN = re.compile(r"^\d+$")


def leaf_path(key_path) -> str:
    """Dotted path of a `tree_flatten_with_path` key path:
    `(GetAttrKey('params'), DictKey('ViTBlock_0'), DictKey('kernel'))`
    -> `params.ViTBlock_0.kernel`."""
    toks = []
    for k in key_path:
        if hasattr(k, "name"):  # GetAttrKey (flax.struct fields)
            toks.append(str(k.name))
        elif hasattr(k, "key"):  # DictKey / FlattenedIndexKey
            toks.append(str(k.key))
        elif hasattr(k, "idx"):  # SequenceKey (optax state tuples)
            toks.append(str(k.idx))
        else:
            toks.append(str(k))
    return ".".join(toks)


def normalize_path(path: str) -> str:
    """Integer -> `*` name normalization (SNIPPETS.md [2]'s
    `_process_sharding_name`): every pure-integer path token becomes
    `*`, so `layers.11.attention.wo.weight` normalizes to
    `layers.*.attention.wo.weight` and the optimizer state's tuple
    indices (`opt_state.1.0.trace...`) disappear from the match. Flax's
    `Name_N` layer suffixes are NOT normalized — `Mlp_0.Dense_0` vs
    `Mlp_0.Dense_1` distinguishes the column- from the row-parallel
    projection — the PATTERN's glob (`ViTBlock_*`) generalizes over
    layer indices instead."""
    return ".".join(
        "*" if _INT_TOKEN.match(t) else t for t in path.split("."))


def _floor_for(mesh: Mesh, min_sharded: int,
               floor_axes: Sequence[str]) -> int:
    """The coverage floor a mesh must clear: the declared `min_sharded`
    when every floor axis is actually present with size > 1, else 0 (a
    pure-DP mesh replicates by design). Shared by the table and the
    heuristic fallback so their gating can never diverge."""
    shape = dict(mesh.shape)
    if all(shape.get(a, 0) > 1 for a in floor_axes):
        return int(min_sharded)
    return 0


def _validate_spec(pattern: str, spec) -> tuple:
    if not isinstance(spec, (tuple, list)):
        raise ShardingRuleError(
            f"rule {pattern!r}: spec must be a tuple of per-dimension "
            f"entries (None / axis name / tuple of axis names), got "
            f"{spec!r}")
    for entry in spec:
        if entry is None or isinstance(entry, str):
            continue
        if isinstance(entry, (tuple, list)) and all(
                isinstance(a, str) for a in entry):
            continue
        raise ShardingRuleError(
            f"rule {pattern!r}: spec entry {entry!r} must be None, an "
            "axis name, or a tuple of axis names")
    return tuple(tuple(e) if isinstance(e, list) else e for e in spec)


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """One model family's declarative sharding table.

    rules: ordered ((pattern, spec), ...) — first match wins; the LAST
    rule must be the catch-all `("*", ...)` so no leaf can fall through
    unseen. min_sharded: the family's declared coverage floor — the
    startup `assert_sharding_coverage` fails when fewer float leaves
    actually shard (`floor_for(mesh)` waives it on meshes where the
    floor's axes have size 1, e.g. a pure-DP mesh). batch_axes: the
    mesh axes the BATCH leading dim shards over — the Trainer places
    single batches, multistep superstep stacks, and device-prefetched
    batches per this declaration.
    """

    name: str
    rules: Tuple[Tuple[str, tuple], ...]
    min_sharded: int = 0
    batch_axes: Tuple[str, ...] = (DATA_AXIS,)
    floor_axes: Tuple[str, ...] = (MODEL_AXIS,)

    def __post_init__(self):
        if not self.rules:
            raise ShardingRuleError(f"table {self.name!r} has no rules")
        validated = tuple(
            (str(pat), _validate_spec(str(pat), spec))
            for pat, spec in self.rules)
        object.__setattr__(self, "rules", validated)
        if validated[-1][0] != "*":
            raise ShardingRuleError(
                f"table {self.name!r} has no catch-all: the LAST rule "
                "must be ('*', ...) so every leaf resolves explicitly — "
                "a leaf no rule covers must be a decision, not an "
                "accident")
        seen = set()
        for pat, _ in validated:
            if pat in seen:
                raise ShardingRuleError(
                    f"table {self.name!r}: duplicate pattern {pat!r} — "
                    "the second copy can never match (first match wins)")
            seen.add(pat)
        for field in ("batch_axes", "floor_axes"):
            axes = getattr(self, field)
            if not isinstance(axes, (tuple, list)) or (
                    field == "batch_axes" and not axes) or not all(
                    isinstance(a, str) and a for a in axes):
                raise ShardingRuleError(
                    f"table {self.name!r}: {field} must be a "
                    f"{'non-empty ' if field == 'batch_axes' else ''}"
                    f"tuple of axis names, got {axes!r}")
            object.__setattr__(self, field, tuple(axes))

    # -- matching ----------------------------------------------------------
    def match(self, path: str) -> Tuple[str, tuple]:
        """(pattern, spec) of the first rule matching the NORMALIZED
        path — the catch-all guarantees a hit."""
        norm = normalize_path(path)
        for pat, spec in self.rules:
            if fnmatch.fnmatchcase(norm, pat):
                return pat, spec
        raise ShardingRuleError(  # unreachable: catch-all is enforced
            f"table {self.name!r}: no rule matched {norm!r}")

    def floor_for(self, mesh: Mesh) -> int:
        return _floor_for(mesh, self.min_sharded, self.floor_axes)

    # -- resolution --------------------------------------------------------
    def _entry_for(self, entry, dim: int, mesh_shape: dict, path: str,
                   pat: str, report: dict):
        if entry is None:
            return None
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        for a in axes:
            if a not in mesh_shape:
                raise ShardingRuleError(
                    f"table {self.name!r} rule {pat!r}: unknown mesh "
                    f"axis {a!r} (mesh has {sorted(mesh_shape)}) at "
                    f"leaf {path}")
        size = int(np.prod([mesh_shape[a] for a in axes]))
        if size <= 1:
            return None  # axis of size 1: sharding over it IS
            # replication — resolve to None so the coverage count stays
            # honest (a table must not claim tp_sharded_leaves on a mesh
            # with no model parallelism)
        if dim % size != 0:
            # the replace_on_mesh convention: an axis that does not
            # divide the dim replicates that dim instead of failing the
            # whole family table on one odd-width layer — counted, so
            # the coverage floor still catches a table gone stale
            report["dropped_dims"].append(
                {"path": path, "rule": pat, "dim": dim, "axes": axes})
            return None
        return entry

    def resolve(self, tree, mesh: Mesh):
        """(shardings, report): a NamedSharding for EVERY leaf of
        `tree`, and the rule -> leaf resolution report journaled as the
        typed `sharding_resolved` event.

        report = {model, mesh, rules: {pattern: hits}, float_leaves,
        matched, unmatched, unmatched_paths, sharded_leaves,
        replicated, dropped_dims}. `matched` counts float leaves an
        EXPLICIT rule claimed; `unmatched` those only the catch-all
        caught — the number whose growth means the table went stale.
        """
        import jax.numpy as jnp

        mesh_shape = dict(mesh.shape)
        # batch axes resolve at startup too: a typo'd axis must refuse
        # HERE (the same loud-at-construction/startup contract the rule
        # specs have), not as a raw KeyError at the first train step
        for a in self.batch_axes:
            if a not in mesh_shape:
                raise ShardingRuleError(
                    f"table {self.name!r}: batch axis {a!r} is not a "
                    f"mesh axis (mesh has {sorted(mesh_shape)})")
        report = {
            "model": self.name,
            "mesh": {k: int(v) for k, v in mesh_shape.items()},
            "rules": {pat: 0 for pat, _ in self.rules},
            "float_leaves": 0,
            "matched": 0,
            "unmatched": 0,
            "unmatched_paths": [],
            "sharded_leaves": 0,
            "replicated": 0,
            "dropped_dims": [],
        }
        catch_all = self.rules[-1][0]

        def resolve_leaf(key_path, leaf):
            path = leaf_path(key_path)
            pat, spec = self.match(path)
            shape = getattr(leaf, "shape", ())
            if len(spec) > len(shape):
                raise ShardingRuleError(
                    f"table {self.name!r} rule {pat!r}: spec {spec!r} "
                    f"has {len(spec)} entries but leaf {path} has rank "
                    f"{len(shape)} (shape {tuple(shape)}) — a rule must "
                    "never imply axes the tensor does not have")
            entries = [
                self._entry_for(e, int(shape[d]), mesh_shape, path, pat,
                                report)
                for d, e in enumerate(spec)
            ]
            dtype = getattr(leaf, "dtype", None)
            if dtype is not None and jnp.issubdtype(dtype, jnp.floating):
                # the per-rule ledger counts FLOAT leaves only, so its
                # rows stay consistent with the matched/unmatched/
                # sharded counts beside it (a catch-all hit on the rng
                # key must not read as a leaf falling through)
                report["rules"][pat] += 1
                report["float_leaves"] += 1
                if pat == catch_all:
                    report["unmatched"] += 1
                    report["unmatched_paths"].append(path)
                else:
                    report["matched"] += 1
                if any(e is not None for e in entries):
                    report["sharded_leaves"] += 1
                else:
                    report["replicated"] += 1
            return NamedSharding(mesh, P(*entries))

        shardings = jax.tree_util.tree_map_with_path(resolve_leaf, tree)
        return shardings, report


def resolution_event_fields(report: dict) -> dict:
    """The journal payload of a resolve report: the typed
    `sharding_resolved` schema (tools/check_journal.py --strict) plus
    the per-rule hit counts obs_report renders. Path lists are capped —
    a journal event is a summary, the full report is the return value
    of `resolve()`."""
    return {
        "model": str(report["model"]),
        "matched": int(report["matched"]),
        "unmatched": int(report["unmatched"]),
        "sharded_leaves": int(report["sharded_leaves"]),
        "replicated": int(report["replicated"]),
        "float_leaves": int(report["float_leaves"]),
        "mesh": dict(report["mesh"]),
        "rules": dict(report["rules"]),
        "unmatched_paths": list(report["unmatched_paths"][:8]),
        "dropped_dims": len(report["dropped_dims"]),
    }


@dataclasses.dataclass(frozen=True)
class HeuristicRules:
    """The `infer_tp_sharding` size heuristic behind the SAME interface
    — the EXPLICIT fallback for model families without a curated table
    (`--sharding-rules heuristic`). Its report has no per-rule
    breakdown (the heuristic has one implicit rule), which is exactly
    why the curated tables exist."""

    name: str = "heuristic"
    min_size: int = 4096
    min_sharded: int = 0
    batch_axes: Tuple[str, ...] = (DATA_AXIS,)
    floor_axes: Tuple[str, ...] = (MODEL_AXIS,)

    def floor_for(self, mesh: Mesh) -> int:
        return _floor_for(mesh, self.min_sharded, self.floor_axes)

    def resolve(self, tree, mesh: Mesh):
        mesh_shape = dict(mesh.shape)
        for a in self.batch_axes:
            if a not in mesh_shape:
                raise ShardingRuleError(
                    f"heuristic rules: batch axis {a!r} is not a mesh "
                    f"axis (mesh has {sorted(mesh_shape)})")
        shardings = infer_tp_sharding(tree, mesh, min_size=self.min_size)
        stats = sharding_coverage(tree, shardings)
        report = {
            "model": self.name,
            "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
            "rules": {f"<size heuristic min_size={self.min_size}>":
                      stats["sharded"]},
            "float_leaves": stats["float_leaves"],
            "matched": stats["sharded"],
            "unmatched": stats["replicated"],
            "unmatched_paths": list(stats.get("replicated_paths", []))[:8],
            "sharded_leaves": stats["sharded"],
            "replicated": stats["replicated"],
            "dropped_dims": [],
        }
        return shardings, report


# -- curated family tables ----------------------------------------------------
#
# Axis conventions (parallel/mesh.py): 'data' = batch, 'model' = tensor
# parallel. Leading-`*` patterns intentionally match BOTH params and the
# optimizer momentum mirrors (their flattened paths carry the param path
# as a suffix under opt_state...trace), so moments shard with their
# params — the bf16-momentum HBM win scales with TP too.

#: ViT family (models/vit.py): Megatron-style column->row pairing.
#: qkv splits the HEAD dim, the out projection contracts it; the MLP
#: splits hidden on the way up and contracts it on the way down — each
#: pair costs one all-reduce, the textbook TP layout.
VIT_RULES = ShardingRules(
    name="vit",
    rules=(
        # attention: qkv DenseGeneral kernel (D, 3, H, Dh) / bias (3, H, Dh)
        ("*.Attention_*.qkv.kernel", (None, None, MODEL_AXIS, None)),
        ("*.Attention_*.qkv.bias", (None, MODEL_AXIS, None)),
        # out projection (H, Dh, D): contracting dim sharded, bias full
        ("*.Attention_*.out.kernel", (MODEL_AXIS, None, None)),
        ("*.Attention_*.out.bias", ()),
        # MLP: hidden up-projection column-split, down-projection row-split
        ("*.Mlp_*.Dense_0.kernel", (None, MODEL_AXIS)),
        ("*.Mlp_*.Dense_0.bias", (MODEL_AXIS,)),
        ("*.Mlp_*.Dense_1.kernel", (MODEL_AXIS, None)),
        ("*.Mlp_*.Dense_1.bias", ()),
        # patch embed conv (P, P, C, D): embed dim split
        ("*.patch_embed.kernel", (None, None, None, MODEL_AXIS)),
        ("*.patch_embed.bias", (MODEL_AXIS,)),
        ("*.pos_embed", ()),
        ("*.LayerNorm_*.*", ()),
        # classifier head (D, classes): vocab-style output split. Last
        # of the Dense rules: the Mlp rules above already claimed the
        # block MLPs (first match wins).
        ("*.Dense_*.kernel", (None, MODEL_AXIS)),
        ("*.Dense_*.bias", (MODEL_AXIS,)),
        ("*.hyperparams.*", ()),
        ("*", ()),
    ),
    min_sharded=12,
)

#: V-MoE family (models/vit.py MoeMlp + parallel/moe.py layout): the
#: ViT attention/MLP rules plus the expert/router split — expert
#: params (E, ...) shard their leading EXPERT dim over the model axis
#: (each model-rank owns E/m experts), the router stays replicated
#: (every token scores every expert locally; only expert compute is
#: distributed).
MOE_RULES = ShardingRules(
    name="moe",
    rules=(
        ("*.MoeMlp_*.router", ()),
        ("*.MoeMlp_*.w1", (MODEL_AXIS, None, None)),
        ("*.MoeMlp_*.b1", (MODEL_AXIS, None)),
        ("*.MoeMlp_*.w2", (MODEL_AXIS, None, None)),
        ("*.MoeMlp_*.b2", (MODEL_AXIS, None)),
    ) + VIT_RULES.rules,
    min_sharded=16,
)

#: ResNet family (models/resnet.py + nn/layers.py ConvBN): output
#: channels over the model axis for every conv and the dense head;
#: BN scale/bias/running stats replicated (they are per-channel
#: vectors XLA re-broadcasts anyway and sharding them buys nothing).
RESNET_RULES = ShardingRules(
    name="resnet",
    rules=(
        # no Conv bias rule: every conv in models/resnet.py is
        # use_bias=False (shard_check flags a bias rule as dead)
        ("*.Conv_*.kernel", (None, None, None, MODEL_AXIS)),
        ("*.Dense_*.kernel", (None, MODEL_AXIS)),
        ("*.Dense_*.bias", (MODEL_AXIS,)),
        ("*.BatchNorm_*.*", ()),
        ("*.hyperparams.*", ()),
        ("*", ()),
    ),
    min_sharded=16,
)

FAMILY_RULES = {
    "vit": VIT_RULES,
    "moe": MOE_RULES,
    "resnet": RESNET_RULES,
}

#: model-name prefix -> family (ordered: vmoe before vit)
_MODEL_PREFIXES = (
    ("vmoe", "moe"),
    ("vit", "vit"),
    ("resnet", "resnet"),
)


def rules_for(model_name: str) -> Optional[ShardingRules]:
    """The curated table for a model/config name (`vit_s16` -> vit,
    `vmoe_s16` -> moe, `resnet50` -> resnet), or None when the family
    has no table yet (callers fall back to `HeuristicRules` —
    explicitly, never silently)."""
    name = model_name.lower()
    if name in FAMILY_RULES:
        return FAMILY_RULES[name]
    for prefix, family in _MODEL_PREFIXES:
        if name.startswith(prefix):
            return FAMILY_RULES[family]
    return None


def get_rules(spec: str, model_name: str = ""):
    """CLI resolution of `--sharding-rules`:

    - a family name (`vit` / `moe` / `resnet`) -> that curated table;
    - `auto` -> `rules_for(model_name)`, REFUSING models without a
      table (the operator asked for declarative sharding; a silent
      heuristic fallback would recreate the 108 -> 34 incident);
    - `heuristic` -> the explicit `infer_tp_sharding` fallback.
    """
    spec = (spec or "").lower()
    if spec in FAMILY_RULES:
        return FAMILY_RULES[spec]
    if spec == "heuristic":
        return HeuristicRules()
    if spec == "auto":
        rules = rules_for(model_name)
        if rules is None:
            raise ShardingRuleError(
                f"--sharding-rules auto: no curated table for model "
                f"{model_name!r} (families: {sorted(FAMILY_RULES)}); "
                "pass --sharding-rules heuristic for the explicit "
                "size-heuristic fallback")
        return rules
    raise ShardingRuleError(
        f"unknown --sharding-rules value {spec!r}: expected one of "
        f"{sorted(FAMILY_RULES) + ['auto', 'heuristic']}")
