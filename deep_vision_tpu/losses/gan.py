"""GAN losses: DCGAN sigmoid-BCE and CycleGAN LSGAN/cycle/identity.

Parity targets: DCGAN/tensorflow/main.py:42-53 (BinaryCrossentropy from_logits
for G and D) and CycleGAN/tensorflow/train.py:14-17,58-72 (LSGAN = MSE against
ones/zeros, cycle-consistency L1 with lambda=10, identity L1 with lambda=5).
"""
from __future__ import annotations

import jax.numpy as jnp
import optax

CYCLE_LAMBDA = 10.0
IDENTITY_LAMBDA = 5.0


# -- DCGAN (non-saturating BCE) ---------------------------------------------

def bce_generator_loss(fake_logits):
    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(fake_logits, jnp.ones_like(fake_logits))
    )


def bce_discriminator_loss(real_logits, fake_logits):
    real = optax.sigmoid_binary_cross_entropy(real_logits, jnp.ones_like(real_logits))
    fake = optax.sigmoid_binary_cross_entropy(fake_logits, jnp.zeros_like(fake_logits))
    return jnp.mean(real) + jnp.mean(fake)


# -- LSGAN (CycleGAN) --------------------------------------------------------

def lsgan_generator_loss(fake_logits):
    return jnp.mean(jnp.square(fake_logits - 1.0))


def lsgan_discriminator_loss(real_logits, fake_logits):
    # 0.5 factor per the CycleGAN paper (slows D relative to G)
    return 0.5 * (
        jnp.mean(jnp.square(real_logits - 1.0)) + jnp.mean(jnp.square(fake_logits))
    )


def cycle_consistency_loss(real, reconstructed, weight: float = CYCLE_LAMBDA):
    return weight * jnp.mean(jnp.abs(real - reconstructed))


def identity_loss(real, same, weight: float = IDENTITY_LAMBDA):
    return weight * jnp.mean(jnp.abs(real - same))
