#!/usr/bin/env python
"""`python train.py -m <config> [-c ckpt]` — see deep_vision_tpu/train_cli.py.

The single entry point replacing the reference's 12 per-model train scripts
(`python train.py -m resnet50` contract, ResNet/pytorch/train.py:541-562).
"""
from deep_vision_tpu.train_cli import main

if __name__ == "__main__":
    raise SystemExit(main())
