"""Goodput plane (obs/goodput.py): the wall-clock partition invariant,
the attribution rules (failure windows land in their NAMED buckets),
the live meter's interval/summary emission, the device-prefetch
no-double-count contract, and the check_journal schema drift guards."""
import json
import time

from deep_vision_tpu.obs import RunJournal, read_journal
from deep_vision_tpu.obs.goodput import (
    GOODPUT_BUCKETS,
    OWN_EVENTS,
    GoodputAccountant,
    GoodputMeter,
    attribute_journal,
)
from deep_vision_tpu.obs.registry import Registry


def row(event: str, ts: float, **fields) -> dict:
    return {"event": event, "ts": ts, "run_id": "r1", **fields}


def feed(rows):
    acc = GoodputAccountant()
    for r in rows:
        acc.observe(r)
    return acc


def assert_partition(acc: GoodputAccountant):
    """The signature invariant: buckets sum to wall clock EXACTLY —
    attribution can only mislabel seconds, never lose or mint them."""
    assert abs(acc.total_s() - acc.wall_s()) < 1e-6, acc.buckets
    assert acc.imbalance_frac() < 1e-6


# -- the accountant: attribution rules ----------------------------------------

class TestAccountant:
    def test_empty_and_single_row(self):
        acc = GoodputAccountant()
        assert acc.wall_s() == 0.0 and acc.goodput_frac() == 0.0
        acc.observe(row("note", 100.0))
        assert acc.wall_s() == 0.0  # first row anchors, claims nothing
        assert_partition(acc)

    def test_rows_without_ts_ignored(self):
        acc = feed([row("note", 100.0), {"event": "note"},
                    {"event": "note", "ts": "nan-ish"},
                    {"event": "note", "ts": True},  # bool is not a time
                    row("note", 103.0)])
        assert acc.wall_s() == 3.0
        assert_partition(acc)

    def test_backward_ts_claims_nothing(self):
        acc = feed([row("note", 100.0), row("note", 110.0),
                    row("note", 105.0)])  # cross-writer clock skew
        assert acc.wall_s() == 10.0
        assert_partition(acc)

    def test_step_splits_gap_by_stepclock_fields(self):
        # 10 s gap: 2 s data wait + 3 s compile + 3 s productive
        # (step_time 8 s minus wait minus compile), 2 s unclaimed.
        acc = feed([row("note", 100.0),
                    row("step", 110.0, step=1, data_wait_ms=2000.0,
                        compile_ms=3000.0, step_time_ms=8000.0)])
        b = acc.buckets
        assert abs(b["data_wait"] - 2.0) < 1e-9
        assert abs(b["compile"] - 3.0) < 1e-9
        assert abs(b["productive_step"] - 3.0) < 1e-9
        assert abs(b["overhead"] - 2.0) < 1e-9
        assert_partition(acc)
        assert abs(acc.goodput_frac() - 0.3) < 1e-9

    def test_step_fields_clamped_to_gap(self):
        # stamps larger than the gap can never inflate the partition
        acc = feed([row("note", 100.0),
                    row("step", 101.0, step=1, data_wait_ms=5000.0,
                        step_time_ms=9000.0)])
        assert abs(acc.buckets["data_wait"] - 1.0) < 1e-9
        assert acc.buckets["productive_step"] == 0.0
        assert_partition(acc)

    def test_host_loss_recovery_window(self):
        # host_lost opens the window; rendezvous carves its stamp; the
        # first post-resize step CLOSES it — recovery is not over until
        # training steps again. Nothing lands in `overhead` after the
        # loss: the smoke-pinned labeling contract.
        acc = feed([
            row("step", 100.0, step=1, step_time_ms=10.0),
            row("host_lost", 101.0, host="h3"),
            row("note", 105.0, note="supervisor respawning"),
            row("world_resized", 108.0, rendezvous_wait_s=2.0),
            row("step", 110.0, step=2, step_time_ms=500.0),
            row("step", 111.0, step=3, step_time_ms=1000.0),
        ])
        b = acc.buckets
        assert abs(b["rendezvous_wait"] - 2.0) < 1e-9
        # 4 s (lost->note) + 1 s (resize remainder) + 1.5 s of the
        # post-resize step gap not explained by step_time
        assert abs(b["host_loss_recovery"] - 6.5) < 1e-9
        # the step after the closing step is ordinary again
        assert abs(b["productive_step"] - (0.5 + 1.0)) < 1e-9
        # only the PRE-loss second is overhead; the outage window never is
        assert abs(b["overhead"] - 1.0) < 1e-9
        assert_partition(acc)

    def test_replica_respawn_brackets(self):
        acc = feed([
            row("note", 100.0),
            row("replica_lost", 101.0, replica="r0"),
            row("replica_recovered", 104.0, replica="r0"),
            row("note", 106.0),
        ])
        b = acc.buckets
        assert abs(b["replica_respawn"] - 3.0) < 1e-9
        assert abs(b["overhead"] - 3.0) < 1e-9  # 1 s before + 2 s after
        assert_partition(acc)

    def test_overlapping_replica_losses_keep_window_open(self):
        acc = feed([
            row("replica_lost", 100.0, replica="r0"),
            row("replica_lost", 101.0, replica="r1"),
            row("replica_recovered", 103.0, replica="r0"),
            # r1 still down: ambient seconds stay respawn-labeled
            row("note", 105.0),
            row("replica_recovered", 106.0, replica="r1"),
            row("note", 107.0),
        ])
        assert abs(acc.buckets["replica_respawn"] - 6.0) < 1e-9
        assert abs(acc.buckets["overhead"] - 1.0) < 1e-9
        assert_partition(acc)

    def test_excache_window_credit_prevents_double_count(self):
        # miss->store window attributes 3 s of compile; the next step's
        # compile_ms delta (4 s) covers the SAME backend compile, so only
        # the uncredited 1 s lands on the step — total compile == 4 s,
        # not 7.
        acc = feed([
            row("note", 100.0),
            row("excache_miss", 101.0, key="k"),
            row("excache_store", 104.0, key="k"),
            row("step", 106.0, step=1, compile_ms=4000.0,
                step_time_ms=6000.0),
        ])
        assert abs(acc.buckets["compile"] - 4.0) < 1e-9
        assert abs(acc.buckets["productive_step"] - 1.0) < 1e-9
        assert_partition(acc)

    def test_excache_hit_without_open_window_is_ambient(self):
        acc = feed([row("note", 100.0),
                    row("excache_hit", 102.0, key="k")])
        assert acc.buckets["compile"] == 0.0
        assert abs(acc.buckets["overhead"] - 2.0) < 1e-9
        assert_partition(acc)

    def test_open_compile_window_owns_ambient_time(self):
        acc = feed([row("excache_miss", 100.0, key="k"),
                    row("note", 103.0)])
        assert abs(acc.buckets["compile"] - 3.0) < 1e-9
        assert_partition(acc)

    def test_checkpoint_and_restore_carve_their_stamps(self):
        acc = feed([
            row("note", 100.0),
            row("checkpoint", 103.0, step=10, saved=True, save_ms=2000.0),
            row("note", 104.0, note="resumed", restore_ms=500.0),
        ])
        assert abs(acc.buckets["checkpoint"] - 2.5) < 1e-9
        assert abs(acc.buckets["overhead"] - 1.5) < 1e-9
        assert_partition(acc)

    def test_unstamped_checkpoint_claims_whole_gap(self):
        # older journals: no save_ms — the row directly follows the work
        acc = feed([row("note", 100.0),
                    row("checkpoint", 103.0, step=1, saved=True)])
        assert abs(acc.buckets["checkpoint"] - 3.0) < 1e-9
        assert_partition(acc)

    def test_serve_drain_carves_drain_s(self):
        acc = feed([row("note", 100.0),
                    row("serve_drain", 104.0, mode="close", drain_s=1.5)])
        assert abs(acc.buckets["drain"] - 1.5) < 1e-9
        assert abs(acc.buckets["overhead"] - 2.5) < 1e-9
        assert_partition(acc)

    def test_transport_ok_latency_is_productive(self):
        acc = feed([
            row("note", 100.0),
            row("transport_request", 102.0, outcome="ok", status=200,
                latency_ms=500.0),
            row("transport_request", 103.0, outcome="error", status=500,
                latency_ms=800.0),
        ])
        b = acc.buckets
        assert abs(b["productive_step"] - 0.5) < 1e-9  # errors earn nothing
        assert abs(b["overhead"] - 2.5) < 1e-9
        assert_partition(acc)

    def test_advance_attributes_ambient(self):
        acc = GoodputAccountant()
        acc.observe(row("host_lost", 100.0))
        acc.advance(107.0)  # interval emission mid-outage
        assert abs(acc.buckets["host_loss_recovery"] - 7.0) < 1e-9
        acc.advance(90.0)  # backward advance is a no-op
        assert acc.wall_s() == 7.0
        assert_partition(acc)

    def test_snapshot_shape(self):
        acc = feed([row("note", 100.0),
                    row("step", 101.0, step=1, step_time_ms=1000.0)])
        snap = acc.snapshot()
        assert snap["wall_s"] == 1.0
        assert set(snap["buckets"]) == set(GOODPUT_BUCKETS)
        assert 0.0 <= snap["goodput_frac"] <= 1.0
        assert snap["imbalance_frac"] == 0.0

    def test_invariant_over_mixed_stream(self):
        # every event type in one stream; the partition cannot leak
        rows = [
            row("run_manifest", 100.0, kind="train"),
            row("excache_miss", 101.0, key="k"),
            row("excache_store", 103.5, key="k"),
            row("step", 105.0, step=1, data_wait_ms=300.0,
                compile_ms=2500.0, step_time_ms=1400.0),
            row("checkpoint", 107.0, step=1, saved=True, save_ms=900.0),
            row("host_lost", 108.0, host="h1"),
            row("world_resized", 111.0, rendezvous_wait_s=1.2),
            row("step", 112.0, step=2, step_time_ms=700.0),
            row("replica_lost", 113.0, replica="r0"),
            row("replica_recovered", 115.5, replica="r0"),
            row("transport_request", 116.0, outcome="ok", status=200,
                latency_ms=250.0),
            row("serve_drain", 118.0, mode="close", drain_s=0.7),
            row("goodput_interval", 118.5, dur_s=18.5, buckets={}),
            row("exit", 119.0, status="clean_exit"),
        ]
        acc = attribute_journal(rows + ["not-a-dict"])
        assert acc.wall_s() == 19.0
        assert_partition(acc)
        assert acc.buckets["rendezvous_wait"] > 0
        assert acc.buckets["replica_respawn"] > 0
        assert acc.buckets["host_loss_recovery"] > 0


# -- satellite: device-prefetch double-count audit ----------------------------

class TestPrefetchNoDoubleCount:
    def test_depth2_prefetch_hides_placement_from_data_wait(self, tmp_path):
        """The StepClock/goodput contract pinned: with a depth-2
        DevicePrefetcher the producer's device_put time overlaps the
        previous step's compute, so iter_data's next() timer must NOT
        see it — those seconds live inside step_time_ms (productive)
        and are never double-counted as data_wait."""
        from deep_vision_tpu.data.device_prefetch import (
            DevicePrefetcher,
            PlacedBatch,
        )
        from deep_vision_tpu.obs.stepclock import StepClock

        place_s, step_s, n_batches = 0.05, 0.06, 6

        def place_one(batch):  # the simulated H2D transfer
            time.sleep(place_s)
            return PlacedBatch(batch, n=8)

        reg = Registry()
        j = RunJournal(str(tmp_path / "run.jsonl"), kind="train")
        clock = StepClock(registry=reg, journal=j, sample_every=1000,
                          track_memory=False)
        pf = DevicePrefetcher(place_one, depth=2, registry=reg)
        for placed in clock.iter_data(pf(iter([object()] * n_batches))):
            with clock.step(batch_size=placed.n):
                time.sleep(step_s)  # the overlapped device compute
        j.close()

        steps = [e for e in read_journal(j.path) if e.get("event") == "step"]
        assert len(steps) == n_batches
        # warmup (first get) legitimately waits for the first placement;
        # every later next() must return well under one placement time
        for e in steps[1:]:
            assert e["data_wait_ms"] < place_s * 1e3 * 0.6, steps
        # and the goodput ledger agrees: waits are a sliver, the
        # partition holds exactly
        acc = attribute_journal(read_journal(j.path))
        assert_partition(acc)
        assert acc.buckets["data_wait"] < acc.buckets["productive_step"]


# -- the live meter -----------------------------------------------------------

class TestMeter:
    def test_interval_emission_and_terminal_summary(self, tmp_path):
        reg = Registry()
        j = RunJournal(str(tmp_path / "run.jsonl"), kind="train")
        meter = GoodputMeter(journal=j, registry=reg, interval_s=5.0)
        base = round(time.time(), 3)
        # explicit ts: the meter runs on EVENT time, not the wall clock
        j.write("note", ts=base)
        j.write("note", ts=round(base + 6.0, 3))
        iv = [e for e in read_journal(j.path)
              if e.get("event") == "goodput_interval"]
        assert len(iv) == 1
        assert iv[0]["dur_s"] == 6.0
        assert set(iv[0]["buckets"]) == set(GOODPUT_BUCKETS)
        assert abs(iv[0]["buckets"]["overhead"] - 6.0) < 0.002
        assert 0.0 <= iv[0]["goodput_frac"] <= 1.0
        # close() via the journal closer: summary lands BEFORE exit
        j.close()
        events = [e["event"] for e in read_journal(j.path)]
        assert events.index("goodput_summary") < events.index("exit")
        summary = next(e for e in read_journal(j.path)
                       if e["event"] == "goodput_summary")
        assert summary["wall_s"] >= 6.0
        assert summary["imbalance_frac"] <= 0.02
        # gauges updated on close; idempotent re-close
        assert reg.gauge("goodput_frac").value == summary["goodput_frac"]
        assert meter.close() is None

    def test_own_events_never_retrigger_emission(self, tmp_path):
        j = RunJournal(str(tmp_path / "run.jsonl"), kind="train")
        GoodputMeter(journal=j, interval_s=1.0)
        base = round(time.time(), 3)
        j.write("note", ts=base)
        for i, ev in enumerate(OWN_EVENTS):
            j.write(ev, ts=round(base + 100.0 * (i + 1), 3))
        # only REAL rows advance the emission clock: the meter emitted
        # nothing (its interval rows carry dur_s; the bare rows are ours)
        iv = [e for e in read_journal(j.path)
              if e.get("event") == "goodput_interval" and "dur_s" in e]
        assert iv == []
        j.close()

    def test_interval_rows_are_deltas_that_sum_to_totals(self, tmp_path):
        j = RunJournal(str(tmp_path / "run.jsonl"), kind="train")
        meter = GoodputMeter(journal=j, interval_s=2.0)
        base = round(time.time(), 3)
        j.write("note", ts=base)
        j.write("host_lost", ts=round(base + 3.0, 3), host="h0")
        j.write("step", ts=round(base + 6.0, 3), step=1,
                step_time_ms=1000.0)
        rows = read_journal(j.path)
        iv = [e for e in rows if e.get("event") == "goodput_interval"]
        assert len(iv) == 2
        for b in GOODPUT_BUCKETS:
            total = sum(e["buckets"][b] for e in iv)
            assert abs(total - meter.snapshot()["buckets"][b]) < 0.01, b
        j.close()

    def test_telemetry_status_shape(self):
        meter = GoodputMeter()
        meter.tap(row("note", 100.0))
        meter.tap(row("step", 101.0, step=1, step_time_ms=1000.0))
        st = meter.telemetry_status()
        assert st["goodput_frac"] == 1.0
        assert st["wall_s"] == 1.0
        assert st["imbalance_frac"] == 0.0
        assert set(st["buckets"]) == set(GOODPUT_BUCKETS)


# -- offline == live ----------------------------------------------------------

class TestOfflineReplay:
    def test_replay_matches_live_accounting(self, tmp_path):
        j = RunJournal(str(tmp_path / "run.jsonl"), kind="train")
        meter = GoodputMeter(journal=j, interval_s=3.0)
        base = round(time.time(), 3)
        j.write("note", ts=base)
        j.write("excache_miss", ts=round(base + 1.0, 3), key="k")
        j.write("excache_store", ts=round(base + 2.5, 3), key="k")
        j.write("step", ts=round(base + 4.0, 3), step=1,
                compile_ms=1500.0, step_time_ms=2500.0)
        live = meter.snapshot()
        # replay the file THROUGH the same algorithm: the interval rows
        # it emitted ride along as ambient rows, and the buckets agree
        acc = attribute_journal(read_journal(j.path))
        for b in GOODPUT_BUCKETS:
            assert abs(acc.buckets[b] - live["buckets"][b]) < 0.01, b
        j.close()


# -- schema drift guards ------------------------------------------------------

class TestSchema:
    def test_bucket_enum_does_not_drift(self):
        from tools.check_journal import GOODPUT_BUCKETS as CJ_BUCKETS

        assert set(GOODPUT_BUCKETS) == CJ_BUCKETS

    def test_emitter_matches_strict_schema(self, tmp_path):
        """The real meter's events pass the strict checker — the
        PR-13-style drift guard between obs/goodput.py and
        tools/check_journal.py."""
        from tools.check_journal import check_journal

        j = RunJournal(str(tmp_path / "run.jsonl"), kind="train")
        GoodputMeter(journal=j, interval_s=2.0)
        j.manifest(config={"name": "t", "task": "clf"})
        base = round(time.time(), 3)
        j.write("note", ts=round(base + 3.0, 3))
        j.close()
        events = [e["event"] for e in read_journal(j.path)]
        assert "goodput_interval" in events
        assert "goodput_summary" in events
        assert check_journal(j.path, strict=True) == []

    def test_strict_rejects_bad_buckets(self, tmp_path):
        from tools.check_journal import check_journal

        path = str(tmp_path / "j.jsonl")
        base = {"ts": time.time(), "run_id": "r1"}
        rows = [
            {"event": "run_manifest", "kind": "train", "argv": [], **base},
            {"event": "goodput_summary", "wall_s": 10.0,
             "goodput_frac": 0.5, "imbalance_frac": 0.0,
             "buckets": {"productive_step": 5.0, "not_a_bucket": 5.0},
             **base},
            {"event": "goodput_interval", "dur_s": -1.0,
             "buckets": {"compile": -2.0}, **base},
            {"event": "exit", "status": "clean_exit", **base},
        ]
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        errs = check_journal(path, strict=True)
        assert any("not_a_bucket" in e for e in errs), errs
        assert any("dur_s" in e for e in errs), errs
        assert any("compile" in e for e in errs), errs
