"""A/B: default vs AUTO (compiler-chosen) parameter layouts (round 4).

The compiled train step contains per-execution layout copies of its inputs
(hbm_breakdown_r04: the batch image enters as default row-major and is
copied to the conv-friendly layout every step, ~150 MB/step). Compiling
with `Format(Layout.AUTO)` lets XLA pick the parameter layouts it actually
computes in, and `jax.device_put` stages the (never-changing) batch in that
layout ONCE — the per-step copies vanish from the executable.

Interleaved same-process A/B (session drift is +-4%; see
artifacts/dispatch_r04.json for why windows close with a scalar fetch).
Writes artifacts/layout_probe_r04.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

WINDOW = 50
REPS = 3


def _log(m):
    print(f"layout_probe: {m}", file=sys.stderr, flush=True)


def main(out_path="artifacts/layout_probe_r04.json"):
    import jax
    from jax.experimental.layout import Format, Layout

    art = {"what": __doc__.split("\n")[0], "window": WINDOW, "reps": REPS}

    _log("building the flagship step (bench.make_train_parts)")
    train_step, state, batch, batch_size, n_chips, devices = (
        bench.make_train_parts(256)
    )

    _log("compiling A (default layouts)")
    step_a = jax.jit(train_step, donate_argnums=0).lower(state, batch).compile()

    _log("compiling B (AUTO layouts)")
    auto = Format(Layout.AUTO)
    jitted_b = jax.jit(train_step, donate_argnums=0,
                       in_shardings=jax.tree.map(lambda _: auto,
                                                 (state, batch)),
                       out_shardings=jax.tree.map(
                           lambda _: auto,
                           jax.eval_shape(train_step, state, batch)))
    # AUTO layouts require abstract avals at lower time
    st_sds = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), state
    )
    bt_sds = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), batch
    )
    step_b = jitted_b.lower(st_sds, bt_sds).compile()
    in_fmts = step_b.input_formats
    # stage a SECOND copy of state+batch in the chosen formats
    state_b = jax.tree.map(jax.device_put, state, in_fmts[0][0])
    batch_b = jax.tree.map(jax.device_put, batch, in_fmts[0][1])

    for name, stp in (("default", step_a), ("auto", step_b)):
        try:
            ca = stp.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            art[f"bytes_gb_{name}"] = round(float(ca["bytes accessed"]) / 1e9,
                                            3)
        except Exception as e:
            art[f"bytes_gb_{name}"] = None
            _log(f"cost_analysis {name}: {e}")
    _log(f"bytes: default {art.get('bytes_gb_default')} GB, "
         f"auto {art.get('bytes_gb_auto')} GB")

    # warmup both
    sa, sb = state, state_b
    for _ in range(3):
        sa, la = step_a(sa, batch)
        sb, lb = step_b(sb, batch_b)
    float(la), float(lb)

    walls = {"default": [], "auto": []}
    for rep in range(REPS):
        for name in ("default", "auto"):
            t0 = time.perf_counter()
            if name == "default":
                for _ in range(WINDOW):
                    sa, la = step_a(sa, batch)
                float(la)
            else:
                for _ in range(WINDOW):
                    sb, lb = step_b(sb, batch_b)
                float(lb)
            dt = (time.perf_counter() - t0) * 1e3 / WINDOW
            walls[name].append(dt)
            _log(f"rep {rep} {name}: {dt:.2f} ms/step")
    art["wall_ms_per_step"] = {k: [round(v, 2) for v in vs]
                               for k, vs in walls.items()}
    art["median_wall_ms"] = {k: round(float(np.median(v)), 2)
                             for k, v in walls.items()}
    # device time for both
    for name, stp, st, bt in (("default", step_a, sa, batch),
                              ("auto", step_b, sb, batch_b)):
        dev = bench._device_step_ms(stp, st, bt, 1)
        art[f"device_ms_{name}"] = round(dev, 2) if dev else None
        _log(f"device {name}: {dev and round(dev, 2)} ms/step")

    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(art, f, indent=2)
    _log(f"wrote {out_path}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else
         "artifacts/layout_probe_r04.json")
