"""Shared flax building blocks for the whole model zoo.

The reference re-implements these per model (e.g. `BasicConv2d` at
Inception/pytorch/models/inception_v1.py, `DarknetConv` at
YOLO/tensorflow/yolov3.py:23-41, custom `SeparableConv2D` at
MobileNet/tensorflow/models/mobilenet_v1.py:7-26). Here they are written once,
NHWC, TPU-native:

- depthwise/group conv lowers to `lax.conv_general_dilated` with
  `feature_group_count` (the XLA-native form of torch's `groups=`);
- BatchNorm under pjit computes batch statistics over the *global* batch
  (XLA inserts the cross-replica psum), i.e. synced BN by construction —
  resolving the DataParallel+BN pitfall the reference documents at
  ResNet/pytorch/train.py:348-349;
- LocalResponseNorm (AlexNet V1, alexnet_v1.py:33-89) is a vectorized
  channel-window sum, fused by XLA.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

INITIALIZERS = {
    "he_normal": nn.initializers.he_normal(),
    "he_uniform": nn.initializers.he_uniform(),
    "xavier_normal": nn.initializers.xavier_normal(),
    "xavier_uniform": nn.initializers.xavier_uniform(),
    "lecun_normal": nn.initializers.lecun_normal(),
    "normal02": nn.initializers.normal(0.02),  # DCGAN init
}


def global_avg_pool(x):
    """NHWC -> NC global average pool (replaces AdaptiveAvgPool2d(1))."""
    return jnp.mean(x, axis=(1, 2))


def channel_shuffle(x, groups: int):
    """ShuffleNet channel shuffle: (B,H,W,g*c) -> transpose group/channel.

    The reference never implemented this (shufflenet_v1.py is a 0-byte file,
    SURVEY.md §2.9); written from the ShuffleNet paper (sec 3.1).
    """
    b, h, w, c = x.shape
    assert c % groups == 0, f"channels {c} not divisible by groups {groups}"
    x = x.reshape(b, h, w, groups, c // groups)
    x = jnp.swapaxes(x, 3, 4)
    return x.reshape(b, h, w, c)


class LocalResponseNorm(nn.Module):
    """AlexNet V1's LRN (alexnet_v1.py:42,52): across-channel normalization."""

    size: int = 5
    alpha: float = 1e-4
    beta: float = 0.75
    k: float = 2.0

    @nn.compact
    def __call__(self, x):
        half = self.size // 2
        sq = jnp.square(x)
        # sum over a channel window via padded cumulative trick
        padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
        window = sum(
            jax.lax.dynamic_slice_in_dim(padded, i, x.shape[-1], axis=x.ndim - 1)
            for i in range(self.size)
        )
        return x / jnp.power(self.k + self.alpha * window, self.beta)


class BatchNorm(nn.Module):
    """BatchNorm that never materializes the activation tensor in float32.

    flax's `nn.BatchNorm` promotes the full activation to f32 to compute
    statistics and to normalize; on a bandwidth-bound TPU that doubles the
    HBM traffic of every BN layer (measured: 12% of a ResNet bottleneck
    block's train-step time on v5e). Here the big tensor stays in its input
    dtype end to end: statistics accumulate in f32 inside the reduction
    (one fused E[x], E[x^2] pass), and normalization is folded to a single
    per-channel multiply-add `x * a + b` computed in the activation dtype.

    Semantics match `nn.BatchNorm(use_fast_variance=True)`: biased batch
    variance, EMA running stats under the same `batch_stats` names
    (`mean`, `var`), and global-batch statistics under pjit (the batch-axis
    `jnp.mean` spans the sharded global batch, so XLA inserts the
    cross-replica psum: synced BN by construction, resolving the
    DataParallel+BN pitfall at ResNet/pytorch/train.py:348-349).
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros
    dtype: Optional[jnp.dtype] = None  # output/compute dtype; None = x.dtype
    # act='relu' (and/or a `residual` call arg) folds the activation and the
    # skip-add into the normalize. With the Pallas fusion enabled
    # (ops/pallas/bn_act.fusion_enabled: TPU default, DVT_PALLAS_FUSED
    # forces) the whole tail runs as ONE kernel pass — the big tensor
    # crosses HBM once instead of once per op; disabled, the math is the
    # exact pre-kernel sequence so existing numerics never drift.
    act: Optional[str] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None,
                 residual=None):
        use_ra = (
            self.use_running_average
            if use_running_average is None
            else use_running_average
        )
        c = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))
        scale = self.param("scale", self.scale_init, (c,), jnp.float32)
        bias = self.param("bias", self.bias_init, (c,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32)
        )
        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            # one pass over x: f32 accumulation without an f32 materialization
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            mean2 = jnp.mean(jnp.square(xf), axis=reduce_axes)
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        inv = scale * jax.lax.rsqrt(var + self.epsilon)
        dt = self.dtype or x.dtype
        if self.act is not None or residual is not None:
            from deep_vision_tpu.ops.pallas import bn_act as _bn_act

            if _bn_act.fusion_enabled():
                # folded apply (x*a + b) is safe here: the kernel computes
                # in f32 internally, so the bf16-cancellation concern below
                # does not apply inside it
                y = _bn_act.fused_scale_bias_act(
                    x, inv, bias - mean * inv, residual=residual,
                    act=self.act)
                return y.astype(dt)
            y = (x.astype(jnp.float32) - mean) * inv + bias
            if residual is not None:
                y = y + residual.astype(jnp.float32)
            if self.act == "relu":
                y = jnp.maximum(y, 0.0)
            elif self.act is not None:
                raise ValueError(f"unsupported act {self.act!r}")
            return y.astype(dt)
        # normalize in f32 *inside the fusion*: per-element upcast costs no
        # HBM traffic (XLA fuses the converts), and subtracting the mean
        # before scaling avoids the bf16 cancellation of a folded x*a + b
        # when |mean| >> std
        y = (x.astype(jnp.float32) - mean) * inv + bias
        return y.astype(dt)


# explicit-intent alias: `BatchNorm` keeps flax's auto-naming producing the
# same `BatchNorm_N` variable-tree paths as `nn.BatchNorm` did, so swapping
# the implementation never invalidates a checkpoint
FusedBatchNorm = BatchNorm


class ConvBN(nn.Module):
    """Conv + BatchNorm + activation, the universal CNN building block."""

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str | Sequence[Tuple[int, int]] = "SAME"
    groups: int = 1
    use_bn: bool = True
    use_bias: bool = False
    act: Optional[Callable] = nn.relu
    kernel_init: Callable = nn.initializers.he_normal()
    bn_momentum: float = 0.9
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True, residual=None):
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=self.strides,
            padding=self.padding,
            feature_group_count=self.groups,
            use_bias=self.use_bias or not self.use_bn,
            kernel_init=self.kernel_init,
            dtype=self.dtype,
        )(x)
        if self.use_bn:
            # ReLU (and a skip tensor, when the caller passes one) fold into
            # the BN apply — one fused pass on TPU (ops/pallas/bn_act.py),
            # the identical unfused sequence elsewhere
            fuse_relu = self.act is nn.relu
            x = FusedBatchNorm(
                use_running_average=not train,
                momentum=self.bn_momentum,
                act="relu" if fuse_relu else None,
            )(x, residual=residual)
            if self.act is not None and not fuse_relu:
                x = self.act(x)
            return x
        if residual is not None:
            x = x + residual
        if self.act is not None:
            x = self.act(x)
        return x


class DepthwiseSeparableConv(nn.Module):
    """MobileNet's depthwise 3x3 + pointwise 1x1 (mobilenet_v1.py:109-122).

    Depthwise = grouped conv with feature_group_count == in_channels; XLA
    lowers this to a TPU-native depthwise convolution.
    """

    features: int  # pointwise output channels
    strides: Tuple[int, int] = (1, 1)
    act: Callable = nn.relu
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        in_ch = x.shape[-1]
        x = ConvBN(
            features=in_ch,
            kernel=(3, 3),
            strides=self.strides,
            groups=in_ch,
            act=self.act,
            dtype=self.dtype,
        )(x, train)
        x = ConvBN(
            features=self.features, kernel=(1, 1), act=self.act, dtype=self.dtype
        )(x, train)
        return x
