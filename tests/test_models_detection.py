"""Shape tests for detection / pose / generative models (small inputs for CPU)."""
import jax
import jax.numpy as jnp
import pytest

from deep_vision_tpu.models import get_model

pytestmark = pytest.mark.slow  # jit-heavy: excluded from the fast tier (`-m "not slow"`)

RNG = jax.random.PRNGKey(0)


def _init_apply(model, x, train=False):
    variables = model.init({"params": RNG, "dropout": RNG}, x, train=train)
    return model.apply(variables, x, train=train)


def test_yolov3_three_scales():
    model = get_model("yolov3", num_classes=6)
    out = _init_apply(model, jnp.zeros((1, 64, 64, 3)))
    assert len(out) == 3
    assert out[0].shape == (1, 2, 2, 3, 11)   # /32
    assert out[1].shape == (1, 4, 4, 3, 11)   # /16
    assert out[2].shape == (1, 8, 8, 3, 11)   # /8


def test_darknet53_feature_pyramid():
    model = get_model("darknet53")
    c3, c4, c5 = _init_apply(model, jnp.zeros((1, 64, 64, 3)))
    assert c3.shape == (1, 8, 8, 256)
    assert c4.shape == (1, 4, 4, 512)
    assert c5.shape == (1, 2, 2, 1024)


def test_hourglass_stacked_heatmaps():
    model = get_model("hourglass", num_stack=2, num_heatmap=4)
    out = _init_apply(model, jnp.zeros((1, 64, 64, 3)))
    assert len(out) == 2
    for hm in out:
        assert hm.shape == (1, 16, 16, 4)  # /4 resolution


def test_objects_as_points_heads():
    model = get_model("objects_as_points", num_classes=3, num_stack=1)
    out = _init_apply(model, jnp.zeros((1, 128, 128, 3)))
    assert len(out) == 1
    head = out[0]
    assert head["heatmap"].shape == (1, 32, 32, 3)
    assert head["wh"].shape == (1, 32, 32, 2)
    assert head["offset"].shape == (1, 32, 32, 2)


def test_cyclegan_generator_preserves_shape():
    model = get_model("cyclegan_generator", n_blocks=1, base=8)
    out = _init_apply(model, jnp.zeros((1, 64, 64, 3)))
    assert out.shape == (1, 64, 64, 3)


def test_patchgan_downsamples_8x():
    model = get_model("cyclegan_discriminator", base=8)
    out = _init_apply(model, jnp.zeros((1, 64, 64, 3)))
    assert out.shape == (1, 8, 8, 1)
