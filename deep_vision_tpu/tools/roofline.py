"""A defensible HBM bound for the flagship step — shape math, not guesses.

Round 3 claimed "92% of HBM roofline" from XLA's `cost_analysis()` "bytes
accessed"; round 4 disqualified that number (at batch 128 it implies
946 GB/s, above the v5e's 819 GB/s pin limit — VMEM-served reads count, so
it over-counts real HBM traffic and cannot anchor a roofline). This tool
replaces it with two defensible quantities:

1. `analytic` — a per-layer activation+param+grad traffic model computed
   from the architecture's shapes alone (this framework knows every conv's
   in/out tensor). The dataflow assumptions are explicit and FUSION-OPTIMAL
   (each tensor crosses HBM the minimum number of times a conv-boundary
   dataflow permits), so the result is a LOWER bound on real traffic: real
   XLA schedules can only move more bytes, never fewer.
2. `measured` (needs the chip) — profiler DMA/copy-event totals over a
   traced window, the tunnel's one reliable per-event signal
   (memory: the axon profile exposes DMA events but no per-op compute), and
   the device step time from the "XLA Modules" line (bench._device_step_ms
   method).

The verdict logic is printed and recorded: if `analytic / peak_bw` accounts
for (most of) the device step time, the step is memory-bound and the bound
names the biggest per-layer consumers to attack next; if it does NOT (the
r4 numbers put the fusion-optimal bound well under the 46 ms step), then
"HBM-bound" is unsupported at the optimal-dataflow limit and the gap is
compute/occupancy (MXU utilization of the actual conv shapes) — which is a
different optimization conversation than byte-cutting.

    python -m deep_vision_tpu.tools.roofline --analytic          # no chip
    python -m deep_vision_tpu.tools.roofline --out artifacts/roofline_r05.json
"""
from __future__ import annotations

import argparse
import json
import os
from typing import List, Optional

# v5e per-chip pins (How to Scale Your Model / public spec)
PEAK_HBM_GBS = 819.0
PEAK_BF16_TFLOPS = 197.0

ACT_BYTES = 2   # activations/activation-grads travel bf16
PAR_BYTES = 4   # params, weight grads, momentum are f32


def resnet50_conv_shapes(image: int = 224, width: int = 64,
                         stem: str = "s2d") -> List[dict]:
    """Every conv in the flagship ResNet-50 (models/resnet.py) as
    {name, h, w, cin, cout, k, stride} — the shape source for the traffic
    and FLOP models. Includes the bottleneck projection (downsample) convs.
    """
    # s2d: host space-to-depth ships (H/2, W/2, 12) and the stem conv is the
    # 4x4 reshaped twin of the 7x7/s2 (models/resnet.py SpaceToDepthStem);
    # either way the stem's output grid is image/2
    stem_args = (dict(cin=12, k=4, stride=1) if stem == "s2d"
                 else dict(cin=3, k=7, stride=2))
    layers = [dict(name="stem", h=image // 2, w=image // 2, cout=width,
                   **stem_args)]
    h = image // 2
    h //= 2  # maxpool /2
    stage_sizes = (3, 4, 6, 3)
    cin = width
    for i, n_blocks in enumerate(stage_sizes):
        feat = width * (2 ** i)
        for j in range(n_blocks):
            stride = 2 if (i > 0 and j == 0) else 1
            hout = h // stride
            pre = f"s{i}b{j}"
            layers.append(dict(name=f"{pre}.conv1", h=h, w=h, cin=cin,
                               cout=feat, k=1, stride=1))
            layers.append(dict(name=f"{pre}.conv2", h=h, w=h, cin=feat,
                               cout=feat, k=3, stride=stride))
            layers.append(dict(name=f"{pre}.conv3", h=hout, w=hout, cin=feat,
                               cout=4 * feat, k=1, stride=1))
            if j == 0:
                layers.append(dict(name=f"{pre}.proj", h=h, w=h, cin=cin,
                                   cout=4 * feat, k=1, stride=stride))
            cin = 4 * feat
            h = hout
    layers.append(dict(name="head", h=1, w=1, cin=cin, cout=1000, k=1,
                       stride=1))
    return layers


def analytic_traffic(batch: int, image: int = 224,
                     stem: str = "s2d") -> dict:
    """Fusion-optimal per-step HBM traffic lower bound, itemized per layer.

    Dataflow model (each line is an explicit assumption, all minimal):
      forward   — conv reads its input once, writes its output once (BN +
                  ReLU + residual-add ride the conv epilogue, as the
                  hbm_breakdown_r04 fusions show; the skip tensor is read
                  once more at the join)
      backward  — reads the saved input once (shared by dgrad and wgrad in
                  an ideal fusion), reads the output grad once, writes the
                  input grad once
      params    — SGD+momentum: weight read fwd + read bwd + grad write +
                  momentum read/write + weight write (6x param bytes)
    Activations bf16, params/grads/momentum f32.
    """
    layers = resnet50_conv_shapes(image, stem=stem)
    rows = []
    total_act = total_par = total_flops = 0
    for L in layers:
        hout, wout = L["h"] // L["stride"], L["w"] // L["stride"]
        a_in = batch * L["h"] * L["w"] * L["cin"] * ACT_BYTES
        a_out = batch * hout * wout * L["cout"] * ACT_BYTES
        # fwd: read in, write out; bwd: read in, read dout, write din
        act = 3 * a_in + 2 * a_out
        p = L["k"] * L["k"] * L["cin"] * L["cout"] * PAR_BYTES
        par = 6 * p
        flops = 2 * batch * hout * wout * L["k"] * L["k"] * L["cin"] * \
            L["cout"] * 3  # fwd + dgrad + wgrad
        rows.append({"layer": L["name"], "gb": round((act + par) / 1e9, 4),
                     "act_gb": round(act / 1e9, 4),
                     "gflops": round(flops / 1e9, 1)})
        total_act += act
        total_par += par
        total_flops += flops
    rows.sort(key=lambda r: -r["gb"])
    total = total_act + total_par
    itemized = sum(r["gb"] for r in rows)
    return {
        "assumptions": analytic_traffic.__doc__.strip().splitlines()[2:],
        "batch": batch,
        "total_gb": round(total / 1e9, 2),
        "itemized_total_gb": round(itemized, 2),  # sum over ALL layers; must
                                                  # equal total_gb
        "activation_gb": round(total_act / 1e9, 2),
        "param_gb": round(total_par / 1e9, 2),
        "train_tflops_per_step": round(total_flops / 1e12, 2),
        "min_step_ms_if_memory_bound": round(total / PEAK_HBM_GBS / 1e6, 2),
        "min_step_ms_if_compute_bound": round(
            total_flops / (PEAK_BF16_TFLOPS * 1e12) * 1e3, 2
        ),
        "top_layers": rows[:10],
    }


def measure_on_chip(batch: int) -> dict:
    """Chip-side: device step time (XLA Modules trace) + DMA-event byte
    totals from the same trace window, per step. Raises if the backend or
    trace is unavailable — callers record the analytic half regardless."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    import shutil
    import tempfile

    import jax
    import numpy as np

    import bench

    step, state, b, batch_size, n_chips, devices = bench.build_bench(batch, 1)
    for _ in range(3):
        state, loss = step(state, b)
    float(loss)

    tmpdir = tempfile.mkdtemp(prefix="dv_roofline_")
    try:
        jax.profiler.start_trace(tmpdir)
        n_steps = 10
        for _ in range(n_steps):
            state, loss = step(state, b)
        float(loss)
        jax.profiler.stop_trace()
        xs = bench.load_xspace(tmpdir)
        module_ms = []
        dma_bytes = 0
        dma_events = 0
        dma_names = {}
        for plane in xs.planes:
            if not plane.name.startswith("/device:TPU"):
                continue
            stat_names = {i: m.name for i, m in plane.stat_metadata.items()}
            ev_names = {i: m.name for i, m in plane.event_metadata.items()}
            for line in plane.lines:
                for ev in line.events:
                    name = ev_names.get(ev.metadata_id, "")
                    if line.name == "XLA Modules":
                        module_ms.append(ev.duration_ps / 1e9)
                        continue
                    size = None
                    for st in ev.stats:
                        sname = stat_names.get(st.metadata_id, "")
                        if "byte" in sname.lower() or "size" in sname.lower():
                            size = (st.uint64_value or st.int64_value)
                    if size:
                        dma_bytes += int(size)
                        dma_events += 1
                        key = name or line.name
                        dma_names[key] = dma_names.get(key, 0) + int(size)
        med_ms = float(np.median(module_ms)) if module_ms else None
        top = sorted(dma_names.items(), key=lambda kv: -kv[1])[:8]
        return {
            "device_kind": devices[0].device_kind,
            "device_step_ms": round(med_ms, 2) if med_ms else None,
            "traced_steps": n_steps,
            "dma_events": dma_events,
            "dma_gb_per_step": round(dma_bytes / n_steps / 1e9, 2)
            if dma_events else None,
            "dma_top_sources_gb": {k: round(v / n_steps / 1e9, 3)
                                   for k, v in top},
        }
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def load_bench_json(path: str) -> dict:
    """Accept either a raw bench.py JSON line/file or a driver BENCH_rNN.json
    wrapper (the flat dict lives under 'parsed')."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict) or "value" not in doc:
        raise ValueError(f"{path}: not a bench result "
                         "(need the bench.py JSON line or a BENCH_rNN.json)")
    return doc


def bench_position(bench: dict, analytic: dict) -> dict:
    """Where the MEASURED step and each analytic layer sit on the roofline,
    anchored to bench.py's own numbers (`hbm_gbytes_per_sec_per_chip`,
    `model_flops_per_image` from xla_cost_analysis, the MFU percentages)
    instead of re-deriving them.

    Per row: arithmetic intensity (flop/byte), the roofline bound at that
    intensity (min(MXU peak, intensity * pin bandwidth)), and achieved-vs-
    bound plus achieved-vs-the-30%-MFU-baseline — the gap this PR's three
    levers (prefetch, multistep, fused kernels) exist to close."""
    ridge = PEAK_BF16_TFLOPS * 1e12 / (PEAK_HBM_GBS * 1e9)  # flop/byte
    rows = []

    def row(name, tflops_achieved, intensity, extra=None):
        bound_tflops = min(PEAK_BF16_TFLOPS,
                           intensity * PEAK_HBM_GBS / 1e3)
        r = {
            "name": name,
            "intensity_flop_per_byte": round(intensity, 1),
            "bound": "compute" if intensity >= ridge else "memory",
            "roofline_tflops": round(bound_tflops, 1),
        }
        if tflops_achieved is not None:
            r["achieved_tflops"] = round(tflops_achieved, 1)
            r["pct_of_roofline"] = round(
                100 * tflops_achieved / bound_tflops, 1)
            r["vs_30pct_mfu_baseline"] = round(
                tflops_achieved / (0.30 * PEAK_BF16_TFLOPS), 2)
        if extra:
            r.update(extra)
        return r

    flops_per_image = bench.get("model_flops_per_image")  # GF, cost analysis
    gbs = bench.get("hbm_gbytes_per_sec_per_chip")
    for kind, rate_key, mfu_key in (
            ("wall", "value", "mfu_wall_pct"),
            ("device", "device_images_per_sec_per_chip", "mfu_device_pct")):
        rate = bench.get(rate_key)
        if not rate or not flops_per_image:
            continue
        achieved = rate * flops_per_image / 1e3  # TFLOP/s
        # intensity from the bench's own cost-analysis bytes (an HBM upper
        # bound — VMEM-served reads count — so the intensity is a LOWER
        # bound and the memory-bound verdict conservative; bench.py NB)
        gb_per_step = bench.get("hbm_gbytes_per_step_per_chip")
        bpc = bench.get("batch_per_chip") or 1
        intensity = (flops_per_image * bpc / gb_per_step
                     if gb_per_step else ridge)
        rows.append(row(
            f"train_step ({kind})", achieved, intensity,
            {"images_per_sec_per_chip": rate,
             "mfu_pct": bench.get(mfu_key)}))
    # per-layer placement from the analytic shape model: no achieved rate
    # per layer (the profile has no per-op split on this backend), but the
    # intensity says which kernels even CAN go fast — the low-intensity
    # rows are the fusion targets (ops/pallas/bn_act.py), the high ones
    # the MXU-occupancy targets
    for layer in analytic.get("top_layers", []):
        if layer.get("gb"):
            rows.append(row(layer["layer"], None,
                            layer["gflops"] / layer["gb"]))
    return {
        "peak_tflops": PEAK_BF16_TFLOPS,
        "peak_hbm_gbs": PEAK_HBM_GBS,
        "ridge_flop_per_byte": round(ridge, 1),
        "baseline_mfu_pct": 30.0,
        "bench_source": {k: bench.get(k) for k in (
            "metric", "value", "vs_baseline", "multistep",
            "mfu_wall_pct", "mfu_device_pct", "flops_source")},
        "rows": rows,
    }


def render_roofline(pos: dict) -> str:
    lines = [
        f"roofline: peak {pos['peak_tflops']:.0f} TF/s, "
        f"{pos['peak_hbm_gbs']:.0f} GB/s, ridge "
        f"{pos['ridge_flop_per_byte']:.0f} flop/B "
        f"(baseline = {pos['baseline_mfu_pct']:.0f}% MFU)"
    ]
    for r in pos["rows"]:
        s = (f"  {r['name']:<24} {r['intensity_flop_per_byte']:>8.1f} f/B "
             f"{r['bound']:<7} roof {r['roofline_tflops']:>6.1f} TF/s")
        if "achieved_tflops" in r:
            s += (f"  achieved {r['achieved_tflops']:>6.1f} TF/s "
                  f"({r['pct_of_roofline']:.0f}% of roof, "
                  f"{r['vs_30pct_mfu_baseline']:.2f}x the 30%-MFU baseline)")
        lines.append(s)
    return "\n".join(lines)


def verdict(analytic: dict, measured: Optional[dict]) -> str:
    mem_ms = analytic["min_step_ms_if_memory_bound"]
    mxu_ms = analytic["min_step_ms_if_compute_bound"]
    if not measured or not measured.get("device_step_ms"):
        return (f"analytic-only: fusion-optimal traffic "
                f"{analytic['total_gb']} GB needs >= {mem_ms} ms at "
                f"{PEAK_HBM_GBS:.0f} GB/s; MXU floor {mxu_ms} ms — "
                "measured step time required for the binding verdict")
    dev = measured["device_step_ms"]
    frac_mem = mem_ms / dev
    frac_mxu = mxu_ms / dev
    dma = measured.get("dma_gb_per_step")
    dma_gbs = dma / dev * 1e3 if dma else None  # measured bandwidth
    parts = [
        f"device step {dev} ms vs memory-bound floor {mem_ms} ms "
        f"({100 * frac_mem:.0f}% of step) and MXU floor {mxu_ms} ms "
        f"({100 * frac_mxu:.0f}%)"
    ]
    if dma:
        parts.append(
            f"measured DMA traffic {dma} GB/step = {dma_gbs:.0f} GB/s "
            f"({100 * dma_gbs / PEAK_HBM_GBS:.0f}% of pin bw)"
        )
    if frac_mem >= 0.8:
        parts.append("VERDICT: memory-bound at the fusion-optimal limit — "
                     "byte-cutting (layout, dtype, recompute) is the lever")
    elif dma and dma_gbs >= 0.8 * PEAK_HBM_GBS:
        parts.append("VERDICT: memory-bound via measured traffic (real "
                     "schedule moves more bytes than the optimal-dataflow "
                     "bound) — close the gap between measured and bound")
    else:
        parts.append("VERDICT: NOT memory-bound at these numbers — the gap "
                     "to both floors is MXU utilization / occupancy of the "
                     "actual conv shapes (early high-res low-channel convs "
                     "tile poorly), not bandwidth")
    return "; ".join(parts)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--batch", type=int, default=128,
                   help="per-chip batch (the flagship bench point)")
    p.add_argument("--analytic", action="store_true",
                   help="skip the chip: shape-math bound only")
    p.add_argument("--device-ms", type=float, default=None,
                   help="previously measured device step time (ms) to use "
                        "when the chip is unreachable; cite --device-ms-source")
    p.add_argument("--device-ms-source", default=None,
                   help="artifact the --device-ms number came from")
    p.add_argument("--bench-json", default=None, metavar="PATH",
                   help="anchor the roofline to a measured bench result "
                        "(bench.py JSON line or a driver BENCH_rNN.json): "
                        "renders where the step and each analytic layer "
                        "sit vs the 30%%-MFU baseline")
    p.add_argument("--out", default="artifacts/roofline_r05.json")
    args = p.parse_args(argv)

    bench = None
    if args.bench_json:
        bench = load_bench_json(args.bench_json)
        if bench.get("batch_per_chip"):
            args.batch = int(bench["batch_per_chip"])
    analytic = analytic_traffic(args.batch)
    measured = None
    if not args.analytic:
        try:
            measured = measure_on_chip(args.batch)
        except Exception as e:
            measured = {"error": f"{type(e).__name__}: {e}"}
    if (measured is None or "error" in measured) and args.device_ms:
        prior = {"device_step_ms": args.device_ms,
                 "source": args.device_ms_source or "prior measurement",
                 "note": "chip unreachable; device time from the cited "
                         "prior artifact (no DMA totals this run)"}
        if measured and "error" in measured:
            prior["chip_error"] = measured["error"]
        measured = prior
    v = verdict(analytic, measured if measured and "error" not in
                (measured or {}) else None)
    result = {
        "what": "HBM roofline re-founded: fusion-optimal analytic traffic "
                "bound (shape math) + profiler DMA totals; replaces the "
                "disqualified cost_analysis() bytes (see bench.py NB)",
        "peak_hbm_gbs": PEAK_HBM_GBS,
        "peak_bf16_tflops": PEAK_BF16_TFLOPS,
        "analytic": analytic,
        "measured": measured,
        "verdict": v,
        # the measured optimization attempts behind the current operating
        # point (interleaved same-process A/B unless noted):
        "optimization_attempts": [
            {"lever": "batch size (coarse sweep 128-512)",
             "result": "WIN: 97.88 -> 46.31 ms per 128 images "
                       "(2615 -> 2764 img/s); batch 128 is the knee",
             "artifact": "artifacts/batch_scaling_r04.json"},
            {"lever": "Layout.AUTO input/param layouts",
             "result": "NULL: bytes-accessed 77.9 -> 68.1 GB but device "
                       "time 97.9 -> 103.4 ms — XLA's default layout "
                       "copies buy conv-optimal tiling worth more than "
                       "their bandwidth",
             "artifact": "artifacts/layout_probe_r04.json"},
            {"lever": "compiler knobs (rwb fusion, latency-hiding "
                      "scheduler, scoped vmem, MSA)",
             "result": "NULL: none beat baseline in interleaved A/B (r3)",
             "artifact": "memory: r3 probe series"},
            {"lever": "fused single-pass BatchNorm",
             "result": "WIN (shipped): 1.286x step vs flax nn.BatchNorm",
             "artifact": "artifacts/ablate_r04.json"},
        ],
    }
    if bench is not None:
        pos = bench_position(bench, analytic)
        result["bench_roofline"] = pos
        print(render_roofline(pos))
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(v)
    print(f"-> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
