"""Model zoo registry.

Every model the reference ships (README.md:5 table) plus the ones it left
broken (ShuffleNet V1, Inception V3, ObjectsAsPoints — SURVEY.md §2.9) which
are implemented properly here. Models register by name so configs select them
the way `training_config['model']` did (ResNet/pytorch/train.py:26-215).
"""
from __future__ import annotations

from typing import Callable, Dict

MODEL_REGISTRY: Dict[str, Callable] = {}


def register_model(name: str):
    def deco(fn):
        MODEL_REGISTRY[name] = fn
        return fn

    return deco


def get_model(name: str, **kwargs):
    if name not in MODEL_REGISTRY:
        raise KeyError(f"unknown model '{name}'; have {sorted(MODEL_REGISTRY)}")
    return MODEL_REGISTRY[name](**kwargs)


# importing the modules populates the registry
from deep_vision_tpu.models import lenet  # noqa: E402,F401
from deep_vision_tpu.models import alexnet  # noqa: E402,F401
from deep_vision_tpu.models import vgg  # noqa: E402,F401
from deep_vision_tpu.models import inception  # noqa: E402,F401
from deep_vision_tpu.models import resnet  # noqa: E402,F401
from deep_vision_tpu.models import mobilenet  # noqa: E402,F401
from deep_vision_tpu.models import shufflenet  # noqa: E402,F401
from deep_vision_tpu.models import yolov3  # noqa: E402,F401
from deep_vision_tpu.models import hourglass  # noqa: E402,F401
from deep_vision_tpu.models import centernet  # noqa: E402,F401
from deep_vision_tpu.models import dcgan  # noqa: E402,F401
from deep_vision_tpu.models import cyclegan  # noqa: E402,F401
from deep_vision_tpu.models import vit  # noqa: E402,F401
