"""All five parallelism flavors on one mesh, in ~80 lines.

The reference's only distribution is single-host data parallel
(MirroredStrategy at YOLO/tensorflow/train.py:281); this example shows the
TPU-native spectrum on a (data, model) mesh: DP (batch sharding), TP
(Megatron-style weight sharding via `infer_tp_sharding`), SP (ring
attention), PP (GPipe over the model axis), EP (Switch MoE with all_to_all).

Run without hardware on a virtual mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/distributed_training.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor a JAX_PLATFORMS override even when a site hook imported jax before
# the env var could take effect at backend init (e.g. JAX_PLATFORMS=cpu to
# run this example without an accelerator)
import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


import jax
import jax.numpy as jnp
import numpy as np

from deep_vision_tpu.core.train_state import create_train_state
from deep_vision_tpu.losses import classification_loss_fn
from deep_vision_tpu.models import get_model
from deep_vision_tpu.parallel import (
    create_mesh,
    data_sharding,
    expert_param_sharding,
    moe_ffn,
    pipeline_apply,
    pipeline_param_sharding,
    stack_pipeline_params,
)
from deep_vision_tpu.parallel.mesh import infer_tp_sharding
from deep_vision_tpu.parallel.ring_attention import ring_attention
from deep_vision_tpu.train import build_optimizer


def main():
    n = len(jax.devices())
    model_par = 2 if n % 2 == 0 and n > 1 else 1
    mesh = create_mesh(data=n // model_par, model=model_par)
    print(f"mesh: {dict(mesh.shape)}")

    # --- DP x TP: the full ResNet-50 train step, sharded ------------------
    model = get_model("resnet50", num_classes=64)
    tx = build_optimizer("sgd", 0.1, momentum=0.9)
    state = create_train_state(model, tx, jnp.ones((2, 64, 64, 3)))
    state = jax.device_put(state, infer_tp_sharding(state, mesh, min_size=1024))
    batch = {
        "image": np.random.RandomState(0).rand(
            2 * mesh.shape["data"], 64, 64, 3).astype(np.float32),
        "label": np.arange(2 * mesh.shape["data"], dtype=np.int32) % 64,
    }
    batch = {k: jax.device_put(v, data_sharding(mesh, np.ndim(v)))
             for k, v in batch.items()}

    @jax.jit
    def train_step(state, batch):
        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            out, nms = state.apply_fn(variables, batch["image"], train=True,
                                      rngs={"dropout": state.rng},
                                      mutable=["batch_stats"])
            return classification_loss_fn(out, batch)[0], nms["batch_stats"]

        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params)
        return state.apply_gradients(grads).replace(batch_stats=bs), loss

    with mesh:
        state, loss = train_step(state, batch)
    print(f"DPxTP train step: loss {float(loss):.4f}")

    # --- SP: ring attention, sequence sharded over 'data' -----------------
    from jax.sharding import NamedSharding, PartitionSpec as P

    t = 8 * mesh.shape["data"]
    q, k, v = (np.random.RandomState(1).randn(2, t, 2, 8).astype(np.float32)
               for _ in range(3))
    spec = NamedSharding(mesh, P(None, "data", None, None))
    out = ring_attention(*(jax.device_put(x, spec) for x in (q, k, v)),
                         mesh, causal=True)
    print(f"SP ring attention: out {out.shape}")

    # --- PP: a 4-stage GPipe over the model axis (when it exists) ---------
    if model_par > 1:
        stages = [{"w": jnp.asarray(
            np.random.RandomState(s).randn(16, 16) * 0.1, jnp.float32)}
            for s in range(model_par)]
        stacked = stack_pipeline_params(stages)
        stacked = jax.device_put(stacked, pipeline_param_sharding(mesh, stacked))
        y = pipeline_apply(lambda p, h: h + jnp.tanh(h @ p["w"]), stacked,
                           jnp.ones((8, 16)), mesh, num_microbatches=4)
        print(f"PP GPipe: out {y.shape}")

    # --- EP: Switch MoE with all_to_all dispatch over 'data' --------------
    e = 2 * mesh.shape["data"]
    rng = np.random.RandomState(2)
    router = jnp.asarray(rng.randn(16, e) * 0.5, jnp.float32)
    experts = {"w1": jnp.asarray(rng.randn(e, 16, 32) * 0.1, jnp.float32),
               "b1": jnp.zeros((e, 32)),
               "w2": jnp.asarray(rng.randn(e, 32, 16) * 0.1, jnp.float32),
               "b2": jnp.zeros((e, 16))}
    tokens = jnp.asarray(rng.randn(4 * mesh.shape["data"], 16), jnp.float32)
    out = moe_ffn(router, jax.device_put(
        experts, expert_param_sharding(mesh, experts)), tokens, mesh,
        capacity=4)
    print(f"EP MoE: out {out.shape}")
    print("OK")


if __name__ == "__main__":
    main()
