// Native record-file reader + multi-shard threaded prefetch pool.
//
// The training-speed IO path behind data/records.py: the Python reader is
// the portable twin; this .so feeds the DataLoader without holding the GIL
// during file IO + CRC verification. Exposed as a flat C API for ctypes
// (the repo's binding convention: no pybind11 in the image).
//
// Format (TFRecord framing, see data/records.py):
//   uint64 len | uint32 masked_crc(len) | payload | uint32 masked_crc(payload)
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "crc32c.h"

namespace {

constexpr int kOk = 0;
constexpr int kEof = 1;
constexpr int kCorrupt = 2;
constexpr int kIoError = 3;
constexpr int kTruncated = 4;

struct Record {
  std::vector<uint8_t> data;
};

// Pool records are views into a whole-file slab: one malloc per file instead
// of one per record (per-record vectors caused negative thread scaling —
// cross-thread allocator churn dominated the CRC+IO win).
struct SlabRecord {
  std::shared_ptr<uint8_t[]> slab;  // uninitialized buffer: no memset cost
  size_t off = 0;
  size_t len = 0;
};

// -- single-file reader ------------------------------------------------------

class RecordFile {
 public:
  RecordFile(const char* path, bool verify)
      : f_(std::fopen(path, "rb")), verify_(verify) {}
  ~RecordFile() {
    if (f_) std::fclose(f_);
  }
  bool ok() const { return f_ != nullptr; }

  // Returns kOk and fills out, or kEof / kCorrupt / kIoError.
  int Next(std::vector<uint8_t>* out) {
    uint8_t header[8];
    size_t n = std::fread(header, 1, 8, f_);
    if (n == 0) return kEof;
    if (n < 8) return kTruncated;
    uint32_t hcrc;
    if (std::fread(&hcrc, 1, 4, f_) != 4) return kTruncated;
    if (verify_ && dvtpu::MaskedCrc32c(header, 8) != hcrc) return kCorrupt;
    uint64_t len;
    std::memcpy(&len, header, 8);
    if (len > (1ull << 34)) return kCorrupt;  // 16GB sanity cap
    out->resize(len);
    if (len && std::fread(out->data(), 1, len, f_) != len) return kTruncated;
    uint32_t dcrc;
    if (std::fread(&dcrc, 1, 4, f_) != 4) return kTruncated;
    if (verify_ && dvtpu::MaskedCrc32c(out->data(), len) != dcrc)
      return kCorrupt;
    return kOk;
  }

 private:
  FILE* f_;
  bool verify_;
};

// -- multi-shard prefetch pool -----------------------------------------------

class RecordPool {
 public:
  RecordPool(std::vector<std::string> paths, int num_threads, size_t capacity,
             bool verify)
      : paths_(std::move(paths)),
        capacity_(capacity ? capacity : 8192),
        verify_(verify) {
    next_path_.store(0);
    int n = num_threads > 0 ? num_threads : 4;
    int hw = static_cast<int>(std::thread::hardware_concurrency());
    if (hw > 0 && n > hw) n = hw;  // 1-core hosts: threading only adds churn
    if (n > static_cast<int>(paths_.size()))
      n = static_cast<int>(paths_.size());
    active_workers_.store(n > 0 ? n : 0);
    for (int i = 0; i < n; ++i)
      workers_.emplace_back([this] { Work(); });
  }

  ~RecordPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      cancelled_ = true;
    }
    cv_pop_.notify_all();
    cv_push_.notify_all();
    for (auto& t : workers_) t.join();
  }

  // kOk + record view, kEof when drained, kCorrupt/kIoError sticky.
  // Pops up to 64 records per lock acquisition into a consumer-side stash;
  // the returned view stays valid until the next call (stash holds the slab).
  int Next(const uint8_t** data, uint64_t* len) {
    if (stash_pos_ < stash_.size()) {
      const SlabRecord& r = stash_[stash_pos_++];
      *data = r.slab.get() + r.off;
      *len = r.len;
      return kOk;
    }
    std::unique_lock<std::mutex> lk(mu_);
    cv_pop_.wait(lk, [this] {
      return !queue_.empty() || active_workers_.load() == 0 || error_ ||
             cancelled_;
    });
    if (error_) return error_;
    if (queue_.empty()) return kEof;
    stash_.clear();
    stash_pos_ = 0;
    stash_.reserve(queue_.size());
    while (!queue_.empty()) {  // drain everything: one lock per queue swap
      stash_.push_back(std::move(queue_.front()));
      queue_.pop_front();
    }
    cv_push_.notify_all();
    lk.unlock();
    const SlabRecord& r = stash_[stash_pos_++];
    *data = r.slab.get() + r.off;
    *len = r.len;
    return kOk;
  }

 private:
  void Work() {
    for (;;) {
      size_t idx = next_path_.fetch_add(1);
      if (idx >= paths_.size()) break;
      // whole-file slab read: one allocation + one fread per shard
      FILE* f = std::fopen(paths_[idx].c_str(), "rb");
      if (!f) {
        Fail(kIoError);
        break;
      }
      std::fseek(f, 0, SEEK_END);
      long size = std::ftell(f);
      std::fseek(f, 0, SEEK_SET);
      const size_t total = size > 0 ? static_cast<size_t>(size) : 0;
      std::shared_ptr<uint8_t[]> slab(new uint8_t[total ? total : 1]);
      bool read_ok =
          total == 0 || std::fread(slab.get(), 1, total, f) == total;
      std::fclose(f);
      if (!read_ok) {
        Fail(kIoError);
        break;
      }
      // parse + verify record frames in place
      std::vector<SlabRecord> batch;
      size_t pos = 0;
      const uint8_t* base = slab.get();
      bool bad = false;
      int bad_rc = kCorrupt;
      while (pos < total) {
        if (pos + 16 > total) {  // not even room for an empty record's frame
          bad = true;
          bad_rc = kTruncated;
          break;
        }
        uint64_t len;
        uint32_t hcrc, dcrc;
        std::memcpy(&len, base + pos, 8);
        std::memcpy(&hcrc, base + pos + 8, 4);
        if (len > total - pos - 16) {  // payload+crc overruns the file
          bad = true;
          bad_rc = kTruncated;
          break;
        }
        if (verify_ && dvtpu::MaskedCrc32c(base + pos, 8) != hcrc) {
          bad = true;
          break;
        }
        std::memcpy(&dcrc, base + pos + 12 + len, 4);
        if (verify_ && dvtpu::MaskedCrc32c(base + pos + 12, len) != dcrc) {
          bad = true;
          break;
        }
        batch.push_back(SlabRecord{slab, pos + 12, static_cast<size_t>(len)});
        pos += 16 + len;
        if (batch.size() == 64 || pos >= total) {
          std::unique_lock<std::mutex> lk(mu_);
          cv_push_.wait(lk, [this] {
            return queue_.size() < capacity_ || cancelled_;
          });
          if (cancelled_) goto done;
          for (auto& r : batch) queue_.push_back(std::move(r));
          cv_pop_.notify_all();
          lk.unlock();
          batch.clear();
        }
      }
      if (bad) {
        Fail(bad_rc);
        break;
      }
    }
  done:
    if (active_workers_.fetch_sub(1) == 1) cv_pop_.notify_all();
  }

  void Fail(int rc) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!error_) error_ = rc;
    cv_pop_.notify_all();
  }

  std::vector<std::string> paths_;
  std::atomic<size_t> next_path_;
  std::atomic<int> active_workers_;
  size_t capacity_;
  bool verify_;
  std::mutex mu_;
  std::condition_variable cv_pop_, cv_push_;
  std::deque<SlabRecord> queue_;
  int error_ = 0;
  bool cancelled_ = false;
  std::vector<std::thread> workers_;
  std::vector<SlabRecord> stash_;  // consumer-side, no lock needed
  size_t stash_pos_ = 0;
};

// The C API hands out buffers owned by the handle until the next call.
struct ReaderHandle {
  std::unique_ptr<RecordFile> file;
  std::vector<uint8_t> last;
};

struct PoolHandle {
  std::unique_ptr<RecordPool> pool;
};

}  // namespace

extern "C" {

void* dv_reader_open(const char* path, int verify) {
  auto* h = new ReaderHandle;
  h->file.reset(new RecordFile(path, verify != 0));
  if (!h->file->ok()) {
    delete h;
    return nullptr;
  }
  return h;
}

// Returns kOk/kEof/kCorrupt; on kOk sets *data/*len (valid until next call).
int dv_reader_next(void* handle, const uint8_t** data, uint64_t* len) {
  auto* h = static_cast<ReaderHandle*>(handle);
  int rc = h->file->Next(&h->last);
  if (rc == kOk) {
    *data = h->last.data();
    *len = h->last.size();
  }
  return rc;
}

void dv_reader_close(void* handle) { delete static_cast<ReaderHandle*>(handle); }

void* dv_pool_open(const char** paths, int num_paths, int num_threads,
                   uint64_t capacity, int verify) {
  std::vector<std::string> ps(paths, paths + num_paths);
  auto* h = new PoolHandle;
  h->pool.reset(new RecordPool(std::move(ps), num_threads, capacity,
                               verify != 0));
  return h;
}

int dv_pool_next(void* handle, const uint8_t** data, uint64_t* len) {
  return static_cast<PoolHandle*>(handle)->pool->Next(data, len);
}

void dv_pool_close(void* handle) { delete static_cast<PoolHandle*>(handle); }

uint32_t dv_masked_crc32c(const uint8_t* data, uint64_t len) {
  return dvtpu::MaskedCrc32c(data, len);
}

}  // extern "C"
