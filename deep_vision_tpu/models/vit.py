"""Vision Transformer (+ V-MoE variant): the framework's attention flagship.

Net-new beyond the reference (its zoo is all-CNN, SURVEY.md §2.9): an
attention-based image classifier is what the framework's long-context and
expert-parallel machinery exists for, so the zoo ships one. Architecture
follows ViT (Dosovitskiy 2020) with the TPU-friendly choices:

- patchify as a single stride-P conv (one big MXU matmul, no gather);
- token global-average pooling instead of a class token (keeps the sequence
  length a power-of-two-ish multiple of 8/128 tiling at common resolutions
  and sidesteps concat-of-one ragged shapes);
- attention auto-routes to the fused Pallas flash kernel
  (`ops/pallas/flash_attention.py`) when the sequence is long enough to
  matter and runs the exact dense einsum otherwise — high-res inputs get
  O(T) memory, 224px inputs get zero kernel-launch overhead;
- pre-norm blocks, GELU MLP, bf16-friendly: LayerNorm statistics in f32,
  params f32, activations in the module dtype.

The V-MoE variant (Riquelme 2021) swaps every other MLP for a top-1
(Switch) mixture-of-experts whose expert params are STACKED on a leading
expert axis — exactly the layout `parallel.moe.expert_param_sharding`
shards for expert-parallel training and `parallel.moe.moe_ffn` consumes
under shard_map. Inside the module the routing runs the dense einsum
formulation (`moe_ffn_dense` semantics, no capacity drops: exact, and the
right thing on a single chip); the router's gates feed the Switch
load-balancing aux loss, returned as an aux output in train mode like
Inception V1's aux heads (losses/classification.py handles the plumbing).
"""
from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deep_vision_tpu.core.backend import get_backend
from deep_vision_tpu.models import register_model
# the flash routing floor lives with the kernel (shared by this backbone
# and parallel/ring_attention.py); re-exported here for the historical
# import path (tests, train_cli)
from deep_vision_tpu.ops.pallas.flash_attention import (  # noqa: F401
    FLASH_MIN_TOKENS,
    flash_min_tokens,
)
from deep_vision_tpu.parallel.moe import load_balancing_loss


class Attention(nn.Module):
    num_heads: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        h = self.num_heads
        assert d % h == 0, f"dim {d} not divisible by {h} heads"
        qkv = nn.DenseGeneral((3, h, d // h), dtype=self.dtype,
                              name="qkv")(x)
        q, k, v = (qkv[:, :, i] for i in range(3))  # (B, T, H, Dh)
        # t must divide the kernel's block_q=512 AND block_k=1024 grid
        # (flash_attention.py asserts it), so the guard is t % 1024 == 0 —
        # t % 128 alone would admit 1280/1536-token inputs the kernel rejects
        use_flash = (
            get_backend().pallas_compiled
            and t >= flash_min_tokens()
            and t % 1024 == 0
        )
        if use_flash:
            from deep_vision_tpu.ops.pallas.flash_attention import (
                flash_attention,
            )

            o = flash_attention(q, k, v)
        else:
            scale = (d // h) ** -0.5
            s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
            p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
            o = jnp.einsum("bhts,bshd->bthd", p, v)
        return nn.DenseGeneral(d, axis=(-2, -1), dtype=self.dtype,
                               name="out")(o)


class Mlp(nn.Module):
    hidden: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        x = nn.Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.gelu(x)
        return nn.Dense(d, dtype=self.dtype)(x)


class MoeMlp(nn.Module):
    """Top-1 Switch MoE MLP; expert params stacked on a leading E axis.

    Returns (out, gates) — gates (B*T, E) feed the load-balancing loss.
    Expert weights use the (E, d_in, d_out) layout of `parallel.moe`, so
    `expert_param_sharding` / `moe_ffn` apply unchanged for expert-parallel
    training across a mesh axis.
    """

    num_experts: int
    hidden: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        e, h = self.num_experts, self.hidden
        tok = x.reshape(b * t, d)
        router = self.param(
            "router", nn.initializers.lecun_normal(), (d, e), jnp.float32
        )
        w1 = self.param(
            "w1", nn.initializers.lecun_normal(), (e, d, h), jnp.float32
        )
        b1 = self.param("b1", nn.initializers.zeros, (e, h), jnp.float32)
        w2 = self.param(
            "w2", nn.initializers.lecun_normal(), (e, h, d), jnp.float32
        )
        b2 = self.param("b2", nn.initializers.zeros, (e, d), jnp.float32)
        dt = self.dtype or x.dtype
        # router in f32 (softmax over logits is precision-sensitive)
        gates = jax.nn.softmax(tok.astype(jnp.float32) @ router)
        choice = jnp.argmax(gates, axis=-1)
        prob = jnp.take_along_axis(gates, choice[:, None], axis=-1)
        # dense dispatch: one-hot einsum packs each token's chosen expert
        # contribution; E small (<=16) so compute is E x the MLP, all MXU
        onehot = jax.nn.one_hot(choice, e, dtype=dt)
        hmid = jax.nn.gelu(
            jnp.einsum("te,td,edh->teh", onehot, tok.astype(dt),
                       w1.astype(dt)) + b1.astype(dt)
        )
        # onehot on BOTH sides: hmid rows of unselected experts are
        # gelu(0 + b1[e]) != 0 once b1 trains, and must not leak into the
        # output sum (top-1 Switch semantics == parallel/moe.moe_ffn_dense)
        out = jnp.einsum(
            "te,teh,ehd->td", onehot, hmid, w2.astype(dt)
        ) + jnp.einsum("te,ed->td", onehot, b2.astype(dt))
        out = out * prob.astype(dt)
        return out.reshape(b, t, d), gates


class ViTBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    num_experts: int = 0  # 0 = dense MLP
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        y = nn.LayerNorm(dtype=jnp.float32)(x).astype(x.dtype)
        x = x + Attention(self.num_heads, dtype=self.dtype)(y)
        y = nn.LayerNorm(dtype=jnp.float32)(x).astype(x.dtype)
        gates = None
        if self.num_experts:
            y, gates = MoeMlp(
                self.num_experts, d * self.mlp_ratio, dtype=self.dtype
            )(y)
        else:
            y = Mlp(d * self.mlp_ratio, dtype=self.dtype)(y)
        return x + y, gates


class ViT(nn.Module):
    """ViT classifier. Input NHWC; output logits (f32)."""

    depth: int = 12
    dim: int = 384
    num_heads: int = 6
    patch: int = 16
    num_classes: int = 1000
    mlp_ratio: int = 4
    num_experts: int = 0  # >0: MoE every other block (V-MoE "last-2"-ish)
    moe_every: int = 2
    dropout: float = 0.0
    remat: bool = False  # rematerialize each block: activations are
    # recomputed in backward instead of stored — O(sqrt) activation memory,
    # the lever for long-token-count training (jax.checkpoint under flax)
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        b, hh, ww, _ = x.shape
        p = self.patch
        assert hh % p == 0 and ww % p == 0, (
            f"image {hh}x{ww} not divisible by patch {p}"
        )
        dt = self.dtype or x.dtype
        x = nn.Conv(
            self.dim, (p, p), strides=(p, p), padding="VALID", dtype=dt,
            name="patch_embed",
        )(x.astype(dt))
        x = x.reshape(b, -1, self.dim)  # (B, T, D)
        t = x.shape[1]
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, t, self.dim),
            jnp.float32,
        )
        x = x + pos.astype(dt)
        if self.dropout:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        all_gates = []
        block_cls = nn.remat(ViTBlock) if self.remat else ViTBlock
        for i in range(self.depth):
            moe = (
                self.num_experts
                if self.num_experts
                and (i % self.moe_every == self.moe_every - 1)
                else 0
            )
            # explicit name: nn.remat would auto-name the module
            # remat(CheckpointViTBlock_i), breaking checkpoint
            # interchangeability with the stored-activation variant
            x, gates = block_cls(
                self.num_heads, self.mlp_ratio, num_experts=moe,
                dtype=self.dtype, name=f"ViTBlock_{i}",
            )(x)
            if gates is not None:
                all_gates.append(gates)
        x = nn.LayerNorm(dtype=jnp.float32)(x.astype(jnp.float32))
        x = jnp.mean(x, axis=1)  # token-mean pool
        logits = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        if train and all_gates:
            # Switch aux loss per MoE block, averaged; the classification
            # loss adds `moe_aux_weight * aux` (losses/classification.py)
            aux = jnp.mean(
                jnp.stack([load_balancing_loss(g) for g in all_gates])
            )
            # router telemetry ('_'-prefixed = metrics-only, never added to
            # the loss): mean per-token gate entropy in nats (ln E = uniform
            # routing, 0 = hard routing) and the max fraction of tokens any
            # one expert receives (1/E = balanced, 1.0 = collapse) — the
            # instruments for diagnosing router cold-start stalls
            gates = jnp.stack(all_gates)  # (L, T, E)
            ent = -jnp.sum(gates * jnp.log(gates + 1e-9), axis=-1)
            top1 = jax.nn.one_hot(
                jnp.argmax(gates, axis=-1), gates.shape[-1],
                dtype=jnp.float32,
            )
            load_max = jnp.max(jnp.mean(top1, axis=1))
            return logits, {
                "moe_aux": aux,
                "_router_entropy": jnp.mean(ent),
                "_expert_load_max": load_max,
            }
        return logits


def pipeline_vit_trunk(model: ViT, variables, x, mesh, *,
                       num_microbatches: int, axis_name: str = "model"):
    """Run a dense ViT's block trunk as a GPipe pipeline over `axis_name`.

    The ViT trunk is the textbook pipeline workload — `depth` blocks with
    identical param shapes and one fixed (B, T, D) activation shape. This
    bridges the zoo model to `parallel.pipeline.pipeline_apply`: blocks are
    grouped into `mesh.shape[axis_name]` stages (depth must divide evenly),
    per-stage params are stacked/sharded, and each device runs its
    contiguous block span with one ppermute hop between stages.

    x: (B, T, D) tokens (after patch embed + pos). Returns (B, T, D).
    Matches the sequential trunk exactly (see tests/test_vit.py); grads flow,
    so a pipelined train step is jax.grad over this. MoE blocks are not
    pipelineable this way (their param shapes differ); use dense ViT.
    """
    from deep_vision_tpu.parallel.pipeline import (
        pipeline_apply,
        pipeline_param_sharding,
        stack_pipeline_params,
    )

    assert model.num_experts == 0, "pipeline trunk requires a dense ViT"
    n_stages = mesh.shape[axis_name]
    depth = model.depth
    assert depth % n_stages == 0, (
        f"depth {depth} not divisible into {n_stages} stages"
    )
    span = depth // n_stages
    params = variables["params"]
    block = ViTBlock(model.num_heads, model.mlp_ratio, dtype=model.dtype)
    # stage s holds blocks [s*span, (s+1)*span), stacked on a span axis
    stage_params = [
        jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[params[f"ViTBlock_{s * span + j}"] for j in range(span)],
        )
        for s in range(n_stages)
    ]
    stacked = stack_pipeline_params(stage_params)
    stacked = jax.device_put(
        stacked, pipeline_param_sharding(mesh, stacked, axis_name)
    )

    def stage_fn(p, h):
        def body(h, block_p):
            h, _ = block.apply({"params": block_p}, h)
            return h, None

        h, _ = jax.lax.scan(body, h, p)
        return h

    return pipeline_apply(
        stage_fn, stacked, x, mesh,
        num_microbatches=num_microbatches, axis_name=axis_name,
    )


@register_model("vit_s16")
def vit_s16(num_classes: int = 1000, dtype=None, remat: bool = False, **_):
    return ViT(depth=12, dim=384, num_heads=6, num_classes=num_classes,
               remat=remat, dtype=dtype)


@register_model("vit_b16")
def vit_b16(num_classes: int = 1000, dtype=None, remat: bool = False, **_):
    return ViT(depth=12, dim=768, num_heads=12, num_classes=num_classes,
               remat=remat, dtype=dtype)


@register_model("vmoe_s16")
def vmoe_s16(num_classes: int = 1000, dtype=None, num_experts: int = 8,
             remat: bool = False, **_):
    return ViT(depth=12, dim=384, num_heads=6, num_classes=num_classes,
               num_experts=num_experts, remat=remat, dtype=dtype)
