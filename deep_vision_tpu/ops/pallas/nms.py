"""Greedy NMS as a Pallas TPU kernel: the whole selection loop in VMEM.

The lax implementation in ops/nms.py dispatches a `fori_loop` whose every
iteration does an argmax over HBM-resident scores plus one IoU row — at
YOLO scale (N=10647 candidates, 100 selections) that is 100 sequential
reduce+broadcast rounds the XLA scheduler cannot overlap, and the decode
shows up as a serial tail on the inference profile. This kernel pins the
candidate set (4 coordinate rows + scores, ~250 KB at YOLO scale) in VMEM
for the whole greedy loop: one grid step per image, zero HBM round-trips
per selection.

Same algorithm and arithmetic as ops/nms.py `_nms_single` (argmax ->
suppress-by-IoU with the `broadcast_iou` union/eps convention), so the two
implementations are interchangeable — the parity tests assert exact
agreement on indices and scores. `interpret=True` runs the same kernel on
CPU (the tier-1 path); `ops/nms.py non_maximum_suppression(impl=...)` picks
lax vs pallas (env DVT_NMS_IMPL overrides, TPU defaults to pallas).

Layout: coordinates travel as four (B, N) rows (lane-major over candidates)
rather than (B, N, 4) — a 4-wide lane dim would waste 124 of the VPU's 128
lanes on every op. N and max_detections are padded to lane multiples in the
wrapper; padded candidates carry score -1 so the `best > 0` selection gate
never picks them.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deep_vision_tpu.core import backend as dvt_backend

_LANES = 128


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _nms_kernel(x1_ref, y1_ref, x2_ref, y2_ref, s_ref,
                out_s_ref, out_i_ref, *, max_detections: int,
                iou_threshold: float):
    x1 = x1_ref[...]  # (1, Np)
    y1 = y1_ref[...]
    x2 = x2_ref[...]
    y2 = y2_ref[...]
    live = s_ref[...]
    np_ = live.shape[-1]
    idx = jax.lax.broadcasted_iota(jnp.int32, live.shape, 1)
    # broadcast_iou convention: side lengths clipped at 0, union floored
    # at 1e-9 (ops/boxes.py:34-41)
    area = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)
    out_idx2 = jax.lax.broadcasted_iota(jnp.int32, out_i_ref.shape, 1)

    def body(i, carry):
        live, out_s, out_i = carry
        best = jnp.max(live, axis=None, keepdims=True)  # (1, 1)
        keep = best > 0.0
        # first index attaining the max (lax argmax tie rule)
        bi = jnp.min(jnp.where(live == best, idx, np_), axis=None,
                     keepdims=True)
        sel = idx == bi  # one-hot (1, Np)
        bx1 = jnp.sum(jnp.where(sel, x1, 0.0), axis=None, keepdims=True)
        by1 = jnp.sum(jnp.where(sel, y1, 0.0), axis=None, keepdims=True)
        bx2 = jnp.sum(jnp.where(sel, x2, 0.0), axis=None, keepdims=True)
        by2 = jnp.sum(jnp.where(sel, y2, 0.0), axis=None, keepdims=True)
        barea = jnp.sum(jnp.where(sel, area, 0.0), axis=None, keepdims=True)
        iw = jnp.maximum(jnp.minimum(x2, bx2) - jnp.maximum(x1, bx1), 0.0)
        ih = jnp.maximum(jnp.minimum(y2, by2) - jnp.maximum(y1, by1), 0.0)
        inter = iw * ih
        iou = inter / jnp.maximum(area + barea - inter, 1e-9)
        suppress = (iou >= iou_threshold) | sel
        live = jnp.where(keep & suppress, -1.0, live)
        out_s = jnp.where(out_idx2 == i, jnp.where(keep, best, 0.0), out_s)
        out_i = jnp.where(out_idx2 == i, jnp.where(keep, bi, -1), out_i)
        return live, out_s, out_i

    out_s = jnp.zeros(out_s_ref.shape, out_s_ref.dtype)
    out_i = jnp.full(out_i_ref.shape, -1, jnp.int32)
    _, out_s, out_i = jax.lax.fori_loop(
        0, max_detections, body, (live, out_s, out_i))
    out_s_ref[...] = out_s
    out_i_ref[...] = out_i


def pallas_nms(boxes, scores, max_detections: int, iou_threshold: float,
               score_threshold: float, interpret: bool | None = None):
    """Batched greedy NMS selection. boxes (B, N, 4) xyxy, scores (B, N)
    -> (sel_scores (B, D), sel_idx (B, D) int32, -1 = no selection).

    Matches ops/nms.py `_nms_single` exactly (same thresholding, same
    tie-breaking, same IoU arithmetic); class-awareness is the caller's
    offset trick, gathers of boxes/classes stay outside the kernel.
    """
    if interpret is None:
        interpret = dvt_backend.pallas_interpret()
    b, n, _ = boxes.shape
    np_ = _round_up(max(n, 1), _LANES)
    dp = _round_up(max(max_detections, 1), _LANES)
    scores = jnp.where(scores >= score_threshold, scores, -1.0)
    scores = scores.astype(jnp.float32)
    boxes = boxes.astype(jnp.float32)
    if np_ != n:
        scores = jnp.pad(scores, ((0, 0), (0, np_ - n)),
                         constant_values=-1.0)
        boxes = jnp.pad(boxes, ((0, 0), (0, np_ - n), (0, 0)))
    x1, y1, x2, y2 = (boxes[..., i] for i in range(4))

    row = pl.BlockSpec((1, np_), lambda i: (i, 0))
    out_row = pl.BlockSpec((1, dp), lambda i: (i, 0))
    out_s, out_i = pl.pallas_call(
        functools.partial(_nms_kernel, max_detections=max_detections,
                          iou_threshold=float(iou_threshold)),
        out_shape=[
            jax.ShapeDtypeStruct((b, dp), jnp.float32),
            jax.ShapeDtypeStruct((b, dp), jnp.int32),
        ],
        grid=(b,),
        in_specs=[row, row, row, row, row],
        out_specs=[out_row, out_row],
        interpret=bool(interpret),
    )(x1, y1, x2, y2, scores)
    return out_s[:, :max_detections], out_i[:, :max_detections]
