"""Optimizers and LR schedules for the whole zoo, built on optax.

Covers every recipe the reference configures (SURVEY.md §2.4):
- SGD(momentum, weight_decay), Adam(beta1 override), RMSprop(alpha/eps)
  (ResNet/pytorch/train.py:34-212, CycleGAN/tensorflow/train.py:130-131);
- StepLR / LambdaLR-poly / linear-decay schedules (ResNet/pytorch/train.py:45,
  93,133-138; CycleGAN/tensorflow/utils.py:5-28), cosine for modern recipes;
- ReduceLROnPlateau, which is *stateful host logic* (manual plateau at
  YOLO/tensorflow/train.py:56-68; torch plateau stepped on top-1 at
  ResNet/pytorch/train.py:411-415). Under jit the LR must be a traced input,
  so the optimizer is wrapped in `optax.inject_hyperparams` and the plateau
  object mutates `opt_state.hyperparams['learning_rate']` between steps.

Weight decay follows the reference semantics: torch-style SGD weight_decay is
L2 on *all* params; we default to skipping BN/bias (standard TPU recipe) with
`decay_bn_bias=True` to reproduce torch exactly.
"""
from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
import optax

Schedule = Union[float, Callable[[jnp.ndarray], jnp.ndarray]]


def _decay_mask(params, decay_bn_bias: bool):
    if decay_bn_bias:
        return jax.tree_util.tree_map(lambda _: True, params)

    def mask_fn(path, leaf):
        name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
        is_norm_or_bias = (
            name.endswith("bias") or "BatchNorm" in name or name.endswith("scale")
        )
        return not is_norm_or_bias

    return jax.tree_util.tree_map_with_path(mask_fn, params)


def make_schedule(kind: str = "constant", base_lr: float = 0.1, **kw) -> Schedule:
    """Named LR schedules matching the reference's configs."""
    if kind == "constant":
        return base_lr
    if kind == "step":  # torch StepLR (ResNet/pytorch/train.py:93)
        return optax.exponential_decay(
            base_lr,
            transition_steps=kw["step_size"],
            decay_rate=kw.get("gamma", 0.1),
            staircase=True,
        )
    if kind == "poly":  # LambdaLR poly decay (ResNet/pytorch/train.py:133-138)
        return optax.polynomial_schedule(
            init_value=base_lr,
            end_value=kw.get("end_lr", 0.0),
            power=kw.get("power", 1.0),
            transition_steps=kw["total_steps"],
        )
    if kind == "linear_decay":  # CycleGAN LinearDecay (utils.py:5-28)
        hold = kw.get("hold_steps", 0)
        total = kw["total_steps"]
        return optax.schedules.join_schedules(
            [
                optax.constant_schedule(base_lr),
                optax.linear_schedule(base_lr, 0.0, total - hold),
            ],
            boundaries=[hold],
        )
    if kind == "cosine":
        warmup = kw.get("warmup_steps", 0)
        sched = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=base_lr,
            warmup_steps=max(warmup, 1),
            decay_steps=kw["total_steps"],
            end_value=kw.get("end_lr", 0.0),
        )
        return sched
    raise ValueError(f"unknown schedule '{kind}'")


def _cast_float_leaves(tree, dtype):
    """Cast floating-point array leaves; ints (step counters) untouched."""

    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


def cast_optimizer_state(
    tx: optax.GradientTransformation,
    state_dtype,
    compute_dtype=jnp.float32,
) -> optax.GradientTransformation:
    """Store optimizer state (momentum, Adam moments) in `state_dtype`.

    The SGD+momentum update reads and rewrites a full params-sized trace
    every step; at f32 that is 2x params bytes of pure HBM traffic per
    step on top of the weights themselves. Storing the trace in bf16
    halves it (the roofline's `params` rows in tools/roofline.py price
    this directly). The update itself still runs in `compute_dtype`: state
    is upcast entering the wrapped transform and the new state rounded
    back on the way out — one rounding per step, the same error model as
    bf16 gradient accumulation. Float leaves only; step counters and other
    integer state pass through untouched.
    """

    def init(params):
        return _cast_float_leaves(tx.init(params), state_dtype)

    def update(updates, state, params=None, **extra):
        state = _cast_float_leaves(state, compute_dtype)
        updates, new_state = tx.update(updates, state, params, **extra)
        return updates, _cast_float_leaves(new_state, state_dtype)

    return optax.GradientTransformation(init, update)


def build_optimizer(
    name: str,
    learning_rate: Schedule,
    params=None,
    weight_decay: float = 0.0,
    decay_bn_bias: bool = False,
    grad_clip_norm: Optional[float] = None,
    state_dtype=None,
    **kw,
) -> optax.GradientTransformation:
    """Build an injectable optimizer. `learning_rate` may be float or schedule.

    Returned transformation always has `opt_state.hyperparams['learning_rate']`
    (via inject_hyperparams) so host-side plateau schedules can override it.
    `state_dtype` (e.g. jnp.bfloat16 / 'bfloat16') stores the optimizer
    state — momentum, Adam moments — in that dtype via
    `cast_optimizer_state`, halving the update's HBM traffic at bf16; the
    injected hyperparams (learning_rate) stay f32 so plateau writes and
    schedules are unaffected.
    """

    def _make(learning_rate):
        chain = []
        if grad_clip_norm:
            chain.append(optax.clip_by_global_norm(grad_clip_norm))
        if name == "sgd":
            if weight_decay:
                chain.append(
                    optax.add_decayed_weights(
                        weight_decay, mask=lambda p: _decay_mask(p, decay_bn_bias)
                    )
                )
            chain.append(
                optax.sgd(
                    learning_rate,
                    momentum=kw.get("momentum", 0.0),
                    nesterov=kw.get("nesterov", False),
                )
            )
        elif name == "adam":
            chain.append(
                optax.adam(
                    learning_rate,
                    b1=kw.get("b1", 0.9),
                    b2=kw.get("b2", 0.999),
                    eps=kw.get("eps", 1e-8),
                )
            )
            if weight_decay:
                chain.insert(
                    -1,
                    optax.add_decayed_weights(
                        weight_decay, mask=lambda p: _decay_mask(p, decay_bn_bias)
                    ),
                )
        elif name == "adamw":
            chain.append(
                optax.adamw(
                    learning_rate,
                    b1=kw.get("b1", 0.9),
                    b2=kw.get("b2", 0.999),
                    weight_decay=weight_decay,
                    mask=lambda p: _decay_mask(p, decay_bn_bias),
                )
            )
        elif name == "rmsprop":
            if weight_decay:
                chain.append(
                    optax.add_decayed_weights(
                        weight_decay, mask=lambda p: _decay_mask(p, decay_bn_bias)
                    )
                )
            chain.append(
                optax.rmsprop(
                    learning_rate,
                    decay=kw.get("alpha", 0.9),
                    eps=kw.get("eps", 1e-8),
                    momentum=kw.get("momentum", 0.0),
                )
            )
        elif name == "lamb":  # large-batch ImageNet recipes
            chain.append(
                optax.lamb(learning_rate, weight_decay=weight_decay,
                           mask=lambda p: _decay_mask(p, decay_bn_bias))
            )
        else:
            raise ValueError(f"unknown optimizer '{name}'")
        tx = optax.chain(*chain)
        if state_dtype is not None:
            # cast INSIDE inject_hyperparams: the hyperparams dict (and the
            # LR the plateau writes into it) stays f32, only the big
            # params-shaped state rounds to state_dtype
            tx = cast_optimizer_state(tx, jnp.dtype(state_dtype))
        return tx

    return optax.inject_hyperparams(_make)(learning_rate=learning_rate)


class ReduceLROnPlateau:
    """Host-side plateau schedule, kept outside jit by design.

    Mirrors torch ReduceLROnPlateau stepped on val top-1
    (ResNet/pytorch/train.py:411-415) and the manual plateau at
    YOLO/tensorflow/train.py:56-68. Call `step(metric)` once per epoch; it
    returns the current LR multiplier which the Trainer writes into
    `opt_state.hyperparams['learning_rate']`.
    """

    def __init__(self, factor=0.1, patience=10, mode="max", threshold=1e-4,
                 min_scale=0.0):
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.threshold = threshold
        # LR floor as a fraction of the base LR (torch's min_lr / base_lr)
        self.min_scale = min_scale
        self.best = None
        self.num_bad = 0
        self.scale = 1.0

    def _is_better(self, v):
        if self.best is None:
            return True
        if self.mode == "max":
            return v > self.best + self.threshold
        return v < self.best - self.threshold

    def step(self, metric: float) -> float:
        if self._is_better(metric):
            self.best = metric
            self.num_bad = 0
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.scale = max(self.scale * self.factor, self.min_scale)
                self.num_bad = 0
        return self.scale

    def state_dict(self):
        return {
            "best": self.best,
            "num_bad": self.num_bad,
            "scale": self.scale,
        }

    def load_state_dict(self, d):
        self.best = d["best"]
        self.num_bad = d["num_bad"]
        self.scale = d["scale"]
