"""Host input pipeline: transform workers -> shuffle -> fixed-shape batches.

The TPU-feed replacement for both reference input stacks: torch DataLoader
with worker processes (ResNet/pytorch/train.py:218-257) and
tf.data map(AUTOTUNE)/shuffle/batch/prefetch chains
(YOLO/tensorflow/train.py:260-273). Decode+augment run on a thread pool
(cv2/PIL release the GIL for the heavy work) or, with `num_procs > 0`, on
worker *processes* that each own a disjoint slice of the dataset — the
GIL-free analog of torch's `num_workers` processes, required to scale JPEG
decode across the ~100-vCPU hosts that feed a v5e-8 slice. A sample-level
shuffle buffer reproduces `shuffle(512)`/`shuffle(10000)` semantics, and
batches are collated into fixed-shape numpy dicts ready for `shard_batch`
onto the mesh.
"""
from __future__ import annotations

import multiprocessing as mp
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

# jax-free like obs/registry: spans are no-ops unless train_cli installed a
# tracer, and this module stays importable from spawned data workers
from deep_vision_tpu.data import snapshot as _snapshot
from deep_vision_tpu.obs import locksmith
from deep_vision_tpu.obs.trace import now_us, span, trace_event


class Compose:
    """Chain of transforms, each `(sample, rng) -> sample`."""

    def __init__(self, transforms: Sequence[Callable]):
        self.transforms = list(transforms)

    def __call__(self, sample: dict, rng: np.random.Generator) -> dict:
        for t in self.transforms:
            sample = t(sample, rng)
        return sample


def collate(samples: List[dict]) -> dict:
    """Stack a list of sample dicts into one batch dict of arrays."""
    keys = samples[0].keys()
    return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in keys}


def _buffer_shuffle(samples: Iterable[dict], buffer: int,
                    rng: np.random.Generator) -> Iterator[dict]:
    """Reservoir-style shuffle (tf.data shuffle(buffer) semantics)."""
    buf: List[dict] = []
    for s in samples:
        if len(buf) < buffer:
            buf.append(s)
            continue
        j = int(rng.integers(0, len(buf)))
        out, buf[j] = buf[j], s
        yield out
    rng.shuffle(buf)  # type: ignore[arg-type]
    yield from buf


def worker_put(out_q, stop_evt, item, timeout: float = 0.2) -> bool:
    """Bounded queue put that keeps observing stop_evt (an abandoned
    consumer leaves the queue full; a plain put would block past the
    stop). Shared by the loader's worker processes and the dataset
    service's (data/service.py) so the stop semantics cannot drift."""
    while not stop_evt.is_set():
        try:
            out_q.put(item, timeout=timeout)
            return True
        except queue.Full:
            continue
    return False


def _proc_worker(dataset, transform, epoch_seed, wid, out_q, stop_evt,
                 skip: int = 0):
    """Worker-process body: stream, transform, and ship samples.

    Runs in a spawned child; `dataset` is this worker's disjoint slice.
    Samples cross the process boundary via the queue's pickling — keep
    images uint8 until the last transform to halve that traffic. Samples
    ship tagged `(wid, sample)` so the parent can count per-worker
    deliveries; a replacement worker for a dead one is started with
    `skip` = that count and fast-forwards past the already-delivered
    prefix of its slice (the slice iterates deterministically — the
    parent never advances the original dataset object it re-pickles).
    """
    def put(item) -> bool:
        return worker_put(out_q, stop_evt, item)

    try:
        rng = np.random.default_rng((epoch_seed, wid))
        for k, sample in enumerate(dataset):
            if stop_evt.is_set():
                break
            if k < skip:
                continue  # already delivered by the worker this one replaces
            if transform is not None:
                sample = transform(sample, rng)
            if not put((wid, sample)):
                break
    except BaseException as e:  # noqa: BLE001 - surfaced in the parent
        put(("__error__", repr(e)))
    finally:
        put(("__done__", wid))


class DataLoader:
    """dataset (+ transforms) -> iterator of batch dicts.

    dataset: __len__/__getitem__ map-style OR any iterable of sample dicts.
    Map-style datasets get a full index shuffle per epoch (torch DataLoader
    shuffle=True semantics); iterable datasets get a reservoir-style shuffle
    buffer (tf.data shuffle(buffer) semantics, YOLO/tensorflow/train.py:267).

    `num_procs > 0` decodes in worker PROCESSES instead of threads: the
    dataset must expose `.split(i, n)` returning the i-th of n disjoint
    slices (RecordDataset does, by shard), and dataset+transform must be
    picklable. Sample order then interleaves arbitrarily across workers —
    use `shuffle` (which is the training configuration anyway).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        transform: Optional[Callable] = None,
        shuffle: bool = False,
        shuffle_buffer: int = 512,
        num_workers: int = 8,
        drop_remainder: bool = False,
        seed: int = 0,
        collate_fn: Callable = collate,
        prefetch: int = 2,
        num_procs: int = 0,
        name: str = "default",
        worker_restarts: int = 1,
        worker_poll_s: float = 10.0,
        host_shard: Optional[tuple] = None,
    ):
        self.dataset = dataset
        self.name = name  # labels this loader's obs metrics (train vs val)
        self.batch_size = batch_size
        self.transform = transform
        self.shuffle = shuffle
        self.shuffle_buffer = shuffle_buffer
        self.num_workers = max(1, num_workers)
        self.drop_remainder = drop_remainder
        self.seed = seed
        self.collate_fn = collate_fn
        self.prefetch = prefetch
        self.num_procs = num_procs
        # times a dead worker PROCESS (OOM-killed, segfaulted) is replaced
        # and its undelivered samples resubmitted before the loader gives up;
        # worker_poll_s is the dead-worker check cadence while the queue is
        # quiet (tests shrink it — a liveness probe, not a correctness knob)
        self.worker_restarts = worker_restarts
        self.worker_poll_s = worker_poll_s
        # which host's slice of a multi-host world this loader feeds
        # ((shard_index, num_shards), the multihost.host_shard() value at
        # construction). Pure snapshot identity: it pins the stream's
        # fingerprint so a DataLoaderState taken at world N refuses
        # restore at world M after an elastic resize — the re-derived
        # slice is a different stream. None (single-host) changes nothing.
        self.host_shard = (tuple(int(v) for v in host_shard)
                           if host_shard is not None else None)
        if num_procs > 0 and not hasattr(dataset, "split"):
            raise TypeError(
                f"num_procs={num_procs} needs a dataset with .split(i, n); "
                f"{type(dataset).__name__} has none"
            )
        self._epoch = 0
        self._map_style = hasattr(dataset, "__getitem__") and hasattr(
            dataset, "__len__"
        )
        # -- snapshot plumbing (data/snapshot.py) --------------------------
        # The producer writes a resumable DataLoaderState into `_ring`
        # after every collated batch (keyed (epoch, batches)); the consumer
        # side of __iter__ marks which key it has actually been handed, so
        # state_dict() returns the exact consumed position even while the
        # prefetch thread runs ahead. `_resume` arms a deterministic
        # skip-replay for the next epoch iteration (see load_state_dict).
        self._ring: dict = {}
        self._ring_keys: List[tuple] = []
        self._ring_lock = locksmith.lock("data.pipeline.snapshot")
        self._consumed_key: Optional[tuple] = None
        self._resume: Optional[_snapshot.DataLoaderState] = None
        self._fp: Optional[str] = None
        # per-batch state recording is OFF until armed (enable_snapshots /
        # load_state_dict / Trainer attaching this loader): eval loaders
        # and non-snapshot runs must not pay the ring/rng/cursor
        # bookkeeping on the producer hot path — the LiveCursor is
        # attached to the dataset only when arming, too
        self._snapshot_on = False
        self._cursor = None

    def __len__(self) -> int:
        if not self._map_style:
            raise TypeError("length unknown for iterable datasets")
        n = len(self.dataset)
        return n // self.batch_size if self.drop_remainder else -(-n // self.batch_size)

    # -- internals ---------------------------------------------------------

    def _samples(self, epoch_rng: np.random.Generator) -> Iterator[dict]:
        if self._map_style:
            idx = np.arange(len(self.dataset))
            if self.shuffle:
                epoch_rng.shuffle(idx)
            for i in idx:
                yield self.dataset[int(i)]
        else:
            it = iter(self.dataset)
            if not self.shuffle:
                yield from it
                return
            yield from _buffer_shuffle(it, self.shuffle_buffer, epoch_rng)

    def _transformed(self, epoch_seed: int,
                     epoch_rng: np.random.Generator,
                     skip: int = 0,
                     quiet_read: int = 0) -> Iterator[dict]:
        """Shuffled + transformed sample stream for one epoch.

        `skip` is the snapshot-resume fast-forward (data/snapshot.py): the
        first `skip` post-shuffle samples are consumed WITHOUT transform —
        they were already trained on before the kill — while the sample
        index `k` keeps advancing so per-sample transform keys
        `(epoch_seed, k)` stay aligned with the uninterrupted run's.

        The bad-record budget's `replaying` latch is held until BOTH the
        consumed prefix is skipped and the source has re-read past
        `quiet_read` (the original run's read frontier from the snapshot
        cursor): the original run dead-lettered every bad record up to
        its frontier — which ran ahead of the consumed prefix by the
        shuffle buffer and in-flight transforms — so re-emitting rows
        for anything before it would double-report.
        """
        budget = getattr(self.dataset, "bad_record_budget", None)
        latched = bool(skip) and budget is not None
        if latched:
            budget.replaying = True

        def maybe_unlatch(k: int) -> None:
            nonlocal latched
            if not latched or k < skip:
                return
            if (quiet_read and self._cursor is not None
                    and self._cursor.read_count() < quiet_read):
                return
            budget.replaying = False
            latched = False

        try:
            samples = self._samples(epoch_rng)
            if self.transform is None:
                for k, sample in enumerate(samples):
                    if k < skip:
                        continue
                    maybe_unlatch(k)
                    yield sample
                return
            # ordered parallel map: worker i gets its own derived rng stream
            with ThreadPoolExecutor(self.num_workers) as pool:
                window: "queue.Queue" = queue.Queue()
                in_flight = 0
                max_in_flight = self.num_workers * 2

                def submit(sample, k):
                    rng = np.random.default_rng((epoch_seed, k))
                    return pool.submit(self.transform, sample, rng)

                k = 0
                for sample in samples:
                    if k < skip:
                        k += 1
                        continue
                    maybe_unlatch(k)
                    window.put(submit(sample, k))
                    k += 1
                    in_flight += 1
                    if in_flight >= max_in_flight:
                        yield window.get().result()
                        in_flight -= 1
                while in_flight:
                    yield window.get().result()
                    in_flight -= 1
        finally:
            if budget is not None:
                budget.replaying = False

    def _proc_samples(self, epoch_seed: int, epoch: int) -> Iterator[dict]:
        """Transformed samples from `num_procs` spawned workers, merged.

        Spawn, not fork: the parent has usually initialized JAX (threads +
        a live TPU client) by the time the first epoch starts, and forking a
        multithreaded process is a deadlock lottery. Spawned children import
        fresh; the env override below pins any jax import they trigger to
        the CPU backend so 8+ workers never try to attach to the chip.
        """
        import os

        ctx = mp.get_context("spawn")
        out_q: "mp.Queue" = ctx.Queue(maxsize=self.num_procs * 64)
        stop = ctx.Event()
        procs = []
        shards = []

        def spawn(wid: int, skip: int = 0):
            """Start (or restart) worker `wid` on its pre-built slice; the
            env override pins any jax import in the child to CPU."""
            saved = {k: os.environ.get(k) for k in ("JAX_PLATFORMS",)}
            os.environ["JAX_PLATFORMS"] = "cpu"
            try:
                p = ctx.Process(
                    target=_proc_worker,
                    args=(shards[wid], self.transform, epoch_seed, wid,
                          out_q, stop, skip),
                    daemon=True,
                )
                p.start()
                return p
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v

        # Spawn, not fork (see docstring). Build every slice up front: a
        # replacement worker re-pickles the SAME slice object, which the
        # parent never iterates, so its replay order is deterministic.
        try:
            for i in range(self.num_procs):
                shard = self.dataset.split(i, self.num_procs)
                # the parent never iterates self.dataset in proc mode, so its
                # epoch counter would freeze the per-epoch shard reshuffle —
                # propagate the loader's epoch into each slice explicitly
                if hasattr(shard, "set_epoch"):
                    shard.set_epoch(epoch)
                shards.append(shard)
                procs.append(spawn(i))
        except BaseException:
            # a failed start (EAGAIN at high num_procs) must not leak the
            # already-live workers for the process's lifetime
            stop.set()
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()
            raise
        done: set = set()
        delivered = [0] * self.num_procs  # samples consumed per worker id
        restarts = [0] * self.num_procs

        def classify(item):
            """-> ('done', wid) | ('sample', wid, sample); raises on error."""
            if isinstance(item, tuple) and len(item) == 2:
                tag = item[0]
                if tag == "__done__":
                    return ("done", item[1])
                if tag == "__error__":
                    raise RuntimeError(f"data worker failed: {item[1]}")
                return ("sample", tag, item[1])
            return ("sample", None, item)

        try:
            while len(done) < self.num_procs:
                try:
                    item = out_q.get(timeout=self.worker_poll_s)
                except queue.Empty:
                    # watchdog: a SIGKILL'd/segfaulted worker writes no done
                    # marker; without this the loader would hang forever.
                    failed = [
                        i for i, p in enumerate(procs)
                        if i not in done and not p.is_alive()
                    ]
                    if not failed:
                        continue
                    # Drain what the dead worker(s) already shipped BEFORE
                    # deciding the resubmission point: anything still in the
                    # queue would otherwise be replayed twice. A dead
                    # producer adds nothing, so get_nowait-until-Empty is a
                    # consistent snapshot of its output.
                    while True:
                        try:
                            extra = out_q.get_nowait()
                        except queue.Empty:
                            break
                        kind = classify(extra)
                        if kind[0] == "done":
                            done.add(kind[1])
                            continue
                        _, wid, sample = kind
                        if wid is not None:
                            delivered[wid] += 1
                        yield sample
                    for wid in failed:
                        if wid in done:
                            continue  # its done marker was in the drain
                        if restarts[wid] >= self.worker_restarts:
                            raise RuntimeError(
                                f"data worker {wid} died without a done "
                                f"marker {restarts[wid] + 1}x (OOM-killed or "
                                "crashed in native code); restart budget "
                                f"({self.worker_restarts}) spent"
                            )
                        restarts[wid] += 1
                        print(
                            f"data: worker {wid} died (OOM-killed or crashed "
                            f"in native code); restarting it and resubmitting "
                            f"its in-flight samples (delivered "
                            f"{delivered[wid]}, restart {restarts[wid]}/"
                            f"{self.worker_restarts})", flush=True,
                        )
                        try:
                            from deep_vision_tpu.obs.registry import (
                                get_registry,
                            )

                            get_registry().counter(
                                "data_worker_restarts_total",
                                "dead data workers replaced",
                                labels={"loader": self.name}).inc()
                        except Exception:
                            pass
                        # flight-recorder breadcrumb: a worker that keeps
                        # dying is prime postmortem context for the crash
                        # or hang that often follows (no-op when no
                        # recorder is installed)
                        try:
                            from deep_vision_tpu.obs import flight

                            flight.note(
                                "data_worker_restart", loader=self.name,
                                worker=wid, delivered=delivered[wid],
                                restart=restarts[wid],
                                budget=self.worker_restarts)
                        except Exception:
                            pass
                        procs[wid] = spawn(wid, skip=delivered[wid])
                    continue
                kind = classify(item)
                if kind[0] == "done":
                    done.add(kind[1])
                    continue
                _, wid, sample = kind
                if wid is not None:
                    delivered[wid] += 1
                yield sample
        finally:
            stop.set()
            # drain so children blocked in put() can observe the stop
            try:
                while True:
                    out_q.get_nowait()
            except queue.Empty:
                pass
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    def _batches(self) -> Iterator[dict]:
        epoch = self._epoch
        epoch_seed = self.seed + epoch
        self._epoch += 1
        # pin the dataset's own epoch counter to the LOADER's in every
        # mode (was proc-mode-only): a resumed process otherwise restarts
        # the dataset at epoch 0 and silently replays shard order from
        # scratch while the trainer continues at epoch N — every per-epoch
        # random decision must derive from (seed, epoch), not from how
        # many times this process happened to iterate
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)
        resume = self._resume
        self._resume = None
        if resume is not None and resume.epoch != epoch:
            resume = None  # armed for a different epoch: nothing to skip
        skip = resume.batches * self.batch_size if resume is not None else 0
        budget = getattr(self.dataset, "bad_record_budget", None)
        if budget is not None:
            if resume is not None and resume.budget_epoch_start is not None:
                # the deterministic replay below re-spends the intra-epoch
                # portion; start the epoch where the original did
                budget.set_spend(resume.budget_epoch_start)
            budget_start = budget.spend()
        else:
            budget_start = None
        epoch_rng = np.random.default_rng(epoch_seed)
        if self.num_procs > 0:
            samples: Iterable[dict] = self._proc_samples(epoch_seed, epoch)
            if self.shuffle:
                samples = _buffer_shuffle(
                    samples, self.shuffle_buffer, epoch_rng,
                )
        else:
            samples = self._transformed(
                epoch_seed, epoch_rng, skip=skip,
                quiet_read=int((resume.cursor or {}).get("read", 0) or 0)
                if resume is not None else 0)
        buf: List[dict] = []
        bi = skip // self.batch_size  # batches already consumed pre-resume
        # per-batch producer span via explicit timestamps: one batch's
        # decode+augment work spans loop iterations, so a with-block can't
        # bracket it. t0 is when the batch's first sample was requested.
        t0 = now_us()
        for s in samples:
            buf.append(s)
            if len(buf) == self.batch_size:
                with span("data/collate", loader=self.name):
                    batch = self.collate_fn(buf)
                trace_event("data/augment_batch", t0, loader=self.name,
                            batch_size=len(buf))
                bi += 1
                self._record_snapshot(epoch, bi, epoch_seed, epoch_rng,
                                      budget, budget_start)
                yield batch
                buf = []
                t0 = now_us()
        if buf and not self.drop_remainder:
            batch = self.collate_fn(buf)
            trace_event("data/augment_batch", t0, loader=self.name,
                        batch_size=len(buf))
            bi += 1
            # the tail batch's entry is the epoch-end state, written
            # BEFORE the yield (handed = consumed, same as _mark_consumed):
            # a preempt save while the trainer processes the tail must
            # find its key in the ring, not fabricate a position
            self._record_snapshot(epoch, bi, epoch_seed, epoch_rng,
                                  budget, budget_start, epoch_end=True)
            yield batch
        # end-of-epoch state: resuming after the final batch means
        # starting the NEXT epoch clean (overwrites the tail batch's
        # entry under the same key with identical content)
        self._record_snapshot(epoch, bi, epoch_seed, epoch_rng,
                              budget, budget_start, epoch_end=True)

    # -- snapshot/restore (data/snapshot.py) --------------------------------

    def _fingerprint(self) -> str:
        if self._fp is None:
            self._fp = _snapshot.fingerprint(
                self.dataset, self.batch_size, self.seed,
                shuffle=self.shuffle, shuffle_buffer=self.shuffle_buffer,
                drop_remainder=self.drop_remainder,
                host_shard=self.host_shard)
        return self._fp

    def _record_snapshot(self, epoch: int, bi: int, epoch_seed: int,
                         epoch_rng, budget, budget_start,
                         epoch_end: bool = False) -> None:
        """Producer side: the resumable state AFTER batch `bi` of `epoch`
        (or after the whole epoch), written into the bounded ring the
        consumer-side state_dict() reads."""
        if not self._snapshot_on or self.num_procs > 0:
            return  # not armed (or unsupported): stay off the hot path
        spend = budget.spend() if budget is not None else None
        if epoch_end:
            st = _snapshot.DataLoaderState(
                epoch=epoch + 1, batches=0,
                epoch_seed=self.seed + epoch + 1,
                fingerprint=self._fingerprint(),
                cursor=self._cursor.snapshot() if self._cursor else None,
                budget=spend, budget_epoch_start=spend,
            )
        else:
            st = _snapshot.DataLoaderState(
                epoch=epoch, batches=bi, epoch_seed=epoch_seed,
                fingerprint=self._fingerprint(),
                cursor=self._cursor.snapshot() if self._cursor else None,
                rng=_snapshot.rng_state(epoch_rng),
                budget=spend, budget_epoch_start=budget_start,
            )
        key = (epoch, bi)
        # the bound must exceed how far the producer can run ahead of the
        # consumer (the prefetch depth), or a deep-prefetch loader could
        # evict the very key the consumer's next state_dict() needs
        bound = max(64, self.prefetch + 8)
        with self._ring_lock:
            if key not in self._ring:
                self._ring_keys.append(key)
            self._ring[key] = st.to_dict()
            while len(self._ring_keys) > bound:
                old = self._ring_keys.pop(0)
                self._ring.pop(old, None)

    def _mark_consumed(self, epoch: int, batches: int) -> None:
        self._consumed_key = (epoch, batches)

    def pin_host_shard(self, shard) -> None:
        """Stamp the host-shard identity (shard_index, num_shards) into
        this loader's snapshot fingerprint after construction — the
        Trainer does this for elastic multi-host runs when the loader
        was built without one, so a DataLoaderState taken at world N
        actually REFUSES restore at world M instead of silently
        matching. Must happen before the fingerprint is first computed
        (i.e. before any state is recorded): re-stamping a live stream
        would be the very identity shift the fingerprint exists to
        catch."""
        shard = tuple(int(v) for v in shard)
        if self._fp is not None and self.host_shard != shard:
            raise _snapshot.SnapshotError(
                "pin_host_shard after the fingerprint was computed: the "
                "stream's identity is already fixed")
        self.host_shard = shard

    def snapshot_supported(self) -> bool:
        """num_procs workers interleave nondeterministically — no
        host-side state can reproduce that stream, so snapshots refuse."""
        return self.num_procs == 0

    def enable_snapshots(self) -> None:
        """Arm per-batch state recording (Trainer does this when the
        loader is attached as its data_loader). Must happen before the
        epoch whose mid-epoch positions you want to capture — epoch-
        boundary states are exact either way."""
        if not self.snapshot_supported():
            raise _snapshot.SnapshotUnsupported(
                f"DataLoader(num_procs={self.num_procs}) cannot snapshot: "
                "worker-process interleave order is nondeterministic")
        self._snapshot_on = True
        if self._cursor is None and hasattr(self.dataset, "cursor"):
            self._cursor = _snapshot.LiveCursor()
            self.dataset.cursor = self._cursor

    def state_dict(self) -> dict:
        """The resumable position of this loader's batch stream (a
        data/snapshot.py DataLoaderState as a JSON-clean dict), exact to
        the batch the consumer was last handed — checkpoint it next to
        the model (Trainer puts it in the crc32c host sidecar)."""
        if not self.snapshot_supported():
            raise _snapshot.SnapshotUnsupported(
                f"DataLoader(num_procs={self.num_procs}) cannot snapshot: "
                "worker-process interleave order is nondeterministic")
        key = self._consumed_key
        with self._ring_lock:
            st = dict(self._ring[key]) if key in self._ring else None
        if st is not None:
            return st
        if self._resume is not None:
            return self._resume.to_dict()  # armed but not yet iterated
        if key is not None:
            # the loader HAS been iterated but the consumed position is
            # not in the ring: either snapshots were armed after
            # iteration started, or the ring bound failed — fabricating
            # a position here would be the silent stream shift this
            # module exists to refuse
            raise _snapshot.SnapshotError(
                f"no recorded state for consumed position {key}: call "
                "enable_snapshots() before iterating (Trainer does this "
                "when the loader is attached)")
        return _snapshot.DataLoaderState(
            epoch=self._epoch, batches=0,
            epoch_seed=self.seed + self._epoch,
            fingerprint=self._fingerprint(),
        ).to_dict()

    def load_state_dict(self, state: dict) -> dict:
        """Arm a resume at `state`'s position; the next epoch iteration
        deterministically replays and skips what was already consumed.
        Returns a small info dict (epoch/batches/shard/record) for the
        caller's `data_resume` journal event. Raises SnapshotMismatch
        when the dataset or loader shape changed under the snapshot."""
        if not self.snapshot_supported():
            raise _snapshot.SnapshotUnsupported(
                f"DataLoader(num_procs={self.num_procs}) cannot snapshot: "
                "worker-process interleave order is nondeterministic")
        st = _snapshot.validate_state(state)
        if st.fingerprint and st.fingerprint != self._fingerprint():
            raise _snapshot.SnapshotMismatch(
                "data_state fingerprint mismatch: the dataset shard list, "
                "loader shape (batch size, seed, shuffle/buffer, "
                "drop_remainder), or host-shard slice (an elastic N->M "
                "world resize) changed since the snapshot — resuming "
                "would silently shift the stream")
        self._epoch = st.epoch
        self._resume = st
        self._consumed_key = None
        self.enable_snapshots()  # a restored loader keeps snapshotting
        budget = getattr(self.dataset, "bad_record_budget", None)
        if budget is not None and st.budget is not None:
            # boundary snapshot: counters restore directly; mid-epoch:
            # epoch-start values now, the replay re-spends the rest
            budget.set_spend(
                st.budget if st.batches == 0
                else (st.budget_epoch_start or st.budget))
        cur = st.cursor or {}
        return {"epoch": st.epoch, "batches": st.batches,
                "shard": cur.get("shard"), "record": cur.get("record")}

    def __iter__(self) -> Iterator[dict]:
        """Yield batches, producing up to `prefetch` ahead on a thread.

        This is the HOST half of the prefetch story (decode/augment
        latency); the DEVICE half — overlapping the H2D transfer itself
        with compute — is data/device_prefetch.py, which the Trainer
        stacks on top of this iterator (`--device-prefetch`)."""
        iter_epoch = self._epoch  # the epoch _batches() is about to run
        base = (self._resume.batches
                if self._resume is not None
                and self._resume.epoch == iter_epoch else 0)
        if self.prefetch <= 0:
            i = base
            for b in self._batches():
                i += 1
                self._mark_consumed(iter_epoch, i)
                yield b
            return
        # obs hooks: registry.py is jax-free, so this stays importable from
        # spawned data workers. Depth is sampled at every consumer get;
        # a get on an empty queue means the accelerator out-ran the host
        # pipeline (starvation — exactly the data_wait the StepClock sees).
        from deep_vision_tpu.obs.registry import get_registry

        reg = get_registry()
        labels = {"loader": self.name}  # train vs val stay distinguishable
        g_depth = reg.gauge("data_prefetch_depth",
                            "prefetch batches ready when the consumer asked",
                            labels=labels)
        c_starved = reg.counter("data_prefetch_starved_total",
                                "consumer gets that found the queue empty",
                                labels=labels)
        c_batches = reg.counter("data_batches_total", "batches yielded",
                                labels=labels)
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        err: List[BaseException] = []

        def producer():
            try:
                for b in self._batches():
                    q.put(b)
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        first = True
        i = base
        while True:
            depth = q.qsize()
            t0 = now_us()
            item = q.get()
            if item is sentinel:
                # end-of-epoch wait is not starvation — and not fetch
                # time either: a span here would stamp one giant
                # producer-drain wait per epoch onto the fetch totals
                break
            trace_event("data/fetch", t0, loader=self.name,
                        prefetch_depth=depth)
            g_depth.set(depth)
            # skip the first get (the producer just started — inevitably
            # empty): counting it would stamp phantom starvation on every
            # epoch of a healthy pipeline
            if depth == 0 and not first:
                c_starved.inc()
            first = False
            c_batches.inc()
            i += 1
            # marked BEFORE the yield: a batch handed to the consumer is
            # consumed — a checkpoint taken mid-step must not replay it
            self._mark_consumed(iter_epoch, i)
            yield item
        t.join()
        if err:
            raise err[0]
