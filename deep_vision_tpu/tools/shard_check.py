"""Offline semantic validation of the curated sharding tables.

DV205 (lint/distlint.py) proves a ShardingRules table is WELL-FORMED —
literal patterns, trailing catch-all, declared axes — without running
anything. This checker proves the table still FITS its model family:
it builds each family's train state as a pure abstract tree
(`jax.eval_shape` over `create_train_state` with ShapeDtypeStruct
inputs — zero device arrays materialized, zero XLA compiles) and
replays the table's first-match-wins resolution over every leaf:

  - coverage floor: at least `min_sharded` float leaves actually shard
    on a nominal mesh (the 108 -> 34 MULTICHIP regression, caught
    before any hardware — a gutted table fails HERE);
  - first-match shadowing: rules that match leaves but never FIRST
    (dead by ordering — the rule above them claims every leaf);
  - dead patterns: non-catch-all rules that match no leaf of the
    family's tree at all (the table went stale against the model).

Shadowed/dead rules are reported (the table may deliberately carry
rules for model variants this tiny config does not instantiate);
coverage-floor violations and resolution errors fail.

Runs standalone (`python tools/shard_check.py`), inside `make lint`,
and as the `check_sharding_tables` preflight rung. Writes nothing to
disk; needs no mesh, no devices, no TPU.
"""
from __future__ import annotations

import argparse
import fnmatch
import json
import sys
from typing import Dict, List, Optional, Sequence

import numpy as np

#: nominal mesh-axis sizes resolution is audited against: the smallest
#: shape where both axes are real (the mesh4x2 test topology). A dim an
#: axis of size 2 cannot divide will not divide size 4 or 8 either.
NOMINAL_MESH = {"data": 4, "model": 2}

FAMILIES = ("vit", "moe", "resnet")


def _abstract_state(family: str):
    """The family's tiny train state as a tree of ShapeDtypeStruct —
    built entirely under `jax.eval_shape`, so nothing compiles and no
    device buffer is ever allocated."""
    import jax

    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.train.optimizers import build_optimizer

    if family in ("vit", "moe"):
        from deep_vision_tpu.models.vit import ViT

        model = ViT(depth=2, dim=16, num_heads=2, patch=8, num_classes=8,
                    num_experts=2 if family == "moe" else 0)
        sample = jax.ShapeDtypeStruct((2, 16, 16, 3), np.float32)
    elif family == "resnet":
        from deep_vision_tpu.models.resnet import BottleneckBlock, ResNet

        model = ResNet(stage_sizes=(1, 1, 1, 1), block=BottleneckBlock,
                       width=16, num_classes=64)
        sample = jax.ShapeDtypeStruct((2, 32, 32, 3), np.float32)
    else:
        raise ValueError(f"unknown family {family!r} (one of {FAMILIES})")
    tx = build_optimizer("sgd", learning_rate=0.05, momentum=0.9)
    return jax.eval_shape(
        lambda s: create_train_state(model, tx, s), sample)


def check_table(rules, state,
                mesh_axes: Optional[Dict[str, int]] = None) -> dict:
    """Replay first-match resolution over an abstract state tree.

    -> report dict: {table, float_leaves, sharded, min_sharded,
    floor_ok, unmatched, unmatched_paths, rules: {pattern:
    {first, any}}, shadowed, dead, dropped_dims, ok}.
    """
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.parallel.shardmap import leaf_path, normalize_path

    mesh_axes = dict(mesh_axes or NOMINAL_MESH)
    pats = [pat for pat, _ in rules.rules]
    stats = {pat: {"first": 0, "any": 0} for pat in pats}
    catch_all = pats[-1]
    report = {
        "table": rules.name,
        "mesh": mesh_axes,
        "float_leaves": 0,
        "sharded": 0,
        "replicated": 0,
        "min_sharded": int(rules.min_sharded),
        "unmatched": 0,
        "unmatched_paths": [],
        "dropped_dims": [],
        "errors": [],
    }

    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    for key_path, leaf in leaves:
        path = leaf_path(key_path)
        norm = normalize_path(path)
        matches = [pat for pat in pats
                   if fnmatch.fnmatchcase(norm, pat)]
        if not matches:  # unreachable with a constructed table
            report["errors"].append(f"no rule matched {norm!r}")
            continue
        for pat in matches:
            stats[pat]["any"] += 1
        first = matches[0]
        stats[first]["first"] += 1
        spec = dict(rules.rules)[first]
        shape = tuple(getattr(leaf, "shape", ()))
        if len(spec) > len(shape):
            report["errors"].append(
                f"rule {first!r}: spec {spec!r} has {len(spec)} entries "
                f"but leaf {path} has rank {len(shape)}")
            continue
        # mirror ShardingRules._entry_for: unknown axis refuses, an
        # axis product that does not divide the dim drops (replicates)
        sharded_dims = 0
        for d, entry in enumerate(spec):
            if entry is None:
                continue
            axes = (entry,) if isinstance(entry, str) else tuple(entry)
            unknown = [a for a in axes if a not in mesh_axes]
            if unknown:
                report["errors"].append(
                    f"rule {first!r}: unknown mesh axis {unknown[0]!r} "
                    f"at leaf {path}")
                continue
            size = int(np.prod([mesh_axes[a] for a in axes]))
            if size <= 1:
                continue
            if int(shape[d]) % size != 0:
                report["dropped_dims"].append(
                    {"path": path, "rule": first, "dim": int(shape[d]),
                     "axes": list(axes)})
                continue
            sharded_dims += 1
        dtype = getattr(leaf, "dtype", None)
        if dtype is not None and jnp.issubdtype(dtype, jnp.floating):
            report["float_leaves"] += 1
            if first == catch_all:
                report["unmatched"] += 1
                report["unmatched_paths"].append(path)
            if sharded_dims:
                report["sharded"] += 1
            else:
                report["replicated"] += 1

    report["rules"] = stats
    report["shadowed"] = [pat for pat in pats[:-1]
                          if stats[pat]["any"] and not stats[pat]["first"]]
    report["dead"] = [pat for pat in pats[:-1] if not stats[pat]["any"]]
    report["floor_ok"] = report["sharded"] >= report["min_sharded"]
    report["ok"] = report["floor_ok"] and not report["errors"]
    return report


def check_family(family: str,
                 mesh_axes: Optional[Dict[str, int]] = None,
                 rules=None) -> dict:
    """Build the family's abstract state and audit its curated table
    (or an injected `rules` — the gutted-table test path)."""
    from deep_vision_tpu.parallel.shardmap import FAMILY_RULES

    table = rules if rules is not None else FAMILY_RULES[family]
    report = check_table(table, _abstract_state(family),
                         mesh_axes=mesh_axes)
    report["family"] = family
    return report


def render_report(report: dict) -> str:
    line = (f"shard_check[{report['family']}]: "
            f"{'PASS' if report['ok'] else 'FAIL'} "
            f"{report['sharded']}/{report['min_sharded']} sharded "
            f"(float_leaves={report['float_leaves']}, "
            f"unmatched={report['unmatched']}, "
            f"dropped_dims={len(report['dropped_dims'])})")
    extra = []
    for err in report["errors"]:
        extra.append(f"  error: {err}")
    for pat in report["shadowed"]:
        extra.append(f"  shadowed (never first match): {pat!r}")
    for pat in report["dead"]:
        extra.append(f"  dead (matches no leaf): {pat!r}")
    if not report["floor_ok"]:
        extra.append(
            f"  coverage floor violated: {report['sharded']} < "
            f"{report['min_sharded']} — the table no longer fits the "
            "model (the 108->34 regression shape)")
    return "\n".join([line] + extra)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python tools/shard_check.py",
        description="device-free semantic audit of the curated "
                    "VIT/MOE/RESNET sharding tables",
    )
    p.add_argument("--family", default=None, choices=FAMILIES,
                   help="audit one family (default: all)")
    p.add_argument("--format", choices=("human", "json"),
                   default="human")
    args = p.parse_args(argv)

    families = (args.family,) if args.family else FAMILIES
    reports: List[dict] = []
    failed = False
    for family in families:
        try:
            report = check_family(family)
        except Exception as e:  # never a traceback: a broken table is
            # a finding, not a crash
            report = {"family": family, "ok": False,
                      "errors": [f"{type(e).__name__}: {e}"],
                      "sharded": 0, "min_sharded": 0, "float_leaves": 0,
                      "unmatched": 0, "dropped_dims": [],
                      "shadowed": [], "dead": [], "floor_ok": False}
        reports.append(report)
        failed = failed or not report["ok"]

    if args.format == "json":
        print(json.dumps({"version": 1, "reports": reports,
                          "failed": failed}, indent=2, default=str))
    else:
        for report in reports:
            print(render_report(report))
        tail = (f"shard_check: {len(reports)} table(s), "
                f"{'FAIL' if failed else 'ok'}")
        print(tail, file=sys.stderr if failed else sys.stdout)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
