"""locksmith: opt-in runtime lock-order sanitizer for the serving/obs stack.

The static pack (lint/concur.py, DV101-DV104) proves lock discipline
*within* a module at review time; this module catches the dynamic
residue — cross-module lock orders (device lock vs journal lock vs
flight ring), hold-time outliers under real traffic — the way
ThreadSanitizer/lockdep catch what code review cannot. It is armed in
`make serve-smoke` and `make chaos-smoke`, which assert ZERO
`lock_order_violation` events across a full serving run.

Adoption is a drop-in swap at the construction site:

    self._lock = locksmith.lock("serve.device")       # was threading.Lock()
    self._cond = locksmith.condition("serve.queue")   # was threading.Condition()

Every `with self._lock:` / `acquire()` / `release()` / `wait()` keeps
working. Disabled (the default, and the production steady state), each
operation pays ONE module-global load + None check on top of the raw
primitive — the same budget as resilience/faults.fire and flight.note,
probed by chaos-smoke.

Armed (`locksmith.arm(journal=...)`), the sanitizer keeps a per-thread
stack of held locks (name + acquisition site) and:

  - records every held->acquired edge in a global lock-order graph; the
    first time an edge's REVERSE is already present, that is an order
    inversion — two threads taking the opposite paths deadlock — and a
    typed `lock_order_violation` journal event carries both acquisition
    stacks (`locksmith_order_violations_total` counts them);
  - flags hold-time and acquire-wait outliers over the configurable
    `hold_ms` / `wait_ms` thresholds as typed `lock_contention` events
    (`kind: hold | wait`), with per-lock max-hold / contention stats in
    `report()` — what tools/obs_report.py renders as the lock-health row.

Deadlock-safety of the sanitizer itself: journal.write takes the
journal's own (instrumented) lock, so emitting synchronously from
inside an acquire path could re-enter the very lock being acquired.
Events are therefore queued at detection time (counters and the
in-memory violation list update immediately) and flushed to the journal
only when the detecting thread holds no instrumented locks — at its
next full release, or at `disarm()`. A thread-local reentrancy latch
keeps the flush's own lock traffic out of the graph.

Same-name lock instances (every BatchingQueue condition is
"serve.queue") are one NODE in the graph, like lockdep lock classes:
ordering is checked between lock *roles*, and nested same-name
acquisition is treated as reentrant rather than a self-cycle. The
single-instance nested-acquisition deadlock is DV102's static self-loop
check instead.
"""
from __future__ import annotations

import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from deep_vision_tpu.core import knobs

#: env switch for subprocess runs (chaos-smoke children): a true flag
#: value arms at train_cli startup; thresholds override the defaults
ENV_ARM = "DVT_LOCKSMITH"
ENV_HOLD_MS = "DVT_LOCKSMITH_HOLD_MS"
ENV_WAIT_MS = "DVT_LOCKSMITH_WAIT_MS"

DEFAULT_HOLD_MS = 1000.0
DEFAULT_WAIT_MS = 1000.0
_STACK_DEPTH = 8

_active: Optional["Sanitizer"] = None


class Sanitizer:
    """Process-wide lock-order/contention monitor (install via arm())."""

    def __init__(self, journal=None, registry=None,
                 hold_ms: float = DEFAULT_HOLD_MS,
                 wait_ms: float = DEFAULT_WAIT_MS,
                 stack_depth: int = _STACK_DEPTH):
        self.journal = journal
        self.hold_ms = float(hold_ms)
        self.wait_ms = float(wait_ms)
        self.stack_depth = int(stack_depth)
        self._tls = threading.local()
        # RAW lock, never instrumented: guards the graph + stats; leaf by
        # construction (nothing is called while holding it)
        self._mu = threading.Lock()
        self._edges: Dict[tuple, dict] = {}  # (a, b) -> first-seen site
        self._flagged: set = set()  # frozenset({a, b}) latch per pair
        self._violations: List[dict] = []
        self._stats: Dict[str, dict] = {}  # name -> acquisition stats
        self._pending: deque = deque()  # journal rows awaiting a safe point
        if registry is None:
            from deep_vision_tpu.obs.registry import get_registry

            registry = get_registry()
        self._c_violations = registry.counter(
            "locksmith_order_violations_total",
            "runtime lock-order inversions detected")
        self._c_contention = {
            kind: registry.counter(
                "locksmith_contention_total",
                "lock holds/waits over the configured threshold",
                labels={"kind": kind})
            for kind in ("hold", "wait")}

    # -- per-thread bookkeeping -------------------------------------------

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _in_emit(self) -> bool:
        return getattr(self._tls, "in_emit", False)

    def _site(self) -> List[str]:
        # skip the sanitizer + wrapper frames; keep the caller's tail
        frames = traceback.extract_stack(limit=self.stack_depth + 3)[:-3]
        return [f"{f.filename}:{f.lineno} in {f.name}" for f in frames]

    def _stat(self, name: str) -> dict:
        s = self._stats.get(name)
        if s is None:
            s = self._stats[name] = {
                "acquisitions": 0, "max_hold_ms": 0.0, "max_wait_ms": 0.0,
                "hold_contentions": 0, "wait_contentions": 0}
        return s

    # -- wrapper hooks -----------------------------------------------------

    def acquired(self, name: str, wait_s: float) -> None:
        """Called by a wrapper AFTER its raw acquire succeeded."""
        if self._in_emit():
            return
        held = self._held()
        for i, entry in enumerate(held):
            if entry[0] == name:
                # same lock class re-entered (RLock, or a sibling instance
                # sharing the role name): count, no self-edge
                held[i] = (name, entry[1], entry[2], entry[3] + 1)
                return
        site = self._site()
        wait_ms = wait_s * 1e3
        with self._mu:
            st = self._stat(name)
            st["acquisitions"] += 1
            if wait_ms > st["max_wait_ms"]:
                st["max_wait_ms"] = wait_ms
            slow_wait = wait_ms > self.wait_ms
            if slow_wait:
                st["wait_contentions"] += 1
            violation = None
            for h, _, h_site, _ in held:
                edge = (h, name)
                if edge not in self._edges:
                    self._edges[edge] = {
                        "thread": threading.current_thread().name,
                        "stack": site, "held_at": list(h_site)}
                rev = self._edges.get((name, h))
                pair = frozenset((h, name))
                if rev is not None and pair not in self._flagged:
                    self._flagged.add(pair)
                    violation = {
                        "lock_a": h, "lock_b": name,
                        "thread": threading.current_thread().name,
                        "stack": site,
                        "prior_thread": rev["thread"],
                        "prior_stack": rev["stack"],
                    }
                    self._violations.append(violation)
        if slow_wait:
            self._c_contention["wait"].inc()
            self._queue_row("lock_contention", lock=name, kind="wait",
                            ms=round(wait_ms, 3),
                            threshold_ms=self.wait_ms,
                            thread=threading.current_thread().name)
        if violation is not None:
            self._c_violations.inc()
            self._queue_row("lock_order_violation", **violation)
        held.append((name, time.perf_counter(), site, 1))

    def released(self, name: str, flush: bool = True) -> None:
        """Called by a wrapper AFTER its raw release (so a flush here can
        re-acquire the very lock just released, e.g. the journal's)."""
        if self._in_emit():
            return
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                nm, t0, site, count = held[i]
                if count > 1:
                    held[i] = (nm, t0, site, count - 1)
                    return
                del held[i]
                hold_ms = (time.perf_counter() - t0) * 1e3
                with self._mu:
                    st = self._stat(name)
                    if hold_ms > st["max_hold_ms"]:
                        st["max_hold_ms"] = hold_ms
                    slow = hold_ms > self.hold_ms
                    if slow:
                        st["hold_contentions"] += 1
                if slow:
                    self._c_contention["hold"].inc()
                    self._queue_row(
                        "lock_contention", lock=name, kind="hold",
                        ms=round(hold_ms, 3), threshold_ms=self.hold_ms,
                        thread=threading.current_thread().name,
                        site=site[-1] if site else "")
                break
        if flush and not held:
            self.flush_pending()

    # -- emission ----------------------------------------------------------

    def _queue_row(self, event: str, **fields) -> None:
        if self.journal is not None:
            self._pending.append((event, fields))

    def flush_pending(self) -> None:
        """Write queued events; only call while holding no instrumented
        locks (end-of-release safe point, or disarm())."""
        if self.journal is None or not self._pending:
            return
        self._tls.in_emit = True
        try:
            while True:
                try:
                    event, fields = self._pending.popleft()
                except IndexError:
                    break
                try:
                    # deferred-flush plumbing: every row was enqueued by
                    # _queue_row with a literal typed event
                    # (lock_order_violation / lock_contention)
                    # jaxlint: disable=DV204 -- typed at _queue_row sites
                    self.journal.write(event, **fields)
                except Exception:
                    pass  # the sanitizer must never kill what it watches
        finally:
            self._tls.in_emit = False

    # -- reading back ------------------------------------------------------

    def violations(self) -> List[dict]:
        with self._mu:
            return list(self._violations)

    def report(self) -> dict:
        """{violations, locks: {name: stats}, top_contended, max_hold_ms,
        max_hold_lock} — the lock-health summary the smokes assert on and
        obs_report renders from the journal."""
        with self._mu:
            locks = {k: dict(v) for k, v in self._stats.items()}
            violations = list(self._violations)
        top = None
        worst = (0, 0.0)
        max_hold = ("", 0.0)
        for name, st in locks.items():
            score = (st["hold_contentions"] + st["wait_contentions"],
                     st["max_wait_ms"] + st["max_hold_ms"])
            if score > worst:
                worst, top = score, name
            if st["max_hold_ms"] > max_hold[1]:
                max_hold = (name, st["max_hold_ms"])
        return {
            "armed": _active is self,
            "violations": violations,
            "locks": locks,
            "top_contended": top if worst[0] > 0 else None,
            "max_hold_lock": max_hold[0] or None,
            "max_hold_ms": round(max_hold[1], 3),
        }


# -- instrumented primitives --------------------------------------------------

class InstrumentedLock:
    """threading.Lock with a role name, observable by the armed sanitizer.

    Picklable (data-loader worker processes receive copies of objects
    holding one): the raw lock is recreated on unpickle, like the
    BadRecordBudget contract in data/records.py.
    """

    __slots__ = ("name", "_lk", "_reentrant")

    def __init__(self, name: str, raw=None, reentrant: bool = False):
        self.name = name
        self._reentrant = reentrant
        if raw is None:
            raw = threading.RLock() if reentrant else threading.Lock()
        self._lk = raw

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        san = _active
        if san is None:
            return self._lk.acquire(blocking, timeout)
        t0 = time.perf_counter()
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            san.acquired(self.name, time.perf_counter() - t0)
        return ok

    def release(self) -> None:
        self._lk.release()
        san = _active
        if san is not None:
            san.released(self.name)

    def locked(self) -> bool:
        fn = getattr(self._lk, "locked", None)  # RLock lacks it pre-3.13
        return bool(fn()) if fn is not None else False

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.release()
        return False

    def __getstate__(self):
        return {"name": self.name, "reentrant": self._reentrant}

    def __setstate__(self, state):
        # the raw primitive is recreated with its original reentrancy: an
        # rlock that unpickled as a plain Lock would self-deadlock in the
        # worker on the first nested acquire
        self.name = state["name"]
        self._reentrant = state.get("reentrant", False)
        self._lk = (threading.RLock() if self._reentrant
                    else threading.Lock())

    def __repr__(self) -> str:
        return f"InstrumentedLock({self.name!r})"


class InstrumentedCondition:
    """threading.Condition with a role name.

    `wait()` logically releases the lock for its duration — the sanitizer
    is told, so a dispatcher parked on an empty queue neither shows up as
    a marathon hold nor contributes phantom ordering edges while asleep.

    Known blind spot: the re-acquire after a wakeup is recorded with
    wait_s=0 — threading.Condition gives no handle on how much of wait()
    was sleep vs re-acquire contention, so `kind=wait` contention on a
    condition's lock is only measured for explicit acquire()/`with`
    entries, not the post-notify stampede. Hold times and ordering are
    unaffected.
    """

    __slots__ = ("name", "_cv")

    def __init__(self, name: str, lock=None):
        self.name = name
        self._cv = threading.Condition(lock)

    def acquire(self, *args) -> bool:
        san = _active
        if san is None:
            return self._cv.acquire(*args)
        t0 = time.perf_counter()
        ok = self._cv.acquire(*args)
        if ok:
            san.acquired(self.name, time.perf_counter() - t0)
        return ok

    def release(self) -> None:
        self._cv.release()
        san = _active
        if san is not None:
            # no flush here: we may be between a wait() and its caller's
            # own critical-section logic; the next lock-free release or
            # disarm() drains
            san.released(self.name, flush=False)

    def wait(self, timeout: Optional[float] = None) -> bool:
        san = _active
        if san is not None:
            san.released(self.name, flush=False)
        try:
            return self._cv.wait(timeout)
        finally:
            san = _active
            if san is not None:
                san.acquired(self.name, 0.0)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        san = _active
        if san is not None:
            san.released(self.name, flush=False)
        try:
            return self._cv.wait_for(predicate, timeout)
        finally:
            san = _active
            if san is not None:
                san.acquired(self.name, 0.0)

    def notify(self, n: int = 1) -> None:
        self._cv.notify(n)

    def notify_all(self) -> None:
        self._cv.notify_all()

    def __enter__(self) -> "InstrumentedCondition":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"InstrumentedCondition({self.name!r})"


# -- module API ----------------------------------------------------------------

def lock(name: str) -> InstrumentedLock:
    """A named mutex; drop-in for threading.Lock() at construction."""
    return InstrumentedLock(name)


def rlock(name: str) -> InstrumentedLock:
    """A named reentrant mutex (the sanitizer treats same-name nesting as
    reentrant either way; the raw primitive must still allow it, and the
    reentrancy survives pickling into worker processes)."""
    return InstrumentedLock(name, reentrant=True)


def condition(name: str) -> InstrumentedCondition:
    """A named condition variable; drop-in for threading.Condition()."""
    return InstrumentedCondition(name)


def arm(journal=None, registry=None, hold_ms: float = DEFAULT_HOLD_MS,
        wait_ms: float = DEFAULT_WAIT_MS) -> Sanitizer:
    """Install (and return) the process-wide sanitizer. Idempotent-ish:
    arming replaces any previous sanitizer (its findings stay readable
    via the returned handle)."""
    global _active
    san = Sanitizer(journal=journal, registry=registry, hold_ms=hold_ms,
                    wait_ms=wait_ms)
    _active = san
    return san


def arm_from_env(journal=None, registry=None) -> Optional[Sanitizer]:
    """Arm when DVT_LOCKSMITH is set (subprocess smoke runs); no-op and
    None otherwise. Threshold knobs follow the mistype-raises
    convention: DVT_LOCKSMITH_HOLD_MS=soon must fail loudly here, not
    silently sanitize with a garbage threshold (or crash later)."""
    if not knobs.get_flag(ENV_ARM):
        return None
    return arm(journal=journal, registry=registry,
               hold_ms=knobs.get_float(ENV_HOLD_MS, DEFAULT_HOLD_MS),
               wait_ms=knobs.get_float(ENV_WAIT_MS, DEFAULT_WAIT_MS))


def disarm() -> None:
    """Uninstall and flush any queued journal rows."""
    global _active
    san, _active = _active, None
    if san is not None:
        san.flush_pending()


def get_sanitizer() -> Optional[Sanitizer]:
    return _active


def report() -> dict:
    """The active sanitizer's report(), or a disarmed placeholder."""
    san = _active
    if san is None:
        return {"armed": False, "violations": [], "locks": {},
                "top_contended": None, "max_hold_lock": None,
                "max_hold_ms": 0.0}
    return san.report()
