"""Elastic, accelerator-layer resilience: survive the fleet, not just the step.

PR 4 made storage and data I/O unreliable-by-design; every failure in the
repo's own run history since happened one layer down, at the accelerator:

  BENCH_r02       died mid-run on a dropped backend connection
  BENCH_r04/r05   dead-tunnel timeouts (the backend HANGS, no exception)
  MULTICHIP_r01   libtpu client/terminal version skew, fatal 4 minutes in

This module is the shared substrate for treating those as *expected
inputs*:

- `classify_backend_error`: one classification for every consumer —
  `connection_lost` / `timeout` (retryable: rebuild the client and
  replay), `version_skew` (NOT retryable: a skew does not heal mid-run —
  fail fast, that is `tools/preflight.py`'s job to catch before minutes
  are burned), `unknown` (a program bug wearing a RuntimeError; only
  callers replaying pure computation, like bench.py, opt into retrying
  it).
- `BackendSupervisor`: the rebuild-replay choreography bench.py
  prototyped (BENCH_r02's bespoke loop), lifted into one reusable
  object: a single `RetryPolicy` holds the backoff jitter RNG (the
  `_ACTIVE_POLICY` module-global shim this replaces could silently
  re-seed and re-draw the same "jittered" delay), failures journal typed
  `backend_lost` events and recoveries `backend_recovered`, with flight
  recorder breadcrumbs on both. The Trainer and bench.py both drive it.
- cross-mesh sharding metadata (`sharding_meta` / `replace_on_mesh`):
  serializable leaf-level PartitionSpecs saved in the checkpoint sidecar
  so a run checkpointed on N hosts/devices restores onto M — specs are
  re-resolved against the *current* mesh, dropping axes the new topology
  cannot honor (axis absent, or dim no longer divisible) per dimension.
- `backend_alive`: the threaded liveness probe (a dead relay BLOCKS in
  socket recv rather than raising, BENCH_r04's rc=124 — only a join
  timeout can see it), shared by bench.py and the preflight.

jax-free at import (the resilience/ contract — spawned data workers
import this package): jax is imported inside the functions that need it.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from deep_vision_tpu.resilience.retry import RetryPolicy

# -- backend failure classification -------------------------------------------

#: classification kinds; `backend_lost` journal events carry one of these
#: and tools/check_journal.py --strict enforces the enum
KIND_CONNECTION = "connection_lost"
KIND_TIMEOUT = "timeout"
KIND_VERSION_SKEW = "version_skew"
KIND_UNKNOWN = "unknown"
BACKEND_LOST_KINDS = (KIND_CONNECTION, KIND_TIMEOUT, KIND_VERSION_SKEW,
                      KIND_UNKNOWN)
#: kinds a rebuild-and-replay can actually heal
RETRYABLE_KINDS = (KIND_CONNECTION, KIND_TIMEOUT)

#: message fingerprints, checked lowercased. Version skew FIRST: the
#: MULTICHIP_r01 error ("FAILED_PRECONDITION: libtpu version mismatch:
#: terminal has ..., client AOT libtpu has ...") also mentions the word
#: "client", which must not fall through to a connection match.
_VERSION_PATTERNS = (
    "libtpu version mismatch",
    "version mismatch",
    "incompatible libtpu",
)
_TIMEOUT_PATTERNS = (
    "deadline_exceeded",
    "deadline exceeded",
    "timed out",
    "timeout",
    "heartbeat",
    "liveness probe still blocked",  # backend_alive's dead-tunnel verdict
)
_CONNECTION_PATTERNS = (
    "connection reset",
    "connection refused",
    "connection closed",
    "connection aborted",
    "backend connection",
    "body closed",
    "socket closed",
    "broken pipe",
    "unavailable",
    "remote_compile",
    "tunnel",
)


def classify_backend_error(exc) -> str:
    """Classify an exception (or message string) from the accelerator layer.

    Returns one of `BACKEND_LOST_KINDS`. The exception TYPE gates the
    message match: jax wraps every backend/transport failure in
    RuntimeError (JaxRuntimeError/XlaRuntimeError subclass it), so only
    RuntimeErrors may classify as a lost backend. Everything else is
    `unknown` no matter what its message says — a ValueError mentioning
    'timeout' in a file name must not become retryable, and a raw
    OSError/ConnectionError is the STORAGE/data layer's weather (its own
    RetryPolicy already absorbed what it could; tearing down the backend
    over it would trade a read retry for a full restore-and-replay).
    """
    if isinstance(exc, BaseException):
        if not isinstance(exc, RuntimeError):
            return KIND_UNKNOWN
        msg = f"{type(exc).__name__}: {exc}"
    else:
        msg = str(exc)
    low = msg.lower()
    for pat in _VERSION_PATTERNS:
        if pat in low:
            return KIND_VERSION_SKEW
    for pat in _TIMEOUT_PATTERNS:
        if pat in low:
            return KIND_TIMEOUT
    for pat in _CONNECTION_PATTERNS:
        if pat in low:
            return KIND_CONNECTION
    return KIND_UNKNOWN


def backend_alive(budget_s: float, probe=None, with_kind: bool = False):
    """(ok, error) — does a trivial device op complete within `budget_s`?

    The op runs in a worker thread: against a dead relay it blocks forever
    in socket recv (no exception, BENCH_r04's failure mode), so a plain
    try/except cannot detect the outage — a join timeout can. The orphaned
    thread stays blocked; callers on the degraded path exit via os._exit
    (bench) or report-and-return (preflight), so it never wedges teardown.

    `with_kind=True` returns (ok, error, kind) with the failure classified
    from the EXCEPTION OBJECT the probe raised (a hang is `timeout`) —
    re-classifying the formatted message would lose the exception-type
    gate and let a probe bug mentioning 'timeout' impersonate a dead
    tunnel.
    """
    if probe is None:
        def probe():
            import jax
            import jax.numpy as jnp

            jax.devices()  # backend init is itself part of the handshake
            return float(jnp.ones((), jnp.float32).sum())
    out: Dict[str, Any] = {}

    def run():
        try:
            out["value"] = probe()
        except Exception as e:
            out["exc"] = e

    t = threading.Thread(target=run, daemon=True, name="backend-liveness")
    t.start()
    t.join(budget_s)
    if t.is_alive():
        err = (f"backend liveness probe still blocked after "
               f"{budget_s:.0f}s (dead tunnel?)")
        return (False, err, KIND_TIMEOUT) if with_kind else (False, err)
    if "exc" in out:
        e = out["exc"]
        err = (f"backend liveness probe failed: "
               f"{type(e).__name__}: {e}")
        if with_kind:
            return False, err, classify_backend_error(e)
        return False, err
    return (True, None, None) if with_kind else (True, None)


# -- the rebuild-replay supervisor --------------------------------------------

class BackendSupervisor:
    """Backend-loss detection + rebuild-replay bookkeeping, in one place.

    One supervisor serves one recovery surface (a bench session, a
    Trainer.fit): it owns the `RetryPolicy` whose jitter RNG advances one
    draw per backoff, journals typed `backend_lost` / `backend_recovered`
    events, bumps `backend_lost_total{kind=}` /
    `backend_recoveries_total`, and leaves flight-recorder breadcrumbs so
    a degraded-result postmortem shows the recovery attempts that led
    there.

    The caller keeps its own control flow (what "rebuild" and "replay"
    mean is caller-specific — bench rebuilds the jitted step and replays
    the timed windows; the Trainer re-jits, restores the last checkpoint,
    and replays the epoch); the supervisor decides *whether* another
    attempt is worth it and paces it:

        retrying = sup.on_failure(attempt, exc, step=...)
        if not retrying:
            raise
        sup.recover(attempt)           # breadcrumb + backoff + cache clear
        ... rebuild + replay ...
        sup.on_recovered(attempt, step=...)

    `retry_unclassified=True` (bench) retries `unknown` failures too — a
    bench window is a replayable pure computation, so any Exception is
    worth one more attempt. The Trainer keeps the default False: an
    unknown exception there is a program bug and must propagate.
    `version_skew` is never retried: it cannot heal mid-run, and burning
    the retry budget on it is exactly the minutes `tools/preflight.py`
    exists to save.
    """

    def __init__(self, max_retries: int = 5, policy: Optional[RetryPolicy] = None,
                 journal=None, registry=None, name: str = "backend",
                 retry_unclassified: bool = False,
                 clear_caches_after: int = 2):
        # max_attempts counts the first try too: max_retries retries on top
        self.policy = policy or RetryPolicy(
            name=name, max_attempts=int(max_retries) + 1, base_delay_s=2.0,
            multiplier=2.0, max_delay_s=15.0, jitter=0.25, journal=journal,
            registry=registry, retry_on=Exception,
        )
        self.name = name
        self.journal = journal if journal is not None else self.policy.journal
        if self.policy.journal is None:
            # one journal serves both event streams: the typed
            # backend_lost/backend_recovered rows AND the shared `retry`
            # rows the policy emits per attempt
            self.policy.journal = self.journal
        self._registry = registry
        self.retry_unclassified = bool(retry_unclassified)
        self.clear_caches_after = int(clear_caches_after)

    # -- decisions ---------------------------------------------------------

    def classify(self, exc) -> str:
        return classify_backend_error(exc)

    def should_retry(self, attempt: int, exc) -> bool:
        """Budget + classification: is attempt `attempt`'s failure worth a
        rebuild-and-replay?"""
        if isinstance(exc, (KeyboardInterrupt, SystemExit)):
            return False
        kind = self.classify(exc)
        if kind == KIND_VERSION_SKEW:
            return False  # will not heal; fail fast (preflight's domain)
        if kind not in RETRYABLE_KINDS and not self.retry_unclassified:
            return False
        return self.policy.should_retry(attempt, exc)

    # -- event plumbing ----------------------------------------------------

    def _counter(self, name: str, help: str, labels=None):
        reg = self._registry
        if reg is None:
            from deep_vision_tpu.obs.registry import get_registry

            reg = get_registry()
        return reg.counter(name, help, labels=labels)

    def on_failure(self, attempt: int, exc, step: Optional[int] = None,
                   context: Optional[str] = None) -> bool:
        """Record one backend failure; returns whether to retry.

        Journals a typed `backend_lost` event (kind from the classifier),
        bumps `backend_lost_total{kind=}`, breadcrumbs the flight
        recorder, and emits the shared `retry` event so the existing
        retry dashboards see these attempts too.
        """
        kind = self.classify(exc)
        retrying = self.should_retry(attempt, exc)
        try:
            self._counter("backend_lost_total", "backend failures observed",
                          labels={"kind": kind}).inc()
        except Exception:
            pass
        err = f"{type(exc).__name__}: {exc}"[:500] if isinstance(
            exc, BaseException) else str(exc)[:500]
        if self.journal is not None:
            row = {"attempt": int(attempt), "error": err, "kind": kind,
                   "retrying": bool(retrying)}
            if step is not None:
                row["step"] = int(step)
            if context:
                row["context"] = str(context)
            try:
                self.journal.write("backend_lost", **row)
            except Exception:
                pass
        try:
            from deep_vision_tpu.obs import flight as _flight

            _flight.note("backend_lost", attempt=int(attempt), kind=kind,
                         error=err[:200])
        except Exception:
            pass
        if isinstance(exc, BaseException):
            self.policy.note(attempt, exc,
                             "retrying" if retrying else "gave_up")
        return retrying

    def recover(self, attempt: int) -> float:
        """Pace the next rebuild: breadcrumb, the policy's jittered backoff
        (ONE RNG, advancing per draw), and a jax cache clear on later
        attempts (a stale compiled-executable cache can pin a dead client).
        Returns the delay slept."""
        try:
            from deep_vision_tpu.obs import flight as _flight

            _flight.note("backend_recovery", attempt=int(attempt))
        except Exception:
            pass
        delay = self.policy.backoff(attempt)
        if attempt >= self.clear_caches_after:
            try:
                import jax

                jax.clear_caches()
            except Exception:
                pass
        return delay

    def on_recovered(self, attempt: int, step: Optional[int] = None) -> None:
        """The rebuilt backend made real progress again: journal the typed
        `backend_recovered` event and bump the recovery counter."""
        try:
            self._counter("backend_recoveries_total",
                          "successful backend rebuild-replays").inc()
        except Exception:
            pass
        if self.journal is not None:
            row = {"attempt": int(attempt)}
            if step is not None:
                row["step"] = int(step)
            try:
                self.journal.write("backend_recovered", **row)
            except Exception:
                pass
        try:
            from deep_vision_tpu.obs import flight as _flight

            _flight.note("backend_recovered", attempt=int(attempt))
        except Exception:
            pass


# -- cross-mesh sharding metadata ---------------------------------------------

#: reserved sidecar key the checkpoint layer stores the metadata under
SHARDING_META_KEY = "__sharding__"
SHARDING_META_FORMAT = 1


def _leaf_paths(tree) -> List[Tuple[str, Any]]:
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(p), x) for p, x in flat]


def sharding_meta(tree) -> dict:
    """Serializable leaf-level sharding record for a pytree of jax.Arrays.

    {"format": 1, "mesh": {axis: size}, "device_count": N,
     "leaves": {keystr_path: [spec entries]}} — spec entries are None, an
    axis name, or a list of axis names (PartitionSpec tuples survive the
    JSON round trip as lists). Leaves without a NamedSharding (host
    numpy, scalars) are simply absent and restore replicated.
    """
    from jax.sharding import NamedSharding

    leaves: Dict[str, list] = {}
    mesh_shape: Optional[Dict[str, int]] = None
    device_count = 0
    for path, x in _leaf_paths(tree):
        s = getattr(x, "sharding", None)
        if not isinstance(s, NamedSharding):
            continue
        leaves[path] = [list(e) if isinstance(e, tuple) else e
                        for e in tuple(s.spec)]
        if mesh_shape is None:
            mesh_shape = {str(k): int(v) for k, v in s.mesh.shape.items()}
            device_count = int(s.mesh.devices.size)
    return {
        "format": SHARDING_META_FORMAT,
        "mesh": mesh_shape or {},
        "device_count": device_count,
        "leaves": leaves,
    }


def _resolve_spec(entries, shape, mesh) -> "Any":
    """A saved leaf spec, re-resolved against the CURRENT mesh.

    Per dimension: keep the recorded axis names only when every one
    exists on the new mesh AND their combined size still divides that
    dimension; otherwise that dimension replicates. A checkpoint from an
    8-device {'data': 4, 'model': 2} mesh restoring under a single
    device thus lands fully replicated — bit-identical values, honest
    placement — instead of crashing on a sharding the hardware no longer
    has.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    out = []
    dropped = 0
    ndim = len(shape)
    for dim in range(ndim):
        entry = entries[dim] if dim < len(entries) else None
        if entry is None:
            out.append(None)
            continue
        names = tuple(entry) if isinstance(entry, (list, tuple)) else (entry,)
        size = 1
        ok = True
        for n in names:
            if n not in mesh.shape:
                ok = False
                break
            size *= int(mesh.shape[n])
        if ok and size > 0 and shape[dim] % size == 0:
            out.append(names[0] if len(names) == 1 else names)
        else:
            out.append(None)
            dropped += 1
    while out and out[-1] is None:
        out.pop()  # canonical short form, like hand-written PartitionSpecs
    return NamedSharding(mesh, PartitionSpec(*out)), dropped


def abstract_template(tree, meta: Optional[dict], mesh):
    """`tree` as jax.ShapeDtypeStructs carrying the meta-resolved TARGET
    shardings for `mesh`.

    Handing this to the checkpoint reader (orbax StandardRestore accepts
    abstract arrays) makes a cross-mesh restore land every array ONCE,
    already placed — restoring onto a concrete replicated template and
    re-placing afterwards would pay double host-to-device traffic and
    peak memory on exactly the path a preemption/requeue window is
    racing. Leaves without recorded metadata restore replicated
    (`meta=None`: the whole tree, matching the legacy layout).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    leaves_meta = (meta or {}).get("leaves", {})
    replicated = NamedSharding(mesh, PartitionSpec())

    def make(path, x):
        shape = tuple(getattr(x, "shape", ()))
        entries = leaves_meta.get(path)
        sharding = (_resolve_spec(entries, shape, mesh)[0] if entries
                    else replicated)
        return jax.ShapeDtypeStruct(shape, x.dtype, sharding=sharding)

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [make(jax.tree_util.keystr(p), x) for p, x in flat])


def replace_on_mesh(tree, meta: Optional[dict], mesh):
    """Re-place every leaf of `tree` on `mesh` per the saved metadata.

    Returns (placed_tree, stats): leaves with a recorded spec go back to
    that layout (re-resolved for the current topology), everything else
    replicates. `meta=None` (a pre-metadata checkpoint) places the whole
    tree replicated — exactly what the trainer's legacy restore did.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    leaves_meta = (meta or {}).get("leaves", {})
    replicated = NamedSharding(mesh, PartitionSpec())
    stats = {"placed": 0, "resharded": 0, "dropped_dims": 0}

    def place(path, x):
        entries = leaves_meta.get(path)
        stats["placed"] += 1
        if entries:
            sharding, dropped = _resolve_spec(entries, getattr(x, "shape", ()),
                                              mesh)
            stats["dropped_dims"] += dropped
            if tuple(sharding.spec):
                stats["resharded"] += 1
            return jax.device_put(x, sharding)
        return jax.device_put(x, replicated)

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    placed = [place(jax.tree_util.keystr(p), x) for p, x in flat]
    return jax.tree_util.tree_unflatten(treedef, placed), stats
