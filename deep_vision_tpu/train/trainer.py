"""The single Trainer shared by every model in the zoo.

Replaces the reference's per-model copy-pasted loops (the 562-line
`run_epochs`/`train`/`validate` at ResNet/pytorch/train.py:310-538, the TF2
`Trainer` classes at YOLO/tensorflow/train.py:22-257 and
Hourglass/tensorflow/train.py:15-172, and Keras `model.fit` at
ResNet/tensorflow/train.py:283-297) with ONE jitted SPMD step over a device
mesh:

- `train_step`/`eval_step` are traced once (the pjit analog of the
  `@tf.function distributed_train_epoch` boundary at YOLO/tensorflow/train.py:126);
- the per-replica fan-out + `strategy.reduce(SUM)` pair
  (YOLO/tensorflow/train.py:131-151) disappears: batches are sharded over the
  mesh's 'data' axis and XLA inserts the gradient all-reduce;
- stateful host logic (plateau LR, best-val checkpointing,
  YOLO/tensorflow/train.py:56-68,243-247) stays outside jit and feeds the LR
  back in through `opt_state.hyperparams`.
"""
from __future__ import annotations

import time
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deep_vision_tpu.core.metrics import MetricLogger
from deep_vision_tpu.core.train_state import TrainState, create_train_state
from deep_vision_tpu.data.device_prefetch import DevicePrefetcher, PlacedBatch
from deep_vision_tpu.obs import perfwatch
from deep_vision_tpu.obs.alerts import AlertEngine, default_training_rules
from deep_vision_tpu.obs.goodput import GoodputMeter
from deep_vision_tpu.obs.stepclock import StepClock
from deep_vision_tpu.obs.trace import span
from deep_vision_tpu.parallel.mesh import (
    DATA_AXIS,
    assert_sharding_coverage,
    create_mesh,
    pad_batch_to,
    replicated,
    shard_batch,
    stacked_data_sharding,
)
from deep_vision_tpu.resilience.rendezvous import HostLostError, WorldResized

# one shared jitted sum: evaluate() calls it per masked multi-host batch,
# and a fresh jax.jit wrapper there would retrace every time
_global_sum = jax.jit(jnp.sum)


def _set_lr(opt_state, lr: float):
    """Set the injected learning_rate hyperparam to an absolute value."""
    hp = dict(opt_state.hyperparams)
    hp["learning_rate"] = jnp.asarray(lr, jnp.asarray(hp["learning_rate"]).dtype)
    return opt_state._replace(hyperparams=hp)


class Trainer:
    """One model + optimizer + loss over a mesh.

    loss_fn(outputs, batch) -> (loss, metrics_dict). The model is applied to
    `batch[input_key]` with `train=True/False` and a 'dropout' rng.

    Step-time knobs (README "Making it fast"): `multistep=K` runs K
    optimizer steps per device dispatch as one lax.scan superstep
    (per-microstep metrics/NaN-guard preserved, step counters advance by
    K; incompatible with checkify/EMA); `device_prefetch=N` places the
    next N batches on the mesh from a producer thread so H2D transfer
    overlaps compute (data/device_prefetch.py).

    Sharding (README "Sharding"): `sharding_rules` attaches a
    declarative pattern -> PartitionSpec table (parallel/shardmap.py).
    The full state tree places per the table (coverage-audited at
    startup against the family's floor, journaled as a typed
    `sharding_resolved` event) and every batch path — single step,
    multistep superstep stack, device prefetcher — shards the batch dim
    over the table's declared batch axes.
    """

    def __init__(
        self,
        model,
        tx: optax.GradientTransformation,
        loss_fn: Callable,
        sample_input,
        eval_loss_fn: Optional[Callable] = None,
        mesh=None,
        rng: Optional[jax.Array] = None,
        input_key: str = "image",
        checkpoint_manager=None,
        plateau=None,  # ReduceLROnPlateau or None
        plateau_metric: str = "top1",
        logger: Optional[MetricLogger] = None,
        eval_logger: Optional[MetricLogger] = None,
        profile_dir: Optional[str] = None,
        profile_steps: tuple = (10, 20),
        checkify_errors: bool = False,
        ema_decay: Optional[float] = None,
        journal=None,  # obs.RunJournal or None
        registry=None,  # obs.Registry; default process-wide registry
        telemetry_sample_every: int = 16,
        lr_schedule=None,  # the optax schedule behind tx, for current_lr
        health=None,  # obs.HealthMonitor or None
        autoprof=None,  # obs.AutoProfiler; built from profile_dir if None
        multistep: int = 1,  # optimizer steps per dispatch (lax.scan)
        device_prefetch: int = 0,  # device-resident batch buffer depth
        backend_supervisor=None,  # resilience.BackendSupervisor or None
        data_loader=None,  # snapshot-capable DataLoader (data/snapshot.py)
        host_supervisor=None,  # resilience.rendezvous.HostSupervisor or None
        executable_cache=None,  # core.excache.ExecutableCache or None
        sharding_rules=None,  # parallel.shardmap.ShardingRules or None
        telemetry=None,  # obs.TelemetryServer: live /healthz + /statusz
    ):
        self.mesh = mesh if mesh is not None else create_mesh()
        self.model = model  # single source of truth for summaries/export
        self.loss_fn = loss_fn
        self.eval_loss_fn = eval_loss_fn or loss_fn
        self.input_key = input_key
        self.ckpt = checkpoint_manager
        self.plateau = plateau
        self.plateau_metric = plateau_metric
        # telemetry: step-time breakdown + recompile/HBM gauges into the
        # registry, per-step events into the journal (obs/ subsystem)
        self.journal = journal
        self.health = health
        # skip_step policy: the jitted step itself discards a poisoned
        # update via a finiteness select — host-side "skip" would need the
        # pre-step state, which donate_argnums already gave back to XLA
        self._skip_nonfinite = bool(health is not None
                                    and health.skip_nonfinite)
        self.clock = StepClock(
            registry=registry, journal=journal, name="train",
            sample_every=telemetry_sample_every,
        )
        # goodput plane (obs/goodput.py): a journal tap attributing every
        # wall-clock second to a typed bucket, with periodic
        # goodput_interval events and a terminal goodput_summary (flushed
        # by a journal closer); alert engine (obs/alerts.py) evaluates
        # the knob-tuned training budgets over the same stream
        self.goodput = (GoodputMeter(journal=journal,
                                     registry=self.clock.registry)
                        if journal is not None else None)
        self.alerts = (AlertEngine(default_training_rules(),
                                   journal=journal,
                                   registry=self.clock.registry)
                       if journal is not None else None)
        if self.alerts is not None:
            journal.add_tap(self.alerts.observe)
        self._lr_schedule = lr_schedule
        self.logger = logger or MetricLogger(
            name="train", registry=self.clock.registry, journal=journal)
        # no journal on the val logger: evaluate() writes the typed 'eval'
        # event itself — a journal-wired val logger would duplicate every
        # summary as an 'epoch' event
        self.eval_logger = eval_logger or MetricLogger(
            name="val", print_every=0, registry=self.clock.registry)
        # profiler: the instrumentation the reference never had (SURVEY.md
        # §2.7 'tracing/profilers: NONE'). One AutoProfiler owns BOTH the
        # static [start, stop) window (profile_dir/profile_steps, viewed
        # with tensorboard-plugin-profile/xprof) and the anomaly-triggered
        # capture policy (obs/autoprof.py); it guards re-entry so a second
        # trigger while a trace is in flight can never double-start.
        self.profile_dir = profile_dir
        self.profile_steps = profile_steps
        if autoprof is None and profile_dir is not None:
            from deep_vision_tpu.obs.autoprof import AutoProfiler

            autoprof = AutoProfiler(profile_dir, window=profile_steps,
                                    journal=journal, registry=registry)
        self.prof = autoprof
        if self.prof is not None:
            # drain the device pipeline into the trace before stop_trace
            self.prof.fence = lambda: jax.block_until_ready(
                self.state.params)
        self._pguard = None  # PreemptionGuard, live only inside fit
        self._closed = False
        self.preempted = False  # latched by the SIGTERM escalation path
        # backend-loss recovery (resilience/elastic.py BackendSupervisor):
        # with one installed, fit() treats a classified backend failure
        # (dropped connection, dead-tunnel timeout) as an expected input —
        # rebuild the jitted step from host-side seeds + checkpoint, replay
        # from the last completed step. The host-side ingredients of that
        # rebuild are kept here; everything device-resident is derived.
        # input-pipeline checkpointing (data/snapshot.py): with a
        # snapshot-capable train DataLoader attached, every checkpoint's
        # host sidecar carries the loader's DataLoaderState and resume()
        # re-arms it — the batch stream continues byte-identically instead
        # of restarting from shard zero while the step counter says
        # otherwise. With --device-prefetch N, a MID-epoch snapshot counts
        # batches already handed to the prefetcher as consumed (up to N in
        # flight); epoch-boundary saves (the fit() cadence) are exact.
        self.data_loader = data_loader
        if data_loader is not None and hasattr(data_loader,
                                               "enable_snapshots"):
            # arm per-batch recording BEFORE the first epoch runs so
            # mid-epoch (preempt) saves capture an exact position
            data_loader.enable_snapshots()
        self.backend = backend_supervisor
        if self.backend is not None and self.backend.journal is None:
            self.backend.journal = journal
            if self.backend.policy.journal is None:
                self.backend.policy.journal = journal
        # host-membership supervision (resilience/rendezvous.py): with a
        # HostSupervisor installed, a peer host dying mid-run is an
        # EXPECTED input — the blocking device fetches below become
        # lease-checked bounded fences (a SIGKILLed peer leaves this
        # host's fetch wedged in C++ forever; only a side-channel lease
        # sweep can name it), and fit() turns the typed HostLostError
        # into host_lost/world_resized journal events + a re-rendezvous
        # at generation g+1, raised to the host agent as WorldResized.
        self.hosts = host_supervisor
        if self.hosts is not None:
            if self.hosts.journal is None:
                self.hosts.journal = journal
            if self.hosts.resume_step_fn is None and checkpoint_manager \
                    is not None:
                # what a post-resize resume will land on: the last step
                # the checkpoint layer holds (a directory read — safe
                # from the supervisor's watchdog thread)
                self.hosts.resume_step_fn = checkpoint_manager.latest_step
            if data_loader is not None:
                # an armed snapshot loader pins the OLD host-shard slice
                # in its fingerprint: the restore refuses the resize
                # (SnapshotMismatch) instead of journaling data_reshard.
                # A loader built WITHOUT a host_shard gets this world's
                # slice stamped here — otherwise the fingerprints match
                # across a resize and the refusal can never fire.
                self.hosts.reshardable = False
                view = getattr(self.hosts.rdzv, "view", None)
                if view is not None and \
                        getattr(data_loader, "host_shard", 0) is None:
                    try:
                        data_loader.pin_host_shard(view.shard())
                    except Exception:
                        pass  # already fingerprinted: identity is fixed
        self._tx = tx
        self._sample_input = sample_input
        self._init_rng = rng

        # declarative sharding (parallel/shardmap.py): with a rules table
        # attached, the FULL state tree (params, optimizer moments, BN
        # stats) resolves against the table at startup —
        # `assert_sharding_coverage` audits the result against the
        # family's declared floor BEFORE any buffer is placed, and the
        # rule -> leaf resolution lands in the journal as a typed
        # `sharding_resolved` event. Batches (single, multistep stacks,
        # device-prefetched) follow the table's declared batch axes.
        # Without a table, the state replicates (plain data parallel) —
        # the pre-table behavior, unchanged.
        self.sharding_rules = sharding_rules
        self._state_shardings = None
        self._batch_axes = (DATA_AXIS,)
        state = create_train_state(model, tx, sample_input, rng)
        if sharding_rules is not None:
            shardings, report = sharding_rules.resolve(state, self.mesh)
            # startup hard check FIRST: a stale table must fail before
            # any device placement, naming the leaves it lost
            assert_sharding_coverage(
                state, shardings, self.mesh,
                min_sharded=sharding_rules.floor_for(self.mesh))
            self._state_shardings = shardings
            self._batch_axes = tuple(sharding_rules.batch_axes)
            if journal is not None:
                from deep_vision_tpu.parallel.shardmap import (
                    resolution_event_fields,
                )

                journal.write("sharding_resolved",
                              **resolution_event_fields(report))
        # device boundary: state lives on the mesh from here on —
        # table-sharded when rules are attached, replicated otherwise
        self.state = self._place_state(state)
        # EMA evaluation weights (train/ema.py): updated after every step,
        # used by eval_step. Checkpointed in a SIBLING manager under
        # <ckpt_dir>/ema so the main checkpoint's on-disk structure is
        # identical with or without the flag — runs stay resumable either
        # way (the shadow just re-seeds from the restored params when no
        # EMA history exists).
        self.ema = None
        self._ema_ckpt = None
        if ema_decay is not None:
            from deep_vision_tpu.train.ema import EmaParams

            self.ema = EmaParams(self.state.params, decay=ema_decay)
            if self.ckpt is not None:
                import os as _os

                self._ema_ckpt = type(self.ckpt)(
                    _os.path.join(self.ckpt.directory, "ema"),
                    journal=journal,
                )
        # base LR for plateau scaling: scale is applied to this absolute value,
        # never compounded onto an already-scaled current LR
        try:
            self._base_lr = float(state.opt_state.hyperparams["learning_rate"])
        except (AttributeError, KeyError, TypeError):
            self._base_lr = None
        if self.plateau is not None:
            # a scheduled LR (inject_hyperparams re-evaluates it every step)
            # would silently overwrite the plateau's absolute writes — refuse
            # the combination here too, for trainers built without the config
            # registry's validation
            hp_states = getattr(state.opt_state, "hyperparams_states", None)
            if hp_states and "learning_rate" in hp_states:
                raise ValueError(
                    "plateau scaling requires a constant base learning rate: "
                    "the optimizer's learning_rate is a schedule, which is "
                    "re-evaluated inside the jitted step and would override "
                    "plateau writes — use one LR policy"
                )
            if self._base_lr is None:
                raise ValueError(
                    "plateau scaling requires opt_state.hyperparams"
                    "['learning_rate'] (build the optimizer via "
                    "train.optimizers.build_optimizer)"
                )

        # Sanitizer mode (SURVEY §2.7: the functional-runtime analog of race
        # detectors/ASAN the reference never had): jax.experimental.checkify
        # instruments every op in the jitted step with NaN / out-of-bounds /
        # div-by-zero checks; train_step then raises a located error instead
        # of silently propagating garbage. ~2x step cost — a debugging mode,
        # vs --debug-nans which re-runs ops eagerly only after a NaN fetch.
        self._checkify = checkify_errors
        # -- scan-multistep: K optimizer steps per dispatch ----------------
        # One lax.scan over a (K, B, ...) stacked batch amortizes the
        # per-dispatch host turnaround K-fold (bench.py measured the
        # mechanism; this is the first-class Trainer mode). The scan body
        # IS `_train_step_impl`, so per-microstep RNG (fold_in on the
        # advancing state.step), metrics, and the skip_step NaN-guard all
        # apply per microstep; the epoch tail (fewer than K batches left)
        # rides the single-step executable so neither ever recompiles.
        self.multistep = max(1, int(multistep))
        if self.multistep > 1:
            if checkify_errors:
                raise ValueError(
                    "multistep > 1 is incompatible with checkify: the "
                    "sanitizer needs the un-scanned per-step boundary to "
                    "locate the failing op — debug at multistep=1"
                )
            if ema_decay is not None:
                raise ValueError(
                    "multistep > 1 is incompatible with ema_decay: the EMA "
                    "shadow updates once per HOST dispatch, so K scanned "
                    "microsteps would decay it once instead of K times and "
                    "silently change eval — run EMA at multistep=1"
                )
        # persistent executable cache (core/excache.py): step executables
        # AOT-round-trip through the on-disk store, so a restarted
        # process, the backend-loss rebuild-replay, and a re-exec'd host
        # all load their supersteps instead of recompiling them — the
        # recovery-time-objective stops paying the XLA compiler.
        # Checkify is exempt (its jit carries the error plumbing and is
        # a debugging mode, not a cold path worth caching).
        self.excache = executable_cache
        self._build_jitted_steps()
        # device prefetch: pad/shard/device_put the NEXT batch(es) on a
        # producer thread so H2D transfer overlaps the current step's
        # compute (data/device_prefetch.py); depth 2 = double buffering
        self.device_prefetch = max(0, int(device_prefetch))
        self._prefetcher = None
        if self.device_prefetch > 0:
            self._prefetcher = DevicePrefetcher(
                place_one=self._place_one,
                depth=self.device_prefetch,
                group=self.multistep,
                place_group=(self._place_group
                             if self.multistep > 1 else None),
                registry=self.clock.registry,
            )
        # live telemetry plane (obs/telemetry.py): register host-side
        # status + readiness sources. The scraper thread must never touch
        # the device, so /statusz reads the plain-Python step mirror kept
        # by the *_and_log paths, not `int(self.state.step)` (a device
        # fetch that could fence against an in-flight dispatch).
        self._live_step: Optional[int] = None
        self._live_epoch: Optional[int] = None
        self._live_eps: Optional[float] = None
        self.telemetry = telemetry
        if telemetry is not None:
            telemetry.add_status("train", self._telemetry_status)
            # the perf plane's live face (obs/perfwatch): rolling
            # step-time quantiles off this trainer's StepClock histogram
            # (host-side bucket math, no device fetch), recompile count,
            # last perf-gate verdict / trace digest
            perfwatch.set_quantile_source(self._step_time_quantiles)
            telemetry.add_status("perf", perfwatch.telemetry_status)
            # the goodput plane's live face: bucket fractions + the
            # goodput_frac scalar (obs_poll's "gp NN%" column), and the
            # alert engine behind /alertz + the "alerts" health source
            if self.goodput is not None:
                telemetry.add_status("goodput",
                                     self.goodput.telemetry_status)
            if self.alerts is not None:
                telemetry.set_alerts(self.alerts)
            if self.health is not None:
                telemetry.add_health("train", self.health.healthz)
            if self.hosts is not None:
                telemetry.add_health("rendezvous", self._rendezvous_health)

    def _telemetry_status(self) -> dict:
        """Telemetry status source for /statusz: the last step/epoch and
        throughput the train loop published, plus the world generation.
        Host-side reads only — see the registration comment above."""
        out = {
            "step": self._live_step,
            "epoch": self._live_epoch,
            "examples_per_sec": (round(self._live_eps, 1)
                                 if self._live_eps else self._live_eps),
            "steps_seen": int(self.clock.steps_seen),
            "multistep": int(self.multistep),
        }
        if self.hosts is not None:
            out["generation"] = getattr(self.hosts.rdzv, "generation", None)
        return out

    def _step_time_quantiles(self) -> dict:
        """Rolling step-time p50/p95 for the /statusz perf source —
        bucket-resolution estimates from the StepClock histogram, so the
        scraper thread reads plain host numbers (None until steps land)."""
        h = self.clock._h_step
        if not h.count:
            return {}
        import math

        def finite(v):
            return round(v, 3) if math.isfinite(v) else None

        return {"step_time_ms_p50": finite(h.quantile(0.5)),
                "step_time_ms_p95": finite(h.quantile(0.95))}

    def _rendezvous_health(self):
        """Telemetry health source: this host's OWN lease freshness — a
        host whose heartbeat thread died is about to be declared lost by
        its peers, and /healthz should say so first."""
        rdzv = self.hosts.rdzv
        gap = rdzv.lease_gap(rdzv.host)
        ok = gap is not None and gap <= rdzv.lease_s
        return ok, {
            "host": rdzv.host,
            "generation": rdzv.generation,
            "lease_gap_s": round(gap, 3) if gap is not None else None,
            "lease_s": rdzv.lease_s,
        }

    def _place_state(self, state):
        """Place a host/abstract state onto the mesh: per the resolved
        sharding table when one is attached, fully replicated otherwise.
        Shared by init, the backend-loss rebuild, and the legacy-restore
        path of resume() so a recovered run lands on the SAME layout the
        original compiled against (a layout flip would recompile every
        step executable)."""
        if self._state_shardings is not None:
            return jax.device_put(state, self._state_shardings)
        return jax.device_put(state, replicated(self.mesh))

    # -- jitted steps ------------------------------------------------------
    def _build_jitted_steps(self) -> None:
        """(Re)create the jitted step callables. Called once at init and
        again by the backend-loss recovery path: after a client rebuild
        the old executables reference dead buffers, so the wrappers are
        remade from the pure impl methods (the impls close over nothing
        device-resident — everything flows through state/batch args)."""
        # With a sharding table attached, PIN the step executables' state
        # input AND output to the resolved layout: left unconstrained,
        # XLA may pick slightly different output shardings for the
        # single-step and superstep executables (e.g. a trimmed spec),
        # and alternating them — every epoch tail does — would recompile
        # on the layout flip. Pinning keeps the state in the audited
        # table layout for the whole run; batches stay unconstrained
        # (they arrive pre-placed on the declared batch axes).
        state_pin = {}
        if self._state_shardings is not None:
            state_pin = dict(in_shardings=(self._state_shardings, None),
                             out_shardings=(self._state_shardings, None))
        self._state_pin = state_pin  # reused by profile_step's AOT lowering
        if self._checkify:
            from jax.experimental import checkify

            checked = checkify.checkify(
                self._train_step_impl, errors=checkify.all_checks
            )
            # jaxlint: disable=DV003 -- checkify debug mode: keep the pre-step state un-donated so a thrown error can be inspected against the exact inputs that produced it
            self._train_step_err = jax.jit(checked)
            self._train_step = None
        else:
            self._train_step = jax.jit(
                self._train_step_impl, donate_argnums=0, **state_pin
            )
            self._train_step_err = None
        self._eval_step = jax.jit(self._eval_step_impl)
        self._train_multi = None
        if self.multistep > 1:
            self._train_multi = jax.jit(
                self._multistep_impl, donate_argnums=0, **state_pin
            )
        # AOT executables loaded/stored through self.excache, keyed by
        # (step kind -> batch signature). Reset with the jit wrappers:
        # after a backend rebuild the old executables pin dead buffers,
        # and the next dispatch re-lowers and re-loads from the
        # persistent cache (the disk read IS the recovery fast path).
        # The cache-path jits DO NOT DONATE: jax's executable serialize
        # round trip drops the donated-buffer bookkeeping, so a
        # deserialized donating step aliases the old state's buffers
        # while Python still thinks it owns them — measured as a
        # segfault on the second step (use-after-free). The trade is
        # transient 2x state memory during a cached step; flip the
        # cache off for models where that peak matters more than
        # cold-start.
        self._train_step_cache = self._train_multi_cache = None
        if self.excache is not None and not self._checkify:
            # jaxlint: disable=DV003 -- cache-path step: donation must not ride the executable serialize round trip (deserialized donating executables alias freed buffers)
            self._train_step_cache = jax.jit(self._train_step_impl,
                                             **state_pin)
            if self.multistep > 1:
                # jaxlint: disable=DV003 -- cache-path superstep: same serialize-round-trip donation hazard
                self._train_multi_cache = jax.jit(self._multistep_impl,
                                                  **state_pin)
        self._aot_steps: dict = {}

    def profile_step(self, batch, kind: str = "train"):
        """Journal the XLA cost + collective inventory of the step
        executable for `batch`'s signature (typed perf_profile /
        perf_collective events; see obs/perfwatch).

        The excache path profiles automatically at its AOT build; this
        is the explicit probe for plain-jit trainers (smokes, scaling
        benches). It lowers the NON-donating variant of the step impl —
        same HLO modulo buffer aliasing — which costs one extra backend
        compile the first time per signature (jax's AOT cache absorbs
        repeats). `kind="multi"` profiles the superstep: `batch` must
        then be the (K, B, ...) stacked pytree the superstep consumes.
        Returns the profile dict, or None when extraction failed.
        """
        if kind == "multi":
            if self.multistep <= 1:
                raise ValueError("profile_step(kind='multi') on a "
                                 "multistep=1 trainer")
            impl = self._multistep_impl
        elif kind == "train":
            impl = self._train_step_impl
        else:
            raise ValueError(f"profile_step kind {kind!r} not in "
                             "('train', 'multi')")
        # jaxlint: disable=DV003 -- profiling probe: non-donating on purpose (the compiled artifact is inspected, not dispatched on the training hot path)
        jitted = jax.jit(impl, **self._state_pin)
        compiled = jitted.lower(self.state, batch).compile()
        return perfwatch.profile_compiled(f"trainer/{kind}", compiled,
                                          journal=self.journal,
                                          registry=self.clock.registry)

    @staticmethod
    def _batch_sig(batch) -> tuple:
        """Cheap shape/dtype signature of a (possibly nested) batch —
        the AOT lookup key. Training batches are padded to a fixed
        canonical shape, so in steady state this is one dict walk."""
        return tuple(
            (k, tuple(v.shape), str(getattr(v, "dtype", type(v).__name__)))
            for k, v in sorted(batch.items()))

    def _cached_step(self, kind: str, jitted, cache_jitted, batch):
        """The executable for (kind, batch signature): loaded from the
        persistent cache on a cold start / post-rebuild, compiled-and-
        stored otherwise. Falls back to the plain (donating) jit wrapper
        when no cache is attached — ``cache_jitted`` is the
        donation-free variant of the same impl, the only shape safe to
        serialize (see _build_jitted_steps)."""
        if cache_jitted is None:
            return jitted
        by_sig = self._aot_steps.setdefault(kind, {})
        sig = self._batch_sig(batch)
        compiled = by_sig.get(sig)
        if compiled is None:
            lowered = cache_jitted.lower(self.state, batch)
            compiled, _source = self.excache.get_or_compile(
                lowered, name=f"trainer/{kind}")
            by_sig[sig] = compiled
            # perf attribution (obs/perfwatch): the AOT/cache path is the
            # one trainer site that holds a compiled executable, so its
            # XLA cost + collective inventory journal here — once per
            # (kind, batch signature), at the build it already paid for
            perfwatch.profile_compiled(f"trainer/{kind}", compiled,
                                       journal=self.journal,
                                       registry=self.clock.registry)
        return compiled

    def _train_step_impl(self, state: TrainState, batch):
        step_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            variables = {"params": params}
            mutable = False
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
                mutable = ["batch_stats"]
            out = state.apply_fn(
                variables,
                batch[self.input_key],
                train=True,
                rngs={"dropout": step_rng},
                mutable=mutable,
            )
            outputs, new_model_state = out if mutable else (out, {})
            loss, metrics = self.loss_fn(outputs, batch)
            return loss, (metrics, new_model_state.get("batch_stats", {}))

        grads, (metrics, new_bs) = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads)
        if state.batch_stats:
            new_state = new_state.replace(batch_stats=new_bs)
        metrics["grad_norm"] = optax.global_norm(grads)
        if self._skip_nonfinite:
            # health skip_step policy: one poisoned batch must not destroy
            # the weights — keep the whole pre-step state (params, opt
            # moments, step counter, batch_stats) when loss or grads went
            # non-finite. A select inside jit, so no extra host sync and
            # no reliance on the donated input buffers.
            ok = jnp.isfinite(metrics["grad_norm"])
            if "loss" in metrics:
                ok = ok & jnp.isfinite(metrics["loss"])
            new_state = jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), new_state, state
            )
            metrics["skipped"] = 1.0 - ok.astype(jnp.float32)
        return new_state, metrics

    def _eval_step_impl(self, state: TrainState, batch):
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        outputs = state.apply_fn(variables, batch[self.input_key], train=False)
        _, metrics = self.eval_loss_fn(outputs, batch)
        return metrics

    def _multistep_impl(self, state: TrainState, batches):
        """K optimizer steps over a (K, B, ...) stacked batch, one dispatch.

        The scan body is the exact single-step impl: state.step advances
        inside apply_gradients, so per-microstep RNG derivation
        (fold_in(rng, step)) and the skip_step finiteness select match K
        separate dispatches bit for bit. Returns (state, metrics) with
        every metric leaf stacked (K,) — the per-microstep record the host
        loop un-stacks for loggers/health."""
        return jax.lax.scan(
            lambda s, b: self._train_step_impl(s, b), state, batches
        )

    # -- host API ----------------------------------------------------------
    def _pad_and_mask(self, batch):
        """Pad the final partial batch up to the data-axis multiple and attach
        a '_mask' row-validity array consumed by mask-aware losses/metrics
        (TPU static shapes; the reference just let torch/TF handle ragged
        last batches, ResNet/pytorch/train.py:431-485)."""
        if isinstance(batch[self.input_key], jax.Array) and len(
                batch[self.input_key].sharding.device_set) > 1:
            # multi-host: the batch is already a globally-sharded array
            # (form_global_array) — this host holds only its shards, so
            # padding must happen BEFORE assembly; callers feed full batches
            return dict(batch)
        n_data = int(np.prod([self.mesh.shape[a]
                              for a in self._batch_axes]))
        batch, n_valid = pad_batch_to(dict(batch), n_data)
        n_total = np.asarray(batch[self.input_key]).shape[0]
        if "_mask" not in batch:
            mask = np.zeros((n_total,), np.float32)
            mask[:n_valid] = 1.0
            batch["_mask"] = mask
        return batch

    # -- batch placement (device prefetch + multistep stacking) ------------
    @staticmethod
    def _pad_rows_to(batch: dict, n: int) -> dict:
        """Zero-pad every leaf's leading dim to `n` rows; the '_mask'
        zeros added with them keep the rows out of every masked mean."""
        out = {}
        for k, v in batch.items():
            v = np.asarray(v)
            if v.shape[0] < n:
                pad = [(0, n - v.shape[0])] + [(0, 0)] * (v.ndim - 1)
                v = np.pad(v, pad)
            out[k] = v
        return out

    def _place_one(self, batch) -> PlacedBatch:
        """Host batch -> padded/masked/sharded on the mesh (the work
        train_step otherwise does on the critical path)."""
        n = int(np.shape(batch[self.input_key])[0])
        placed = shard_batch(self.mesh, self._pad_and_mask(batch),
                             axes=self._batch_axes)
        return PlacedBatch(placed, n, 1)

    def _place_group(self, batches) -> PlacedBatch:
        """K host batches -> one (K, B, ...) stacked superstep batch."""
        n = sum(int(np.shape(b[self.input_key])[0]) for b in batches)
        return PlacedBatch(self._stack_batches(batches), n, len(batches))

    def _stack_batches(self, batches):
        """Pad/mask each batch, stack leaves along a new scan axis, and
        place with the (replicated-K, sharded-B) layout.

        A partial final batch inside the group (drop_remainder=False) is
        additionally zero-padded up to the group's common batch size with
        its '_mask' extended accordingly — mask-aware losses/metrics ignore
        the extra rows exactly as they ignore the data-axis padding at
        multistep=1, and np.stack sees uniform shapes."""
        padded = [self._pad_and_mask(b) for b in batches]
        sizes = [np.asarray(p[self.input_key]).shape[0] for p in padded]
        n_max = max(sizes)
        if min(sizes) != n_max:
            padded = [p if n == n_max else self._pad_rows_to(p, n_max)
                      for p, n in zip(padded, sizes)]

        def _stack(*xs):
            return np.stack([np.asarray(x) for x in xs])

        stacked = jax.tree_util.tree_map(_stack, *padded)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, stacked_data_sharding(self.mesh, x.ndim,
                                         axes=self._batch_axes)),
            stacked,
        )

    @property
    def _profiling(self) -> bool:
        """True while a profiler capture is in flight (static or auto)."""
        return self.prof is not None and self.prof.capturing

    def _profiler_hook(self):
        if self.prof is None:
            return
        # int() blocks on the in-flight state — pay it ONLY while a
        # pending static window needs the true optimizer step to anchor
        # (e.g. after a resume). An --autoprof-only run would otherwise
        # drain the device pipeline every step; its internal counter is
        # recalibrated by observe_step's committed opt_step instead.
        self.prof.on_step_start(int(self.state.step)
                                if self.prof.needs_step_index else None)

    def _stop_trace(self, step: Optional[int] = None) -> None:
        """Close an in-flight profiler capture (idempotent); journaled as
        a `profile_capture` event with outcome=closed_early."""
        if self.prof is not None:
            self.prof.interrupt()

    def train_step(self, batch) -> dict:
        self._profiler_hook()
        if isinstance(batch, PlacedBatch):
            batch = batch.data  # device prefetcher already padded + placed
        else:
            batch = shard_batch(self.mesh, self._pad_and_mask(batch),
                                axes=self._batch_axes)
        if self._checkify:
            err, (new_state, metrics) = self._train_step_err(self.state, batch)
            err.throw()  # located NaN/OOB/div0 inside the step, if any
            self.state = new_state
        else:
            step_fn = self._cached_step("train_step", self._train_step,
                                        self._train_step_cache, batch)
            self.state, metrics = step_fn(self.state, batch)
        if self.ema is not None:
            self.ema.update(self.state.params)
        return metrics

    def train_superstep(self, batches) -> list:
        """K optimizer steps in ONE dispatch (requires multistep > 1).

        `batches`: a list of K host batch dicts, or a PlacedBatch the
        device prefetcher stacked ahead of time. Returns K per-microstep
        metric dicts (device scalars — fetch once, not per key)."""
        if self._train_multi is None:
            raise ValueError("train_superstep needs Trainer(multistep=K>1)")
        self._profiler_hook()
        if isinstance(batches, PlacedBatch):
            k, stacked = batches.group, batches.data
        else:
            k, stacked = len(batches), self._stack_batches(batches)
        if k != self.multistep:
            raise ValueError(
                f"superstep got {k} batches, configured multistep is "
                f"{self.multistep} (the epoch tail must use train_step)"
            )
        multi_fn = self._cached_step("superstep", self._train_multi,
                                     self._train_multi_cache, stacked)
        self.state, metrics = multi_fn(self.state, stacked)
        return [jax.tree_util.tree_map(lambda v, i=i: v[i], metrics)
                for i in range(k)]

    def eval_step(self, batch) -> dict:
        batch = shard_batch(self.mesh, self._pad_and_mask(batch),
                            axes=self._batch_axes)
        state = self.state
        if self.ema is not None:
            state = state.replace(params=self.ema.params)
        return self._eval_step(state, batch)

    def lr_at(self, step: int) -> float:
        """LR for a step the caller already fetched (the hot loop passes its
        opt_step so the fallback costs no extra device round-trip)."""
        try:
            return float(self.state.opt_state.hyperparams["learning_rate"])
        except (AttributeError, KeyError, TypeError):
            pass
        # optimizer built without inject_hyperparams: evaluate the schedule
        # at the given step instead of logging NaN forever
        if self._lr_schedule is not None:
            if callable(self._lr_schedule):
                return float(self._lr_schedule(step))
            return float(self._lr_schedule)
        return float("nan")

    @property
    def current_lr(self) -> float:
        return self.lr_at(int(self.state.step))

    def close(self) -> None:
        """Release run-scoped resources: stop an in-flight profiler trace
        (the start_trace leak when training ends before profile_steps[1]),
        flush TensorBoard writers, and drain async checkpoint saves.
        Idempotent; called from train_cli.py and, via journal.add_closer,
        from the journal's atexit hook on abnormal exits."""
        if self._closed:
            return
        self._closed = True
        if self.health is not None:
            self.health.stop()  # disarm the watchdog before teardown
        if self.prof is not None:
            # terminal: stops an in-flight (auto-)capture without leaking
            # the process-wide profiler latch
            self.prof.close()
        for lg in (self.logger, self.eval_logger):
            tb = getattr(lg, "tb", None)
            if tb is not None:
                try:
                    tb.flush()
                except Exception:
                    pass
        if self.ckpt is not None:
            self.ckpt.wait()
        if self._ema_ckpt is not None:
            self._ema_ckpt.wait()
        if self.goodput is not None:
            # terminal goodput_summary (idempotent — the journal closer
            # covers runs that never reach Trainer.close)
            self.goodput.close()

    def evaluate(self, eval_data: Iterable, epoch: int = 0) -> dict:
        with span("eval", epoch=epoch):
            return self._evaluate(eval_data, epoch)

    def _evaluate(self, eval_data: Iterable, epoch: int = 0) -> dict:
        self.eval_logger.start_epoch()
        step = 0
        for batch in eval_data:
            # eval batches are forward progress too: a long val pass must
            # not trip the hang watchdog
            if self.health is not None:
                self.health.beat()
            # consensus (not the local flag): in multi-host runs every host
            # must leave the eval collectives at the same batch boundary.
            # Keyed on the eval-batch index, which is host-identical because
            # the SPMD eval_step itself already requires every host to make
            # the same sequence of calls.
            if self._pguard is not None and self._pguard.agreed(step=step):
                break  # caller re-checks with force=True and checkpoints
            # metrics are masked MEANS over valid rows; weight the epoch
            # aggregate by VALID rows. Multi-host callers pre-pad the final
            # global batch (see _pad_and_mask) and ship '_mask' with it —
            # counting padded rows here would skew every epoch average the
            # padding's share.
            if "_mask" in batch:
                m = batch["_mask"]
                if isinstance(m, jax.Array) and not m.is_fully_addressable:
                    # multi-host global array: shards live on other hosts;
                    # reduce under SPMD, fetch the replicated scalar
                    n = int(_global_sum(m))
                else:
                    n = int(np.sum(np.asarray(m)))
            else:
                n = np.shape(batch[self.input_key])[0]
            metrics = self.eval_step(batch)
            self.eval_logger.log_step(step, metrics, batch_size=n, epoch=epoch)
            step += 1
        summary = self.eval_logger.end_epoch(epoch)
        if self.journal is not None:
            self.journal.write("eval", epoch=epoch, summary=summary)
        return summary

    def fit(
        self,
        train_data_fn: Callable[[], Iterable],
        eval_data_fn: Optional[Callable[[], Iterable]] = None,
        epochs: int = 1,
        start_epoch: int = 0,
        eval_first: bool = False,  # epoch-0 sanity pass (ResNet/pytorch/train.py:390)
        save_every: int = 1,
        handle_preemption: bool = True,
        preemption_poll_every: int = 10,
    ):
        """Epoch driver. With `handle_preemption` (default), SIGTERM — what a
        TPU VM gets ~30s before a maintenance event or spot reclaim — is
        caught, the current step finishes, a checkpoint + host sidecar are
        written synchronously, and fit returns early; `resume()` continues
        the run. The elastic-recovery story the reference lacked entirely
        (SURVEY §2.7: 'recovery = manual resume from checkpoint'). Installed
        only on the main thread (signal module requirement)."""
        from deep_vision_tpu.parallel.multihost import PreemptionGuard

        self._pguard = (
            PreemptionGuard(poll_every=preemption_poll_every)
            if handle_preemption else None
        )
        self._closed = False  # fit may be re-entered after a close()
        self.preempted = False  # re-armed per fit: the latch reports THIS run
        self._resizing = False  # latched by _handle_host_loss: gates the
        # finally-block device waits below
        if self.health is not None:
            self.health.start_watchdog()  # no-op without a timeout
        import contextlib

        ctx = self._pguard if self._pguard is not None else contextlib.nullcontext()
        try:
            with ctx:
                if eval_first and eval_data_fn is not None:
                    self.evaluate(eval_data_fn(), epoch=start_epoch)
                epoch = start_epoch
                attempt = 0  # backend rebuild-replay attempts so far
                while epoch < epochs:
                    try:
                        with span("train/epoch", epoch=epoch):
                            status, summary = self._run_epoch(train_data_fn,
                                                              epoch)
                        if status == "preempted":
                            return self.state
                        if self._post_epoch(summary, eval_data_fn, epoch,
                                            save_every) == "preempted":
                            return self.state
                        if attempt and self.backend is not None:
                            # a full epoch on the rebuilt backend = real
                            # progress: the outage is over
                            self.backend.on_recovered(
                                attempt, step=int(self.state.step))
                            attempt = 0
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except HostLostError as e:
                        # a peer HOST died (lease expired at a bounded
                        # fence / rendezvous barrier): journal, re-
                        # rendezvous at g+1, hand the new world to the
                        # host agent — never the backend path, which
                        # would rebuild-and-replay into the same dead
                        # collective
                        self._handle_host_loss(e)
                    except Exception as e:
                        # a SIGKILLed peer often surfaces as a transport
                        # error (gloo/ICI 'connection closed') MILLI-
                        # seconds before its lease expires: give the
                        # lease ledger one period to name a corpse
                        # before treating this as a backend/program
                        # failure
                        if self.hosts is not None:
                            lost = self.hosts.confirm_loss(e)
                            if lost is not None:
                                self._handle_host_loss(lost)
                        # backend-loss detection + rebuild-replay (the
                        # choreography bench.py prototyped, lifted here):
                        # only failures the supervisor classifies as a
                        # lost backend are retried — program bugs, NaN
                        # aborts, and version skew propagate unchanged
                        attempt += 1
                        if self.backend is None or not self.backend.on_failure(
                                attempt, e, step=None, context="train/fit"):
                            raise
                        self.backend.recover(attempt)
                        epoch = self._rebuild_after_backend_loss(start_epoch)
                        continue
                    epoch += 1
        finally:
            self._pguard = None
            self._stop_trace()  # stop gate never reached (short run)
            # NOT while a world resize is propagating: an async save's
            # device fetch may be wedged in the very collective that
            # just died, and wait() has no deadline — the re-exec'd
            # process re-reads whatever the last COMPLETED save left
            if not self._resizing:
                if self.ckpt is not None:
                    self.ckpt.wait()
                if self._ema_ckpt is not None:
                    self._ema_ckpt.wait()
        return self.state

    def _save_checkpoint(self, epoch: int, val_summary=None) -> bool:
        t0 = time.perf_counter()
        with span("checkpoint/save", epoch=epoch,
                  step=int(self.state.step)):
            host_state = {
                "epoch": epoch,
                "train_logger": self.logger.state_dict(),
                "val_logger": self.eval_logger.state_dict(),
            }
            if self.plateau is not None:
                host_state["plateau"] = self.plateau.state_dict()
            if self.data_loader is not None:
                # the input pipeline is a checkpoint citizen: its state
                # rides the same crc32c sidecar as the plateau/loggers
                host_state["data_state"] = self.data_loader.state_dict()
            saved = self.ckpt.save(
                int(self.state.step), self.state, host_state=host_state,
                metrics=val_summary,
            )
            if self._ema_ckpt is not None:
                self._ema_ckpt.save_tree(
                    int(self.state.step), dict(self.ema.params),
                    host_state=self.ema.state_dict(),
                )
        if self.journal is not None:
            # save_ms is the goodput plane's checkpoint feed: offline
            # attribution (obs/goodput.py) carves exactly this much of
            # the gap before this row into the checkpoint bucket
            self.journal.write("checkpoint", step=int(self.state.step),
                               epoch=epoch, saved=bool(saved),
                               save_ms=round(
                                   (time.perf_counter() - t0) * 1e3, 3))
        return bool(saved)

    def _rebuild_after_backend_loss(self, fallback_epoch: int) -> int:
        """Rebuild the device-side world from host-side seeds + checkpoint
        after a lost backend; returns the epoch to replay from.

        Everything device-resident is reconstructed: the compiled-
        executable caches are dropped (they pin the dead client), the
        jitted wrappers are remade, a fresh TrainState is re-initialized
        from the SAME host seeds (bit-equivalent to the original init),
        and — when a checkpoint manager holds a valid step — `resume()`
        replays from the last completed checkpoint (riding the quarantine
        fallback chain and the cross-mesh re-placement). Without a
        checkpoint the honest floor is a from-scratch replay, journaled
        as such."""
        try:
            jax.clear_caches()
        except Exception:
            pass
        state = create_train_state(self.model, self._tx, self._sample_input,
                                   self._init_rng)
        self.state = self._place_state(state)
        if self.ema is not None:
            from deep_vision_tpu.train.ema import EmaParams

            self.ema = EmaParams(self.state.params, decay=self.ema.decay,
                                 warmup=self.ema.warmup)
        self._build_jitted_steps()
        if self.ckpt is not None and self.ckpt.latest_step() is not None:
            return self.resume()  # journals 'resumed'; restores EMA/loggers
        if self.journal is not None:
            self.journal.write(
                "note", note="backend rebuilt without a checkpoint: "
                             "replaying from scratch",
                epoch=int(fallback_epoch))
        return fallback_epoch

    def _host_fetch(self, fn):
        """Blocking device fetch, lease-checked when a HostSupervisor is
        installed: a peer SIGKILLed mid-collective wedges this host's
        fetch in C++ with no exception — the bounded fence polls the
        rendezvous lease ledger between waits and raises the typed
        HostLostError the fit loop supervises. Without a supervisor,
        the plain fetch (single-host runs pay nothing)."""
        if self.hosts is None:
            return fn()
        return self.hosts.bounded_fetch(fn)

    def _handle_host_loss(self, err: HostLostError):
        """The elastic ladder for host churn: typed `host_lost` event →
        re-rendezvous at generation g+1 with the survivors → typed
        `world_resized{from,to,generation,resume_step}` → hand the new
        world to the host agent as WorldResized.

        Why raise instead of rebuilding in place: this rank may be (and
        after a mid-collective SIGKILL, IS) wedged inside a dead gloo/
        ICI op; `jax.distributed` cannot re-initialize in-process and
        its coordination client terminates the process when it notices
        the corpse (rendezvous.py module docstring). The host agent
        re-execs into the new generation — same process slot, same
        append-mode journal — and `resume()` continues from
        `resume_step` via the PR 10 cross-mesh restore. No new
        checkpoint is attempted here: a save would fetch device state
        through the very collective that just died.
        """
        if self.hosts is None:
            raise err
        self._resizing = True  # fit's finally must not block on device
        # waits that may ride the dead collective
        self._stop_trace()
        # the exactly-once funnel: journals host_lost + world_resized
        # (+ data_reshard when the input re-derives), resizes at g+1. If
        # the supervisor's watchdog won the race, this parks until its
        # reexec replaces the process.
        view = self.hosts.handle_loss(err)
        # the step handle_loss journaled, not a fresh latest_step() read:
        # the postmortem timeline and the actual resume must agree
        raise WorldResized(view, resume_step=self.hosts.last_resume_step)

    def _preempt_save(self, epoch: int) -> None:
        """The SIGTERM escalation ladder's final rung: checkpoint-now-and-
        requeue. The flight recorder already dumped its `preempt` bundle
        from the signal hook; here (at the cross-host-agreed step
        boundary, on the main thread) the state is checkpointed
        synchronously through the atomic crc32c sidecar path, journaled as
        a typed `preempt_checkpoint` event, and the run is marked for the
        scheduler's requeue exit code (obs.flight.REQUEUE_EXIT_CODE) —
        honest about the outcome either way (the VM dies shortly; the
        operator must know whether the step made it to disk)."""
        from deep_vision_tpu.obs import flight as _flight

        step = int(self.state.step)
        self.preempted = True
        if self.ckpt is None:
            print(f"preempted at step {step}: NO checkpoint manager, "
                  "state not saved; exiting fit", flush=True)
            if self.journal is not None:
                self.journal.write("preempt_checkpoint", step=step,
                                   epoch=int(epoch), saved=False,
                                   reason="no checkpoint manager")
            _flight.request_requeue()
            return
        saved = self._save_checkpoint(epoch)
        self.ckpt.wait()
        if self._ema_ckpt is not None:
            self._ema_ckpt.wait()
        if saved:
            print(f"preempted at step {step}: checkpoint written, "
                  "exiting fit", flush=True)
        else:
            print(f"preempted at step {step}: checkpoint manager DECLINED "
                  f"the save (latest on disk: {self.ckpt.latest_step()}); "
                  "exiting fit", flush=True)
        if self.journal is not None:
            self.journal.write("preempt_checkpoint", step=step,
                               epoch=int(epoch), saved=bool(saved),
                               dir=self.ckpt.directory)
        _flight.request_requeue()

    def _grouped(self, data):
        """Coalesce host batches into lists of `multistep` for the scan
        superstep; the short epoch tail flows through as single batches so
        the stacked executable never sees a ragged shape (no recompile)."""
        pending = []
        for batch in data:
            pending.append(batch)
            if len(pending) == self.multistep:
                yield pending
                pending = []
        for batch in pending:
            yield batch

    def _run_epoch(self, train_data_fn, epoch):
        """One epoch of steps; returns ("preempted"|None, logger summary).

        Three data paths share this loop: plain host batches, device-
        prefetched PlacedBatches (H2D already off the critical path), and
        multistep groups (one dispatch = K optimizer steps) — the latter
        two composed by the prefetcher itself when both are on. The
        grouping/prefetch stage sits INSIDE clock.iter_data so data_wait
        honestly covers the whole wait for a dispatch's worth of input."""
        self.logger.start_epoch()
        data = train_data_fn()
        if self._prefetcher is not None:
            data = self._prefetcher(data)
        elif self.multistep > 1:
            data = self._grouped(data)
        for item in self.clock.iter_data(data):
            is_group = isinstance(item, list) or (
                isinstance(item, PlacedBatch) and item.group > 1)
            if is_group:
                status = self._superstep_and_log(item, epoch)
            else:
                status = self._single_step_and_log(item, epoch)
            if status == "preempted":
                # no end_epoch: a partial-epoch summary would pollute the
                # history/TensorBoard rows the re-run epoch writes again
                return "preempted", None
        return None, self.logger.end_epoch(epoch)

    def _single_step_and_log(self, batch, epoch):
        """The classic one-batch step body; `batch` may be a PlacedBatch."""
        if isinstance(batch, PlacedBatch):
            n = batch.n
        else:
            n = np.shape(batch[self.input_key])[0]
        with span("train/step", epoch=epoch) as sp:
            with self.clock.step(batch_size=n, auto_commit=False) as rec:
                metrics = self.train_step(batch)
                self._host_fetch(lambda: rec.fence_on(metrics))
            # these fetches block on the in-flight state — outside the
            # with-block so dispatch_ms stays enqueue-only (the
            # starvation signal compares data_wait against it);
            # commit() folds their cost into step_time_ms. Lease-checked
            # (_host_fetch): in a multi-host world a dead peer wedges
            # them forever otherwise.
            opt_step = self._host_fetch(lambda: int(self.state.step))
            lr = self.lr_at(opt_step)
            sp.set(step=opt_step)
            rec.commit(step=opt_step,
                       metrics={"loss": metrics["loss"], "lr": lr}
                       if "loss" in metrics else {"lr": lr})
        # publish the host-side mirror the telemetry scraper reads (plain
        # attribute writes: benign to race, never a device fetch)
        self._live_step, self._live_epoch = opt_step, epoch
        self._live_eps = rec.examples_per_sec
        # anomaly triggers see the committed record (step-time/data-wait
        # z-scores, recompile bursts, HBM high-water jumps) and arm a
        # capture that the NEXT step's _profiler_hook starts
        if self.prof is not None:
            self.prof.observe_step(opt_step, rec.fields())
        # one host fetch for loggers + health (log_step floats every
        # metric anyway, so this adds no extra device sync)
        metrics_f = {k: float(v) for k, v in metrics.items()}
        loss_f = metrics_f.get("loss")
        grad_norm_f = metrics_f.get("grad_norm")
        skipped = (self._skip_nonfinite
                   and metrics_f.get("skipped", 0.0) > 0)
        if skipped:
            # the discarded update's loss/grads are garbage: keep them
            # out of the epoch means and TB series — the health event
            # and skipped counter (below) carry the record instead
            metrics_f = {k: v for k, v in metrics_f.items()
                         if v == v and abs(v) != float("inf")}
        # (train_learning_rate gauge: MetricLogger's NaN-guarded write)
        self.logger.log_step(
            opt_step, metrics_f, batch_size=n, epoch=epoch,
            lr=lr, data_wait_ms=rec.data_wait_ms,
            examples_per_sec=rec.examples_per_sec,
        )
        # health guard AFTER the step/log writes: an abort's journal
        # then reads step -> health(non_finite) -> crash, in order
        if self.health is not None:
            self.health.check_step(opt_step, loss=loss_f,
                                   grad_norm=grad_norm_f,
                                   skipped=skipped)
        # poll keyed to the optimizer step — globally consistent across
        # hosts, immune to unequal agreed() call counts elsewhere
        if self._pguard is not None and self._pguard.agreed(step=opt_step):
            # epoch-1: this epoch is incomplete, resume re-runs it
            self._preempt_save(epoch - 1)
            return "preempted"
        return None

    def _superstep_and_log(self, item, epoch):
        """One scan dispatch = K optimizer steps; per-microstep metrics are
        recovered from the scanned stack and logged/health-checked exactly
        as K single steps would have been."""
        k = self.multistep
        if isinstance(item, PlacedBatch):
            n_total = item.n
        else:
            n_total = sum(int(np.shape(b[self.input_key])[0]) for b in item)
        with span("train/step", epoch=epoch) as sp:
            with self.clock.step(batch_size=n_total,
                                 auto_commit=False) as rec:
                metrics_k = self.train_superstep(item)
                self._host_fetch(lambda: rec.fence_on(metrics_k))
            opt_step = self._host_fetch(lambda: int(self.state.step))
            lr = self.lr_at(opt_step)
            sp.set(step=opt_step, multistep=k)
            last = metrics_k[-1]
            # journal: ONE step event per dispatch (the thing that actually
            # happened), stamped multistep=K; loggers below keep per-
            # microstep series so histories stay comparable across K
            rec.commit(step=opt_step,
                       metrics={"loss": last["loss"], "lr": lr}
                       if "loss" in last else {"lr": lr},
                       extra={"multistep": k})
        self._live_step, self._live_epoch = opt_step, epoch
        self._live_eps = rec.examples_per_sec
        if self.prof is not None:
            self.prof.observe_step(opt_step, rec.fields())
        floats = jax.device_get(metrics_k)  # ONE fetch for all K microsteps
        n_each = max(1, n_total // k)
        for i, mf in enumerate(floats):
            step_i = opt_step - (k - 1) + i
            mf = {kk: float(v) for kk, v in mf.items()}
            loss_f = mf.get("loss")
            grad_norm_f = mf.get("grad_norm")
            skipped = (self._skip_nonfinite and mf.get("skipped", 0.0) > 0)
            logged = mf
            if skipped:
                logged = {kk: v for kk, v in mf.items()
                          if v == v and abs(v) != float("inf")}
            # per-microstep LR: the post-dispatch hyperparam only reflects
            # the LAST microstep — under a schedule, re-evaluate it at each
            # microstep's pre-update count (update t uses schedule(t-1),
            # matching what lr_at reads after a single-step dispatch)
            lr_i = (float(self._lr_schedule(step_i - 1))
                    if callable(self._lr_schedule) else lr)
            # data_wait amortizes over the K microsteps the one gather fed;
            # examples_per_sec is the dispatch's wall rate (same for all K)
            self.logger.log_step(
                step_i, logged, batch_size=n_each, epoch=epoch, lr=lr_i,
                data_wait_ms=rec.data_wait_ms / k,
                examples_per_sec=rec.examples_per_sec,
            )
            if self.health is not None:
                self.health.check_step(step_i, loss=loss_f,
                                       grad_norm=grad_norm_f,
                                       skipped=skipped)
        if self._pguard is not None and self._pguard.agreed(step=opt_step):
            self._preempt_save(epoch - 1)
            return "preempted"
        return None

    def _post_epoch(self, summary, eval_data_fn, epoch, save_every):
        # failure detection the reference has none of (SURVEY §5): a
        # diverged run must stop loudly, not burn the remaining epochs.
        # Checked at epoch granularity so the hot loop stays sync-free.
        loss_avg = summary.get("loss")
        if loss_avg is not None and not np.isfinite(loss_avg):
            relax = (self.health is not None
                     and getattr(self.health, "policy_explicit", True)
                     and not self.health.skip_nonfinite
                     and self.health.policy != "abort")
            if relax:
                # explicit warn policy: the health layer already journaled
                # every non-finite step; a poisoned epoch mean is reported,
                # not fatal — 'warn continues' is the policy's contract. A
                # defaulted policy (watchdog-only monitor) keeps the
                # pre-existing fatal behavior below.
                self.health.check_summary(epoch, {"loss": loss_avg})
            else:
                # leave postmortem artifacts intact: flush the in-flight
                # async checkpoint and close any open profiler trace first
                if self.ckpt is not None:
                    self.ckpt.wait()
                self._stop_trace()
                if self.journal is not None:
                    self.journal.write(
                        "note", note=f"diverged at epoch {epoch}: "
                                     f"mean loss {loss_avg}")
                if self.health is not None:
                    # abort policy (or a skip_step run whose mean still
                    # went non-finite): typed health event, then raise
                    self.health.check_summary(epoch, {"loss": loss_avg})
                raise FloatingPointError(
                    f"training diverged: epoch {epoch} mean loss is "
                    f"{loss_avg} (re-run with train.py --debug-nans to "
                    "locate the first non-finite op)"
                )

        # honor a SIGTERM that landed after the last step (or during eval,
        # which bails early): the epoch's training IS complete, save as such
        if self._pguard is not None and self._pguard.agreed(force=True):
            self._preempt_save(epoch)
            return "preempted"
        val_summary = {}
        if eval_data_fn is not None:
            val_summary = self.evaluate(eval_data_fn(), epoch=epoch)
        if self._pguard is not None and self._pguard.agreed(force=True):
            self._preempt_save(epoch)
            return "preempted"

        if (
            self.plateau is not None
            and self.plateau_metric in val_summary
            and self._base_lr is not None
        ):
            scale = self.plateau.step(val_summary[self.plateau_metric])
            self.state = self.state.replace(
                opt_state=_set_lr(self.state.opt_state, self._base_lr * scale)
            )

        if self.ckpt is not None and (epoch + 1) % save_every == 0:
            self._save_checkpoint(epoch, val_summary)

    def resume(self, step: Optional[int] = None) -> int:
        """Restore state + host loggers/plateau; returns next epoch to run.

        Rides CheckpointManager's fallback chain: with `step=None` a
        corrupt/incomplete latest step is quarantined (typed
        `ckpt_quarantine` journal event) and the newest valid one restores
        instead — resume() survives a save the crash tore in half. When
        NOTHING valid remains, returns 0: restarting from scratch is the
        honest floor of the degradation ladder, and the journal records
        why.

        Cross-mesh: the restore is handed THIS trainer's mesh, so a
        checkpoint written on a different topology (8 devices, say) lands
        re-placed against the current one (4, or 1) per the sharding
        metadata the save recorded — a preempted run resumes on whatever
        slice the scheduler gives back."""
        assert self.ckpt is not None, "no CheckpointManager configured"
        t0 = time.perf_counter()
        with span("checkpoint/restore", step=step if step is not None
                  else -1):
            self.state, host_state = self.ckpt.restore(self.state, step,
                                                       mesh=self.mesh)
        if self.journal is not None:
            # restore_ms: the goodput plane's restore feed — the gap
            # before this note lands in the checkpoint bucket
            self.journal.write(
                "note", note="resumed", step=int(self.state.step),
                host_state_found=host_state is not None,
                restore_ms=round((time.perf_counter() - t0) * 1e3, 3))
        if not getattr(self.ckpt, "last_restore_placed", False):
            # legacy manager (or nothing restored): re-place on this
            # trainer's mesh — per the sharding table when one is
            # attached, the old blanket replicate otherwise
            self.state = self._place_state(self.state)
        if self.ema is not None:
            restored_ema, ema_host = (None, None)
            if self._ema_ckpt is not None:
                # pin the EMA restore to the step the MAIN restore landed
                # on: after a quarantine fallback the EMA dir's latest can
                # be newer than the restored params, and a mixed-step
                # (params, shadow) pair would silently change eval
                ema_step = step if step is not None else int(self.state.step)
                try:
                    restored_ema, ema_host = self._ema_ckpt.restore_tree(
                        dict(self.ema.params), ema_step
                    )
                except Exception:
                    restored_ema, ema_host = (None, None)
            if restored_ema is not None:
                self.ema.params = restored_ema
                self.ema.load_state_dict(ema_host or {})
            else:
                # checkpoint predates --ema-decay, or the EMA shadow for
                # the restored step is itself missing/corrupt: seed from
                # the restored weights rather than the fresh init
                from deep_vision_tpu.train.ema import EmaParams

                self.ema = EmaParams(self.state.params, decay=self.ema.decay,
                                     warmup=self.ema.warmup)
        if not host_state:
            self._resume_data_state(None)
            return 0
        self.logger.load_state_dict(host_state.get("train_logger", {}))
        self.eval_logger.load_state_dict(host_state.get("val_logger", {}))
        if self.plateau is not None and "plateau" in host_state:
            self.plateau.load_state_dict(host_state["plateau"])
        self._resume_data_state(host_state.get("data_state"))
        return int(host_state.get("epoch", -1)) + 1

    def _resume_data_state(self, data_state) -> None:
        """Re-arm the input pipeline from the sidecar's DataLoaderState
        and journal the typed `data_resume` verdict: 'restored' = the
        loader will replay its exact position (byte-identical stream),
        'fresh' = the checkpoint predates --data-snapshot (or carried no
        loader state) and the stream restarts at epoch 0 — honest, and
        visible in obs_report instead of silent. A SnapshotMismatch
        (dataset changed on disk) propagates: resuming on a shifted
        stream is corruption, not degradation."""
        if self.data_loader is None:
            return
        if data_state:
            info = self.data_loader.load_state_dict(data_state)
            if self.journal is not None:
                self.journal.write(
                    "data_resume", verdict="restored",
                    epoch=int(info["epoch"]), batches=int(info["batches"]),
                    shard=info.get("shard"), record=info.get("record"))
        elif self.journal is not None:
            self.journal.write("data_resume", verdict="fresh",
                               epoch=0, batches=0)
