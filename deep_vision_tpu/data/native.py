"""ctypes binding to the native record-IO runtime (native/libdvtpu.so).

The C++ reader (native/record_reader.cc) parses record framing + crc32c off
the GIL and prefetches multiple shards with a thread pool; this module makes
it a drop-in for the pure-Python `data.records` functions. Falls back to
None when the library hasn't been built (`make -C native`) — callers gate on
`load_library() is not None`.
"""
from __future__ import annotations

import ctypes
import os
from typing import Iterator, List, Optional, Sequence

_OK, _EOF, _CORRUPT, _IOERR, _TRUNCATED = 0, 1, 2, 3, 4

_lib: Optional[ctypes.CDLL] = None


def _repo_lib_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "native", "libdvtpu.so")


def load_library(path: Optional[str] = None) -> Optional[ctypes.CDLL]:
    """Load libdvtpu.so (env DVTPU_NATIVE_LIB > repo native/). None if absent."""
    # only success is cached: the library may be built after the first probe
    # (the test fixture does exactly that), so a miss re-stats each call
    global _lib
    if _lib is not None:
        return _lib
    candidates = (
        [path] if path else
        [os.environ.get("DVTPU_NATIVE_LIB", ""), _repo_lib_path()]
    )
    for cand in candidates:
        if not cand or not os.path.exists(cand):
            continue
        lib = ctypes.CDLL(cand)
        lib.dv_reader_open.restype = ctypes.c_void_p
        lib.dv_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.dv_reader_next.restype = ctypes.c_int
        lib.dv_reader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.dv_reader_close.argtypes = [ctypes.c_void_p]
        lib.dv_pool_open.restype = ctypes.c_void_p
        lib.dv_pool_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_int,
        ]
        lib.dv_pool_next.restype = ctypes.c_int
        lib.dv_pool_next.argtypes = lib.dv_reader_next.argtypes
        lib.dv_pool_close.argtypes = [ctypes.c_void_p]
        lib.dv_masked_crc32c.restype = ctypes.c_uint32
        lib.dv_masked_crc32c.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64
        ]
        _lib = lib
        return _lib
    return None


def _drain(lib, handle, next_fn, close_fn, what: str) -> Iterator[bytes]:
    data = ctypes.POINTER(ctypes.c_uint8)()
    length = ctypes.c_uint64()
    try:
        while True:
            rc = next_fn(handle, ctypes.byref(data), ctypes.byref(length))
            if rc == _EOF:
                return
            # exception parity with records.read_records: truncation is
            # EOFError (records.py), CRC mismatch is IOError
            if rc == _TRUNCATED:
                raise EOFError(f"truncated record in {what}")
            if rc == _CORRUPT:
                raise IOError(f"corrupt record in {what}")
            if rc == _IOERR:
                raise IOError(f"io error reading {what}")
            yield ctypes.string_at(data, length.value)
    finally:
        close_fn(handle)


def read_records_native(path: str, verify: bool = True) -> Iterator[bytes]:
    """Native twin of records.read_records (same exceptions, same output)."""
    lib = load_library()
    assert lib is not None, "native library not built (make -C native)"
    handle = lib.dv_reader_open(path.encode(), int(verify))
    if not handle:
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        raise IOError(f"cannot open {path}")
    yield from _drain(lib, handle, lib.dv_reader_next, lib.dv_reader_close,
                      path)


def pool_records_native(
    paths: Sequence[str], num_threads: int = 4, capacity: int = 256,
    verify: bool = True,
) -> Iterator[bytes]:
    """Multi-shard threaded prefetch. NOTE: records from different shards
    interleave nondeterministically (throughput mode; use
    read_records_native per file when order matters)."""
    lib = load_library()
    assert lib is not None, "native library not built (make -C native)"
    arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
    handle = lib.dv_pool_open(arr, len(paths), num_threads, capacity,
                              int(verify))
    yield from _drain(lib, handle, lib.dv_pool_next, lib.dv_pool_close,
                      f"pool of {len(paths)} shards")


def native_available() -> bool:
    return load_library() is not None
