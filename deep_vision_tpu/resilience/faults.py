"""Deterministic, seeded fault injection at the repo's I/O boundaries.

Storage-failure handling is only trustworthy if it is *testable on CPU*
— Check-N-Run and Varuna both validate their recovery paths with
injected failures, not by waiting for real ones. This module is the
repo's switchboard: named injection points sit at every I/O boundary
(record read, sample decode, checkpoint save/restore, sidecar write,
journal flush, serving-replica execution) and compile to a single
module-global None-check when no spec is installed, so production runs
pay nothing.

Spec grammar (the `--fault-spec` CLI string)::

    point:kind[@when][;point:kind[@when]...]

    data.read:io_error@0.01      # each record read fails w.p. 0.01
    ckpt.sidecar:crash_after_write   # SIGKILL after the 1st tmp write
    ckpt.sidecar:corrupt@2       # flip bytes in the 2nd sidecar written
    journal.flush:io_error@5     # exactly the 5th journal line errors

`when` is a probability when it parses as a float < 1, and "fire exactly
on the Nth hit of this point, once" when it is an integer >= 1 (the
deterministic form every test and the chaos smoke use). Omitted, it
means `1` (first hit). Kinds:

    io_error           raise FaultInjected (an IOError subclass) at the point
    crash              SIGKILL the current process at the point
    crash_after_write  SIGKILL at the point's after-write stage (between a
                       tmp-file write and its atomic rename — the torn-write
                       window)
    corrupt            deterministically flip bytes in data passed through
                       `transform()` at the point (e.g. the sidecar payload)

Rate faults draw from a per-rule `random.Random` seeded from
(seed, point, kind), so a given seed reproduces the exact same fault
sequence run over run. Installation also exports DVT_FAULT_SPEC /
DVT_FAULT_SEED to the environment so spawned data-loader worker
processes (data/pipeline.py spawn context) inherit the spec: this module
auto-installs from those variables at import time. Fired faults emit a
typed `fault` journal event (in the parent process, when a journal is
attached) and bump `fault_injected_total{point=,kind=}`.
"""
from __future__ import annotations

import os
import random
import signal
import sys
import threading
from typing import List, Optional

from deep_vision_tpu.core import knobs

ENV_SPEC = "DVT_FAULT_SPEC"
ENV_SEED = "DVT_FAULT_SEED"

#: the registered injection points; parse() rejects unknown ones so a
#: typo'd spec fails loudly instead of silently injecting nothing
POINTS = (
    "data.read",      # one framed record read from a shard
    "data.decode",    # Example decode + schema application
    "ckpt.save",      # orbax array-tree save enqueue
    "ckpt.restore",   # orbax array-tree restore
    "ckpt.sidecar",   # host-state JSON sidecar write (has after_write stage)
    "journal.flush",  # one journal line write+flush
    "serve.replica",  # a serving replica's execution boundary (serve/pool.py
                      # batch dispatch + respawn) and the swap-restore step
                      # (serve/swap.py): io_error = replica death / failed
                      # swap load, crash = the whole serving process dies
    "data.service",   # the dataset service's frame boundary (data/service.py
                      # send/recv: io_error = dropped client connection the
                      # RetryPolicy must absorb) and its worker body
                      # (env-inherited: crash = a worker process SIGKILLed,
                      # the data_worker_lost/respawn path)
    "serve.transport",  # the HTTP front door's request boundary
                      # (serve/transport.py: io_error = mid-frame
                      # connection reset, corrupt = truncated/garbage
                      # request body via transform(), crash = the
                      # serving process dies mid-request) — a torn
                      # request must fail exactly one response and
                      # never wedge an acceptor thread
)
KINDS = ("io_error", "crash", "crash_after_write", "corrupt")


class FaultInjected(IOError):
    """The injected transient I/O error; an IOError so every real handler
    (retry policies, bad-record budgets) treats it exactly like the
    genuine article, while tests can still tell it apart by type."""


class FaultSpecError(ValueError):
    """Unparseable --fault-spec string."""


class _Rule:
    def __init__(self, point: str, kind: str, when: float, seed: int):
        self.point = point
        self.kind = kind
        # float in (0, 1): per-hit probability; int >= 1: fire exactly on
        # the Nth hit, once
        self.probability = when if when < 1.0 else None
        self.nth = int(when) if when >= 1.0 else None
        self.hits = 0
        self.fired = 0
        self._rng = random.Random(f"{seed}:{point}:{kind}")
        # points can be hit from several threads at once (serve.replica
        # fires on every pool dispatcher): the hit counter must stay
        # exact or the @N deterministic form fires twice or never
        self._tlock = threading.Lock()

    def triggers(self) -> bool:
        with self._tlock:
            self.hits += 1
            if self.nth is not None:
                if self.hits == self.nth:
                    self.fired += 1
                    return True
                return False
            if self._rng.random() < self.probability:
                self.fired += 1
                return True
            return False

    def __repr__(self):
        when = self.nth if self.nth is not None else f"@{self.probability}"
        return f"_Rule({self.point}:{self.kind}@{when}, fired={self.fired})"


class FaultInjector:
    """Holds the parsed rules; `fire`/`transform` are its two hooks."""

    def __init__(self, rules: List[_Rule], seed: int = 0, journal=None,
                 registry=None):
        self.rules = rules
        self.seed = seed
        self.journal = journal
        self._registry = registry
        self.spec = ";".join(
            f"{r.point}:{r.kind}@{r.nth if r.nth is not None else r.probability}"
            for r in rules
        )

    @classmethod
    def parse(cls, spec: str, seed: int = 0, journal=None,
              registry=None) -> "FaultInjector":
        rules: List[_Rule] = []
        for part in (spec or "").split(";"):
            part = part.strip()
            if not part:
                continue
            try:
                point, rest = part.split(":", 1)
            except ValueError:
                raise FaultSpecError(
                    f"fault spec entry {part!r} is not 'point:kind[@when]'")
            if "@" in rest:
                kind, when_s = rest.split("@", 1)
                try:
                    when = float(when_s)
                except ValueError:
                    raise FaultSpecError(
                        f"fault spec {part!r}: '@{when_s}' is neither a "
                        "probability (<1) nor an Nth-hit integer (>=1)")
                if when <= 0:
                    raise FaultSpecError(
                        f"fault spec {part!r}: '@{when_s}' must be positive")
            else:
                kind, when = rest, 1.0
            point, kind = point.strip(), kind.strip()
            if point not in POINTS:
                raise FaultSpecError(
                    f"unknown injection point {point!r}; have {POINTS}")
            if kind not in KINDS:
                raise FaultSpecError(
                    f"unknown fault kind {kind!r}; have {KINDS}")
            rules.append(_Rule(point, kind, when, seed))
        return cls(rules, seed=seed, journal=journal, registry=registry)

    # -- bookkeeping ---------------------------------------------------------

    def set_journal(self, journal) -> None:
        """Attach the run journal after install (the CLI installs faults
        before it builds the journal so data-loader construction is already
        covered)."""
        self.journal = journal

    def _note(self, point: str, kind: str, stage: Optional[str]) -> None:
        try:
            reg = self._registry
            if reg is None:
                from deep_vision_tpu.obs.registry import get_registry

                reg = get_registry()
            reg.counter("fault_injected_total", "injected faults fired",
                        labels={"point": point, "kind": kind}).inc()
        except Exception:
            pass
        # journal.flush faults must not journal themselves: RunJournal.write
        # is the caller one frame up and its re-entry would deadlock on the
        # journal lock (and recurse through this very injection point)
        if self.journal is not None and point != "journal.flush":
            self.journal.write("fault", point=point, kind=kind,
                               **({"stage": stage} if stage else {}))

    # -- the two hooks -------------------------------------------------------

    def fire(self, point: str, stage: Optional[str] = None) -> None:
        """Raise/crash if a rule for `point` (at `stage`) triggers.

        stage=None is a point's primary position (io_error/crash rules);
        stage="after_write" is the post-tmp-write position only
        crash_after_write rules match — the torn-write window.
        """
        for r in self.rules:
            if r.point != point:
                continue
            if (r.kind == "crash_after_write") != (stage == "after_write"):
                continue
            if r.kind == "corrupt":
                continue  # corrupt rules act in transform(), not fire()
            if not r.triggers():
                continue
            self._note(point, r.kind, stage)
            if r.kind == "io_error":
                raise FaultInjected(
                    f"injected io_error at {point}"
                    + (f" (stage={stage})" if stage else ""))
            # crash / crash_after_write: die the way real preemption does —
            # no handlers, no atexit, no flushed buffers. The flight
            # recorder's bundle is the ONE artifact written first: a real
            # SIGKILL gives no warning, but its postmortem value is exactly
            # what the chaos loop exists to prove, so the injected variant
            # dumps the black box in the instants before the kill (fsynced
            # + atomically renamed — obs/flight.py survives what follows)
            try:
                from deep_vision_tpu.obs import flight

                flight.emergency_dump(f"injected_{r.kind}")
            except Exception:
                pass
            sys.stderr.write(
                f"faults: injected {r.kind} at {point} — SIGKILL\n")
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGKILL)

    def transform(self, point: str, data: bytes) -> bytes:
        """Pass `data` through any triggered corrupt rules for `point`:
        deterministically flip a byte in the middle and truncate the tail
        (both torn-write signatures a checksum must catch)."""
        for r in self.rules:
            if r.point != point or r.kind != "corrupt":
                continue
            if not r.triggers():
                continue
            self._note(point, "corrupt", None)
            if not data:
                return b"\xff"
            mid = len(data) // 2
            data = (data[:mid]
                    + bytes([data[mid] ^ 0xFF])
                    + data[mid + 1:max(mid + 1, len(data) - 3)])
        return data


# -- module-global hook (the "compiles to a no-op" part) ----------------------

_INSTALLED: Optional[FaultInjector] = None


def installed() -> Optional[FaultInjector]:
    return _INSTALLED


def install(inj: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install (or, with None, clear) the process-wide injector."""
    global _INSTALLED
    _INSTALLED = inj
    return inj


def install_spec(spec: Optional[str], seed: int = 0, journal=None,
                 export_env: bool = True) -> Optional[FaultInjector]:
    """Parse + install a spec string; with export_env, also export it so
    spawned data workers inherit the same faults (they auto-install from
    the environment at import). Empty/None spec clears the installation."""
    if not spec:
        if export_env:
            os.environ.pop(ENV_SPEC, None)
            os.environ.pop(ENV_SEED, None)
        return install(None)
    inj = FaultInjector.parse(spec, seed=seed, journal=journal)
    if export_env:
        os.environ[ENV_SPEC] = spec
        os.environ[ENV_SEED] = str(seed)
    return install(inj)


def fire(point: str, stage: Optional[str] = None) -> None:
    """The hot-path hook: one global load + None check when disabled."""
    inj = _INSTALLED
    if inj is not None:
        inj.fire(point, stage)


def transform(point: str, data: bytes) -> bytes:
    inj = _INSTALLED
    return data if inj is None else inj.transform(point, data)


# spawned worker processes inherit the spec through the environment
if knobs.get_str(ENV_SPEC):
    try:
        install_spec(knobs.get_str(ENV_SPEC),
                     seed=knobs.get_int(ENV_SEED),
                     export_env=False)
    # a bad env spec/seed must not break imports (KnobError: garbage
    # DVT_FAULT_SEED — loud in the parent that exported it, ignored here)
    except (FaultSpecError, knobs.KnobError) as e:
        sys.stderr.write(f"faults: ignoring {ENV_SPEC}: {e}\n")
