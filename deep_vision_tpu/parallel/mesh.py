"""Device mesh construction and sharding helpers.

This is the TPU-native replacement for the reference's three single-host
data-parallel wrappers (`nn.DataParallel` at ResNet/pytorch/train.py:353-355,
`tf.distribute.MirroredStrategy` at YOLO/tensorflow/train.py:281, and
`keras.utils.multi_gpu_model` at ResNet/tensorflow/train.py:249-251).

Instead of wrapping a model, we build a named `jax.sharding.Mesh` once and
express every parallelism flavor as a sharding of arrays over its axes:

- ``data``  : batch (data parallel; the only axis the reference ever used)
- ``model`` : tensor parallel (output features of wide layers)

Sequence/context parallelism for attention workloads reuses the ``data``
axis (see `parallel/ring_attention.py`) so long sequences shard over the
same mesh without a dedicated axis.  XLA's SPMD partitioner inserts the
all-reduce / all-gather / reduce-scatter collectives over ICI; cross-host
meshes ride DCN transparently (`jax.distributed.initialize` in
`parallel/multihost.py`).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """How to lay a device list out as a (data, model) mesh."""

    data: int = -1  # -1: all remaining devices
    model: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int]:
        model = max(1, self.model)
        if n_devices % model != 0:
            raise ValueError(f"model axis {model} does not divide {n_devices} devices")
        data = self.data if self.data > 0 else n_devices // model
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} != {n_devices} devices; pass data=-1 to infer"
            )
        return data, model


def create_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    data: int = -1,
    model: int = 1,
) -> Mesh:
    """Build a 2-D ('data', 'model') mesh over the given (default: all) devices.

    ``create_mesh()`` -> all devices on the data axis: pure data parallel,
    exactly mirroring the reference's `global_batch = batch * num_replicas`
    contract (YOLO/tensorflow/train.py:282).
    """
    if spec is None:
        spec = MeshSpec(data=data, model=model)
    if devices is None:
        devices = jax.devices()
    d, m = spec.resolve(len(devices))
    arr = np.asarray(devices).reshape(d, m)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def local_mesh_devices(mesh: Mesh) -> list[jax.Device]:
    """Devices of `mesh` that live on this host (for host-sharded input feed)."""
    procid = jax.process_index()
    return [d for d in mesh.devices.flat if d.process_index == procid]


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (params/opt state in plain data parallel)."""
    return NamedSharding(mesh, P())


def _batch_entry(axes: Sequence[str]):
    """The PartitionSpec entry for a batch dim sharded over `axes` (one
    axis name, or a tuple for multi-axis batch layouts like
    ('data', 'model') fully-data-parallel tables)."""
    axes = tuple(axes)
    return axes[0] if len(axes) == 1 else axes


def data_sharding(mesh: Mesh, ndim: int = 1,
                  axes: Sequence[str] = (DATA_AXIS,)) -> NamedSharding:
    """Shard the leading (batch) dimension over `axes` (default 'data').
    Declarative sharding tables (parallel/shardmap.py) may declare other
    batch axes; the Trainer threads them through here."""
    return NamedSharding(
        mesh, P(_batch_entry(axes), *([None] * (ndim - 1))))


def stacked_data_sharding(mesh: Mesh, ndim: int = 2,
                          axes: Sequence[str] = (DATA_AXIS,)
                          ) -> NamedSharding:
    """Sharding for a (K, B, ...) stacked superstep batch (train/trainer.py
    multistep mode): the scan axis K replicates, the batch dim shards over
    the table's batch axes (default 'data') — each dispatch carries K
    microsteps' batches in one transfer."""
    return NamedSharding(
        mesh, P(None, _batch_entry(axes), *([None] * (ndim - 2))))


def shard_batch(mesh: Mesh, batch, axes: Sequence[str] = (DATA_AXIS,)):
    """Place a host batch (pytree of np/jnp arrays) with batch-dim sharding.

    The device boundary of the framework: everything before this call is
    host-side numpy; everything after is SPMD on the mesh.
    """

    def _place(x):
        if isinstance(x, jax.Array) and len(x.sharding.device_set) > 1:
            # already a globally-sharded array (multi-host callers build
            # batches with multihost.form_global_array — this host cannot
            # re-place an array whose shards live on other hosts)
            return x
        x = np.asarray(x)
        return jax.device_put(x, data_sharding(mesh, x.ndim, axes))

    return jax.tree_util.tree_map(_place, batch)


def infer_tp_sharding(tree, mesh: Mesh, min_size: int = 4096):
    """Tensor-parallel sharding rule for a params/state pytree.

    Shards the output-feature (last) dim of large kernels over the 'model'
    axis when it divides evenly; everything else (biases, BN stats, scalars)
    is replicated. XLA's SPMD partitioner propagates the layout through the
    matmuls/convs and inserts the ICI collectives — the explicit Megatron-style
    plumbing the reference never had (its only parallelism was single-host DP,
    SURVEY.md §2.5) falls out of the sharding annotation alone.
    """
    m = mesh.shape[MODEL_AXIS]

    def rule(x):
        shape = getattr(x, "shape", ())
        size = int(np.prod(shape)) if shape else 0
        if (
            m > 1
            and len(shape) >= 2
            and shape[-1] % m == 0
            and size >= min_size
        ):
            return NamedSharding(mesh, P(*([None] * (len(shape) - 1) + [MODEL_AXIS])))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, tree)


class ShardingCoverageError(ValueError):
    """A float leaf has no sharding assignment, or the sharded-leaf count
    regressed below the configured floor. Raised at STARTUP, before any
    step runs: the `tp_sharded_leaves` count silently falling 108 -> 34
    between MULTICHIP r03 and r05 (nothing alerted) is the incident this
    check turns into a hard failure."""


def sharding_coverage(tree, shardings) -> dict:
    """Coverage stats for a (params/state, shardings) pair.

    Returns {"float_leaves", "sharded", "replicated", "replicated_paths",
    "unmatched"}: `sharded` counts float leaves whose NamedSharding
    references at least one mesh axis, `replicated` the rest (their paths
    in `replicated_paths` — the 108 -> 34 incident was undebuggable from
    bare counts), and `unmatched` lists the paths of float leaves the
    sharding tree does not cover with a Sharding at all (a declarative
    rule that stopped matching, a structure drift). Non-float leaves
    (step counters, RNG keys, labels) are ignored — only the leaves whose
    placement decides memory and collective traffic count."""
    import jax.numpy as jnp
    from jax.sharding import Sharding

    flat_t, _ = jax.tree_util.tree_flatten_with_path(tree)
    flat_s, _ = jax.tree_util.tree_flatten_with_path(
        shardings, is_leaf=lambda x: isinstance(x, Sharding))
    by_path = {jax.tree_util.keystr(p): s for p, s in flat_s}
    stats = {"float_leaves": 0, "sharded": 0, "replicated": 0,
             "replicated_paths": [], "unmatched": []}
    for p, x in flat_t:
        dtype = getattr(x, "dtype", None)
        if dtype is None or not jnp.issubdtype(dtype, jnp.floating):
            continue
        stats["float_leaves"] += 1
        path = jax.tree_util.keystr(p)
        s = by_path.get(path)
        if not isinstance(s, Sharding):
            stats["unmatched"].append(path)
        elif isinstance(s, NamedSharding) and any(
                e is not None for e in tuple(s.spec)):
            stats["sharded"] += 1
        else:
            stats["replicated"] += 1
            stats["replicated_paths"].append(path)
    return stats


def _sample_paths(paths, n: int = 5) -> str:
    sample = ", ".join(paths[:n])
    more = len(paths) - n
    return sample + (f" (+{more} more)" if more > 0 else "")


def assert_sharding_coverage(tree, shardings, mesh=None, min_sharded: int = 0,
                             registry=None) -> dict:
    """The startup hard check behind the 108 -> 34 incident: every float
    leaf must have matched a sharding rule, and at least `min_sharded` of
    them must actually be sharded (not replicated). Exports the counts as
    `parallel_sharded_leaves` / `parallel_float_leaves` gauges either
    way, so the journal/metrics trail shows the number even when the
    assert passes. Returns the stats dict."""
    stats = sharding_coverage(tree, shardings)
    try:
        if registry is None:
            from deep_vision_tpu.obs.registry import get_registry

            registry = get_registry()
        registry.gauge("parallel_sharded_leaves",
                       "float leaves sharded over a mesh axis"
                       ).set(stats["sharded"])
        registry.gauge("parallel_float_leaves",
                       "float leaves considered by the sharding rules"
                       ).set(stats["float_leaves"])
    except Exception:
        pass  # metrics must not turn the check itself into a crash
    if stats["unmatched"]:
        raise ShardingCoverageError(
            f"{len(stats['unmatched'])} float leaf(s) matched NO sharding "
            f"rule: {_sample_paths(stats['unmatched'])}"
            " — every float leaf must resolve to a sharding; a rule "
            "stopped matching or the state structure drifted")
    if stats["sharded"] < min_sharded:
        shape = dict(mesh.shape) if mesh is not None else "?"
        # name the leaves that fell back to replication, not just the
        # counts: the 108 -> 34 regression was undebuggable from the
        # numbers alone — the operator needs to see WHICH leaves the
        # rules stopped sharding to find the rule that went stale
        named = ""
        if stats["replicated_paths"]:
            named = ("; replicated float leaves: "
                     + _sample_paths(stats["replicated_paths"]))
        raise ShardingCoverageError(
            f"sharded-leaf count regressed: {stats['sharded']} < floor "
            f"{min_sharded} (mesh {shape}, {stats['float_leaves']} float "
            "leaves) — the tp_sharded_leaves 108 -> 34 regression "
            "signature; check the sharding rules against the current "
            f"model structure{named}")
    return stats


def pad_batch_to(batch, multiple: int):
    """Pad the leading dim of every leaf up to `multiple` (TPU static shapes).

    Returns (padded_batch, valid_count). Needed for the final partial batch
    of an epoch: the reference simply let torch/TF handle ragged last batches
    (ResNet/pytorch/train.py:431-485); under jit we pad and mask instead.
    """
    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        return batch, 0
    n = leaves[0].shape[0]
    target = math.ceil(n / multiple) * multiple if n % multiple else n

    def _pad(x):
        if x.shape[0] == target:
            return x
        pad = [(0, target - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return np.pad(np.asarray(x), pad)

    return jax.tree_util.tree_map(_pad, batch), n
