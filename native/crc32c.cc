#include "crc32c.h"

namespace dvtpu {
namespace {

// 8 tables of 256 entries, generated at first use (slice-by-8).
struct Tables {
  uint32_t t[8][256];
  Tables() {
    constexpr uint32_t kPoly = 0x82f63b78u;  // reversed Castagnoli
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int j = 0; j < 8; ++j)
        crc = (crc >> 1) ^ ((crc & 1) ? kPoly : 0);
      t[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int k = 1; k < 8; ++k)
        t[k][i] = (t[k - 1][i] >> 8) ^ t[0][t[k - 1][i] & 0xff];
  }
};

const Tables& tables() {
  static const Tables kTables;
  return kTables;
}

}  // namespace

#if defined(__SSE4_2__)
// Hardware path: one crc32 instruction per 8 bytes (~an order of magnitude
// faster than the table path; matches google_crc32c's accelerated build).
static uint32_t Crc32cHw(uint32_t crc, const uint8_t* p, size_t len) {
  crc = ~crc;
  while (len && (reinterpret_cast<uintptr_t>(p) & 7)) {
    crc = __builtin_ia32_crc32qi(crc, *p++);
    --len;
  }
  uint64_t crc64 = crc;
  while (len >= 8) {
    uint64_t v;
    __builtin_memcpy(&v, p, 8);
    crc64 = __builtin_ia32_crc32di(crc64, v);
    p += 8;
    len -= 8;
  }
  crc = static_cast<uint32_t>(crc64);
  while (len--) crc = __builtin_ia32_crc32qi(crc, *p++);
  return ~crc;
}
#endif

uint32_t Crc32c(uint32_t crc, const void* buf, size_t len) {
#if defined(__SSE4_2__)
  return Crc32cHw(crc, static_cast<const uint8_t*>(buf), len);
#endif
  const auto& tb = tables();
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  crc = ~crc;
  // align to 8 bytes
  while (len && (reinterpret_cast<uintptr_t>(p) & 7)) {
    crc = tb.t[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
    --len;
  }
  while (len >= 8) {
    uint64_t v;
    __builtin_memcpy(&v, p, 8);
    v ^= crc;  // little-endian assumption (x86/arm64)
    crc = tb.t[7][v & 0xff] ^ tb.t[6][(v >> 8) & 0xff] ^
          tb.t[5][(v >> 16) & 0xff] ^ tb.t[4][(v >> 24) & 0xff] ^
          tb.t[3][(v >> 32) & 0xff] ^ tb.t[2][(v >> 40) & 0xff] ^
          tb.t[1][(v >> 48) & 0xff] ^ tb.t[0][(v >> 56) & 0xff];
    p += 8;
    len -= 8;
  }
  while (len--) crc = tb.t[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
  return ~crc;
}

}  // namespace dvtpu
