"""Core training-state layer.

Re-exports are LAZY (PEP 562): `core/knobs.py` (the DVT_* env-knob
registry) and `core/backend.py` are stdlib-only by contract and are
imported by pre-jax code paths — resilience/rendezvous.py arms its
lease before paying the jax import, resilience/faults.py installs
specs at import time, and the lint CLI prints the knob table without
any jax. An eager `from .train_state import TrainState` here would
drag flax/jax into all of them.
"""
from typing import TYPE_CHECKING

__all__ = [
    "TrainState",
    "create_train_state",
    "CheckpointManager",
    "MetricLogger",
    "topk_accuracy",
    "count_params",
    "model_summary",
]

_EXPORTS = {
    "TrainState": "deep_vision_tpu.core.train_state",
    "create_train_state": "deep_vision_tpu.core.train_state",
    "CheckpointManager": "deep_vision_tpu.core.checkpoint",
    "MetricLogger": "deep_vision_tpu.core.metrics",
    "topk_accuracy": "deep_vision_tpu.core.metrics",
    "count_params": "deep_vision_tpu.core.summary",
    "model_summary": "deep_vision_tpu.core.summary",
}

if TYPE_CHECKING:  # static analyzers see the eager imports
    from deep_vision_tpu.core.checkpoint import CheckpointManager  # noqa: F401
    from deep_vision_tpu.core.metrics import (  # noqa: F401
        MetricLogger,
        topk_accuracy,
    )
    from deep_vision_tpu.core.summary import (  # noqa: F401
        count_params,
        model_summary,
    )
    from deep_vision_tpu.core.train_state import (  # noqa: F401
        TrainState,
        create_train_state,
    )


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(__all__))
