"""Multi-host distributed runtime: initialization, global mesh, host sync.

The reference advertises but never ships multi-host training (`train_dist.py`
is referenced at ResNet/pytorch/README.md:15 and absent — SURVEY.md §2.9);
its real distributed story is single-host NCCL via MirroredStrategy
(YOLO/tensorflow/train.py:281). The TPU-native equivalent is radically
simpler: every host runs the SAME SPMD program, `jax.distributed.initialize`
wires the cluster, the mesh spans all hosts' devices, and XLA routes
collectives over ICI within a slice and DCN across slices. There is no
NCCL/MPI code to write — the comm backend IS the mesh + partitioner.

Elastic overlay (resilience/rendezvous.py): with a generation-numbered
world view installed (`install_world`), every topology read here —
`process_count` / `process_index` / `host_shard` / `per_host_batch_size`
— routes through the CURRENT generation instead of a `jax.process_count()`
frozen at init, and every barrier/agree (`sync_hosts` / `agree_flag` /
`PreemptionGuard.agreed`) becomes deadline-bounded and lease-checked: a
dead peer yields a typed `HostLostError` within the heartbeat deadline
instead of an indefinite collective hang. Without a rendezvous, the raw
jax collectives still get a deadline (`DVT_COLLECTIVE_DEADLINE_S`,
default 600s) via a worker-thread join — no barrier path in this module
can block unboundedly.
"""
from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

import jax
import numpy as np

from deep_vision_tpu.core import knobs
from deep_vision_tpu.parallel.mesh import MeshSpec, create_mesh
from deep_vision_tpu.resilience.rendezvous import HostLostError, WorldView

#: ceiling for the raw-jax-collective fallback path (no rendezvous
#: installed): a barrier blocked past this is declared a lost peer. The
#: rendezvous path detects in ~a lease (seconds); this is the backstop.
DEFAULT_COLLECTIVE_DEADLINE_S = knobs.get_float(
    "DVT_COLLECTIVE_DEADLINE_S")

# -- the installable world view (resilience/rendezvous.py) --------------------

_WORLD: Optional[WorldView] = None
_RDZV = None  # the Rendezvous backing barriers/agree, when elastic


def install_world(view: WorldView, rendezvous=None) -> None:
    """Adopt a rendezvous generation as THE topology: reads route through
    it and, when `rendezvous` is given, barriers/agree run over its
    lease-checked file protocol instead of jax collectives (which cannot
    name a dead peer, only hang on it)."""
    global _WORLD, _RDZV
    _WORLD = view
    _RDZV = rendezvous


def installed_world() -> Optional[WorldView]:
    return _WORLD


def clear_world() -> None:
    global _WORLD, _RDZV
    _WORLD = None
    _RDZV = None


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Wire this host into the cluster (idempotent; no-op single-process).

    With no args, reads the standard env (JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID, or the TPU metadata server on Cloud
    TPU pods where initialize() autodetects everything).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "0")) or None
    if process_id is None:
        pid = os.environ.get("JAX_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    if coordinator_address is None and num_processes in (None, 1):
        return  # single host, nothing to wire
    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _enable_cpu_collectives() -> None:
    """Multi-process collectives on the CPU backend need the gloo
    transport (newer jax: a config flag; without it every cross-process
    psum dies with 'Multiprocess computations aren't implemented on the
    CPU backend'). Must run before the backend initializes; harmless
    no-op on TPU and on jax builds without the flag."""
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    try:
        # gloo shares one context across a process's in-flight
        # computations: async CPU dispatch can overlap two executions
        # and interleave their collectives on the same TCP pair, which
        # gloo answers with a fatal preamble-size EnforceNotMet
        # (observed flakily in the host smoke). Serialize dispatch —
        # this is the CPU test/simulation path, not a perf surface.
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    except Exception:
        pass


def initialize_from_world(view: WorldView) -> None:
    """`jax.distributed.initialize` parameterized by a rendezvous
    generation: the view's coordinator address, world size, and this
    host's dense rank. The re-entry half of an elastic resize — a
    re-exec'd survivor calls this with the g+1 view and lands in a
    fresh, correctly-sized distributed world."""
    if view.world_size == 1:
        return  # a world of one needs no coordinator
    if view.coordinator is None:
        raise ValueError(
            f"generation {view.generation} record carries no coordinator "
            "address — cannot initialize jax.distributed")
    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=view.coordinator,
        num_processes=view.world_size,
        process_id=view.rank,
    )


def global_mesh(data: int = -1, model: int = 1):
    """Mesh over every device in the cluster (all hosts).

    Device order from `jax.devices()` keeps each host's devices contiguous,
    so a (data, model) reshape puts the model axis inside a host whenever
    model <= devices-per-host — TP collectives ride ICI, only DP gradient
    reduction crosses DCN (the layout recipe from the scaling playbook).
    """
    return create_mesh(MeshSpec(data=data, model=model), devices=jax.devices())


def process_count() -> int:
    """World size: the installed rendezvous generation's when elastic,
    else jax's (frozen at init — the fixed-world assumption the elastic
    overlay exists to remove)."""
    if _WORLD is not None:
        return _WORLD.world_size
    return jax.process_count()


def process_index() -> int:
    """This host's dense rank in the current generation (elastic) or
    jax's process index (static)."""
    if _WORLD is not None:
        return _WORLD.rank
    return jax.process_index()


def is_primary() -> bool:
    """True on the host that should write checkpoints/logs (rank 0 of
    the current generation)."""
    return process_index() == 0


def host_shard() -> tuple[int, int]:
    """(shard_index, num_shards) for host-sharded input pipelines: each host
    reads files[shard_index::num_shards] (records.record_iterator contract).
    Generation-aware: after an N→M resize the assignment re-derives over
    the new host set — disjoint and covering at every world size
    (tests/test_rendezvous.py proves the property)."""
    return process_index(), process_count()


def _bounded_collective(fn, name: str, deadline_s: Optional[float]):
    """Run a jax collective with a deadline: the op blocks in C++ when a
    peer is dead (BENCH_r04's failure shape, at the host layer), so the
    only honest bound is a worker-thread join — on timeout the orphaned
    thread stays wedged and the caller gets the typed `HostLostError`
    the supervision layer turns into a re-rendezvous."""
    deadline_s = (DEFAULT_COLLECTIVE_DEADLINE_S
                  if deadline_s is None else float(deadline_s))
    out: dict = {}

    def run():
        try:
            out["value"] = fn()
        except BaseException as e:
            out["exc"] = e

    t = threading.Thread(target=run, daemon=True,
                         name=f"collective-{name}")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        raise HostLostError(
            None, _WORLD.generation if _WORLD is not None else -1,
            detail=f"collective {name!r} blocked past its "
                   f"{deadline_s:.0f}s deadline (dead peer?)")
    if "exc" in out:
        raise out["exc"]
    return out.get("value")


def sync_hosts(name: str = "barrier",
               deadline_s: Optional[float] = None) -> None:
    """Cross-host barrier, deadline-bounded.

    Elastic (rendezvous installed): a lease-checked file barrier — a
    dead peer raises `HostLostError` within the heartbeat deadline, and
    no jax collective (which could wedge in C++) is involved at all.
    Static: the real all-device collective rendezvous, bounded by
    `deadline_s` (default `DVT_COLLECTIVE_DEADLINE_S`)."""
    if process_count() == 1:
        return
    if _RDZV is not None:
        _RDZV.barrier(name, timeout_s=(deadline_s if deadline_s is not None
                                       else DEFAULT_COLLECTIVE_DEADLINE_S))
        return

    def op():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)

    _bounded_collective(op, name, deadline_s)


def agree_flag(local_flag: bool,
               deadline_s: Optional[float] = None) -> bool:
    """Global OR of a per-host boolean (True if ANY host raised it).

    The preemption-consensus primitive (train/trainer.py): SIGTERM lands on
    hosts at different instants; every host calls this at the same step
    boundary, the allgather rendezvouses them, and all act on the same
    answer — no host enters a checkpoint collective while another enters
    the next step's all-reduce. Single-process: returns the flag as-is.
    Deadline-bounded like `sync_hosts`: a dead peer is a typed
    `HostLostError`, never an indefinite hang."""
    if process_count() == 1:
        return bool(local_flag)
    if _RDZV is not None:
        return _RDZV.agree(
            "agree_flag", bool(local_flag),
            timeout_s=(deadline_s if deadline_s is not None
                       else DEFAULT_COLLECTIVE_DEADLINE_S))

    def op():
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([bool(local_flag)])
        )
        return bool(np.any(flags))

    return bool(_bounded_collective(op, "agree_flag", deadline_s))


class PreemptionGuard:
    """SIGTERM → a cross-host-consistent "stop now" signal.

    The context manager installs a SIGTERM handler (main thread only; the
    previous handler is restored on exit). `agreed()` is the ONLY correct
    way to act on the flag in multi-host runs: hosts receive SIGTERM at
    different instants, and a host acting on its local flag alone would
    enter a checkpoint collective while another enters the next step's
    all-reduce — distributed deadlock. `agreed(step=...)` polls a
    cross-host OR (`agree_flag`) when `step % poll_every == 0` — the
    optimizer step is globally consistent (it advances in the SPMD train
    step every host runs), so hosts rendezvous at the same boundary even if
    they make different numbers of agreed() calls overall (uneven data
    shards, an eval iterator ending early on one host). It also polls
    whenever `force=True` (epoch/eval boundaries). The agreed answer is
    sticky. Single-process: returns the local flag directly, no collectives.

    Callers that cannot supply a step may omit it, falling back to a local
    call counter — that cadence is only deadlock-free if EVERY host makes
    the same number of agreed() calls, which the caller must then guarantee
    (one call per jitted step, identical batch counts via drop_remainder
    sharded loading).

    `poll_every` trades detection latency for hot-loop sync: SIGTERM gives
    ~30s of grace, so polling every 10 steps costs nothing in practice
    while keeping the train loop free of a per-step host-blocking
    allgather.
    """

    def __init__(self, poll_every: int = 10):
        self.poll_every = max(1, int(poll_every))
        self.requested = False
        self._agreed = False
        self._calls = 0
        self._prev_handler = None

    def _on_sigterm(self, signum, frame):
        self.requested = True
        # the flight recorder's preemption bundle: SIGTERM gives ~30s of
        # grace, so dumping NOW (not at the eventual consensus boundary)
        # guarantees the postmortem exists even if the graceful path never
        # completes before the VM is reclaimed. The dump runs on a daemon
        # THREAD, never in signal context: the handler interrupts the main
        # thread wherever it is — possibly inside the journal's or
        # recorder's non-reentrant locks — and a dump here would re-acquire
        # them and self-deadlock the very protocol it serves (the import
        # below would similarly contend on the import lock). The thread
        # simply waits until the handler returns and the lock holder
        # resumes.
        try:
            import threading

            threading.Thread(target=self._preempt_dump,
                             name="flight-preempt-dump",
                             daemon=True).start()
        except Exception:
            pass  # a failed dump must not break the preemption protocol

    @staticmethod
    def _preempt_dump() -> None:
        try:
            from deep_vision_tpu.obs import flight

            flight.emergency_dump("preempt")
        except Exception:
            pass

    def __enter__(self):
        import signal
        import threading

        if threading.current_thread() is threading.main_thread():
            self._prev_handler = signal.signal(
                signal.SIGTERM, self._on_sigterm
            )
        return self

    def __exit__(self, *exc):
        import signal

        if self._prev_handler is not None:
            signal.signal(signal.SIGTERM, self._prev_handler)
            self._prev_handler = None
        return False

    def agreed(self, *, step: Optional[int] = None, force: bool = False) -> bool:
        if self._agreed:
            return True
        if process_count() == 1:  # generation-aware (a 2-host world that
            # shrank to 1 must stop holding consensus with a ghost)
            self._agreed = self.requested
            return self._agreed
        if step is not None:
            due = int(step) % self.poll_every == 0
        else:
            self._calls += 1
            due = self._calls % self.poll_every == 0
        if force or due:
            self._agreed = agree_flag(self.requested)
        return self._agreed


def aggregate_obs(journal_path: str, out_path: Optional[str] = None,
                  gap_ms: float = 25.0) -> Optional[str]:
    """Primary-host end-of-run merge of the per-host journals.

    Assumes the standard Cloud TPU pod layout where every host writes its
    `<journal_path>.pN` into the same shared run directory (GCS/NFS). All
    hosts rendezvous at a barrier (so every follower's file is complete),
    then process 0 merges them into `<journal_path>.merged` with
    cross-host straggler detection (obs/merge.py). Returns the merged
    path on the primary, None elsewhere and in single-process runs.
    """
    if process_count() == 1:
        return None
    sync_hosts("obs_merge")
    if not is_primary():
        return None
    import glob as _g

    paths = sorted(_g.glob(journal_path + ".p*"))
    if not paths:
        return None
    from deep_vision_tpu.obs.merge import merge_journal_files

    out = out_path or journal_path + ".merged"
    merge_journal_files(paths, out, gap_ms=gap_ms)
    return out


def per_host_batch_size(global_batch_size: int) -> int:
    """Rows this host must feed per step (global batch / host count); the
    global-batch contract mirrors `batch * num_replicas` at
    YOLO/tensorflow/train.py:282 but spans hosts. Generation-aware: a
    3→2 resize re-derives this from the new world (the global batch is
    the training contract; the per-host share is topology weather)."""
    n = process_count()
    if global_batch_size % n:
        raise ValueError(f"global batch {global_batch_size} not divisible by {n} hosts")
    return global_batch_size // n


def form_global_array(local_batch, mesh, ndim: Optional[int] = None):
    """Assemble per-host numpy rows into one globally-sharded jax.Array.

    Each host passes only ITS rows; `make_array_from_process_local_data`
    stitches them into the global batch laid out over the mesh's data axis —
    the multi-host device_put (single-host path stays `shard_batch`).
    """
    from deep_vision_tpu.parallel.mesh import data_sharding

    def _make(x):
        x = np.asarray(x)
        sharding = data_sharding(mesh, x.ndim)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(_make, local_batch)
