"""LeNet-5 (LeCun 1998).

Parity target: LeNet/pytorch/models/lenet5.py (tanh activations, average
pooling, 32x32x1 input, C1=6/C3=16/C5=120 convs, F6=84 dense, 10-way head;
lenet5.py:24-57) and the Keras twin LeNet/tensorflow/models/lenet5.py:7-34.
NHWC, logits output (softmax lives in the loss).
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from deep_vision_tpu.models import register_model


class LeNet5(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: (B, 32, 32, 1)
        x = nn.Conv(6, (5, 5), padding="VALID")(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID")(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(120, (5, 5), padding="VALID")(x)
        x = nn.tanh(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(84)(x)
        x = nn.tanh(x)
        return nn.Dense(self.num_classes)(x)


@register_model("lenet5")
def lenet5(num_classes: int = 10, **_):
    return LeNet5(num_classes=num_classes)
