"""distlint (lint/distlint.py DV201-DV205) + core/knobs.py + the
sharding-table semantic checker (tools/shard_check.py) + the lint
cache: per-rule positive/negative fixtures, suppression/baseline
interplay, the repo self-lint gate, knob-registry round-trips (the
HOLD_MS garbage regression included), the DV204-backed emitter walk
that replaced the per-PR drift tests, and shard_check's
pass/fail/zero-compile contracts.
"""
from __future__ import annotations

import json
import re
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from deep_vision_tpu.core import knobs
from deep_vision_tpu.lint import lint_source
from deep_vision_tpu.lint.__main__ import main as lint_main
from deep_vision_tpu.lint.cache import LintCache, pack_fingerprint
from deep_vision_tpu.lint.rules import RULES

REPO_ROOT = Path(__file__).resolve().parents[1]


def run(src: str, **kw):
    kept, _ = lint_source(textwrap.dedent(src), "fixture.py", **kw)
    return kept


def codes(src: str, **kw):
    return [f.code for f in run(src, **kw)]


# -- DV201 hardcoded-platform-check -------------------------------------------

class TestDV201:
    def test_default_backend_comparison_flags(self):
        found = run("""
            import jax

            def pick():
                return jax.default_backend() == "tpu"
        """, select=["DV201"])
        assert [f.code for f in found] == ["DV201"]
        assert "core/backend.py" in found[0].message

    def test_device_platform_and_membership_flag(self):
        assert codes("""
            def route(device):
                if device.platform != "cpu":
                    return 1
                return platform in ("tpu", "gpu")
        """, select=["DV201"]) == ["DV201", "DV201"]

    def test_sanctioned_module_is_exempt(self):
        src = textwrap.dedent("""
            import jax

            def is_tpu():
                return jax.default_backend() == "tpu"
        """)
        kept, _ = lint_source(src, "deep_vision_tpu/core/backend.py",
                              select=["DV201"])
        assert kept == []

    def test_recording_platform_is_clean(self):
        # telemetry sites that only RECORD the platform never compare
        assert codes("""
            import jax

            def fingerprint(journal):
                journal.write("note", platform=jax.default_backend())
        """, select=["DV201"]) == []

    def test_non_platform_string_comparison_is_clean(self):
        assert codes("""
            def check(mode):
                return mode == "fast"
        """, select=["DV201"]) == []


# -- DV202 unbounded-collective -----------------------------------------------

class TestDV202:
    def test_raw_multihost_utils_flags(self):
        found = run("""
            from jax.experimental import multihost_utils

            def sync():
                multihost_utils.sync_global_devices("epoch")
        """, select=["DV202"])
        assert [f.code for f in found] == ["DV202"]
        assert "deadline-bounded" in found[0].message

    def test_bare_imported_collective_flags(self):
        assert codes("""
            from jax.experimental.multihost_utils import process_allgather

            def gather(x):
                return process_allgather(x)
        """, select=["DV202"]) == ["DV202"]

    def test_sanctioned_wrappers_are_exempt(self):
        src = textwrap.dedent("""
            from jax.experimental import multihost_utils

            def barrier(tag):
                multihost_utils.sync_global_devices(tag)
        """)
        for sanctioned in ("deep_vision_tpu/parallel/multihost.py",
                           "deep_vision_tpu/resilience/rendezvous.py"):
            kept, _ = lint_source(src, sanctioned, select=["DV202"])
            assert kept == []

    def test_device_collectives_are_not_flagged(self):
        # lax.psum inside shard_map is a different animal (deadlines
        # do not apply to device-level collectives)
        assert codes("""
            import jax

            def reduce(x):
                return jax.lax.psum(x, axis_name="data")
        """, select=["DV202"]) == []


# -- DV203 unregistered-env-knob ----------------------------------------------

class TestDV203:
    def test_raw_environ_read_flags(self):
        found = run("""
            import os

            def deadline():
                return float(os.environ.get("DVT_COLLECTIVE_DEADLINE_S",
                                            "600"))
        """, select=["DV203"])
        assert [f.code for f in found] == ["DV203"]
        assert "core/knobs.py" in found[0].message

    def test_getenv_and_subscript_flag(self):
        assert codes("""
            import os

            def reads():
                a = os.getenv("DVT_TELEMETRY")
                b = os.environ["DVT_LOCKSMITH"]
                return a, b
        """, select=["DV203"]) == ["DV203", "DV203"]

    def test_constant_routed_read_flags(self):
        # ENV_SPEC = "DVT_FAULT_SPEC" then os.environ.get(ENV_SPEC)
        assert codes("""
            import os

            ENV_SPEC = "DVT_FAULT_SPEC"

            def spec():
                return os.environ.get(ENV_SPEC)
        """, select=["DV203"]) == ["DV203"]

    def test_helper_with_unregistered_knob_flags(self):
        found = run("""
            from deep_vision_tpu.core import knobs

            def read():
                return knobs.get_int("DVT_TOTALLY_NEW_KNOB")
        """, select=["DV203"])
        assert [f.code for f in found] == ["DV203"]
        assert "DVT_TOTALLY_NEW_KNOB" in found[0].message

    def test_helper_with_registered_knob_is_clean(self):
        assert codes("""
            from deep_vision_tpu.core import knobs

            def read():
                return knobs.get_float("DVT_COLLECTIVE_DEADLINE_S")
        """, select=["DV203"]) == []

    def test_non_dvt_env_and_writes_are_clean(self):
        assert codes("""
            import os

            def other():
                os.environ["DVT_FAULT_SPEC"] = "spec"   # a WRITE
                return os.environ.get("JAX_PLATFORMS")
        """, select=["DV203"]) == []

    def test_knobs_module_itself_is_exempt(self):
        src = "import os\nV = os.environ.get('DVT_LOCKSMITH')\n"
        kept, _ = lint_source(src, "deep_vision_tpu/core/knobs.py",
                              select=["DV203"])
        assert kept == []


# -- DV204 journal-schema-drift -----------------------------------------------

class TestDV204:
    def test_unschemad_event_flags(self):
        found = run("""
            def emit(journal):
                journal.write("zz_unheard_of_event", value=1)
        """, select=["DV204"])
        assert [f.code for f in found] == ["DV204"]
        assert "--strict schema" in found[0].message

    def test_schemad_event_and_constant_routed_are_clean(self):
        assert codes("""
            EVENT_LOST = "host_lost"

            def emit(journal):
                journal.write("step", step=1)
                journal.write(EVENT_LOST, host="h", generation=0)
        """, select=["DV204"]) == []

    def test_dynamic_event_outside_wrapper_flags(self):
        found = run("""
            def emit(journal, name):
                journal.write(name, value=1)
        """, select=["DV204"])
        assert [f.code for f in found] == ["DV204"]
        assert "dynamic" in found[0].message

    def test_forwarding_wrapper_checks_call_sites(self):
        # the wrapper's own dynamic write is plumbing; its literal call
        # sites are the emitters — one good, one unschema'd
        found = run("""
            class Service:
                def __init__(self, journal):
                    self.journal = journal

                def _event(self, event, **fields):
                    if self.journal is not None:
                        self.journal.write(event, **fields)

                def work(self):
                    self._event("step", step=1)
                    self._event("zz_not_schemad", x=2)
        """, select=["DV204"])
        assert [f.code for f in found] == ["DV204"]
        assert "zz_not_schemad" in found[0].message

    def test_unrelated_write_methods_are_clean(self):
        assert codes("""
            def save(fh):
                fh.write("zz_unheard_of_event")
        """, select=["DV204"]) == []


EMITTER_FILES = sorted(
    str(p.relative_to(REPO_ROOT))
    for d in ("deep_vision_tpu", "tools")
    for p in (REPO_ROOT / d).rglob("*.py")
    if re.search(r"(journal|_journal|self)\.write\(", p.read_text())
) + ["train.py"]


@pytest.mark.parametrize("relpath", EMITTER_FILES)
def test_every_emitter_event_is_schemad(relpath):
    """The DV204-backed walk that replaced the per-PR emitter-vs-schema
    drift tests: every file that writes journal rows lints clean under
    DV204 — each literal event it emits has a check_journal --strict
    schema (suppressed sites carry an inline reason)."""
    src = (REPO_ROOT / relpath).read_text()
    kept, _ = lint_source(src, relpath, select=["DV204"])
    assert kept == [], [f.render() for f in kept]


def test_injected_unschemad_emitter_fails_lint(tmp_path, capsys):
    """The negative half: a fresh emitter with no schema FAILS the gate
    (exit 1), proving DV204 has teeth end-to-end through the CLI."""
    bad = tmp_path / "new_emitter.py"
    bad.write_text(textwrap.dedent("""
        def emit(journal):
            journal.write("zz_new_subsystem_started", pid=1)
    """))
    rc = lint_main([str(bad), "--config",
                    str(REPO_ROOT / "pyproject.toml"), "--no-cache"])
    capsys.readouterr()
    assert rc == 1


# -- DV205 pspec-table-hygiene ------------------------------------------------

class TestDV205:
    def test_curated_shape_is_clean(self):
        assert codes("""
            from deep_vision_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
            from deep_vision_tpu.parallel.shardmap import ShardingRules

            BASE = ShardingRules(
                name="base",
                rules=(
                    ("*.Dense_*.kernel", (None, MODEL_AXIS)),
                    ("*.hyperparams.*", ()),
                    ("*", ()),
                ),
            )
            EXTENDED = ShardingRules(
                name="ext",
                rules=(
                    ("*.Moe_*.kernel", (None, "model")),
                ) + BASE.rules,
            )
        """, select=["DV205"]) == []

    def test_unknown_axis_flags(self):
        found = run("""
            from deep_vision_tpu.parallel.shardmap import ShardingRules

            T = ShardingRules(
                name="t",
                rules=(
                    ("*.kernel", (None, "modle")),
                    ("*", ()),
                ),
            )
        """, select=["DV205"])
        assert [f.code for f in found] == ["DV205"]
        assert "modle" in found[0].message

    def test_missing_catch_all_flags(self):
        found = run("""
            from deep_vision_tpu.parallel.shardmap import ShardingRules

            T = ShardingRules(
                name="t",
                rules=(
                    ("*.kernel", (None, "model")),
                    ("*.bias", ("model",)),
                ),
            )
        """, select=["DV205"])
        assert [f.code for f in found] == ["DV205"]
        assert "catch-all" in found[0].message

    def test_non_literal_pattern_and_table_flag(self):
        found = run("""
            from deep_vision_tpu.parallel.shardmap import ShardingRules

            pat = make_pattern()
            T = ShardingRules(
                name="t",
                rules=(
                    (pat, (None, "model")),
                    ("*", ()),
                ),
            )
            U = ShardingRules(name="u", rules=build_rules())
        """, select=["DV205"])
        assert [f.code for f in found] == ["DV205", "DV205"]
        assert "literal" in found[0].message

    def test_unrelated_calls_are_clean(self):
        assert codes("""
            T = dict(rules=(("*", "x"),))
        """, select=["DV205"]) == []


# -- pack integration: suppression, baseline, self-lint ------------------------

DV201_SRC = """
import jax


def pick():
    return jax.default_backend() == "tpu"{pragma}
"""


def test_dv2xx_inline_suppression():
    dirty = textwrap.dedent(DV201_SRC.format(pragma=""))
    kept, dropped = lint_source(dirty, "mod.py", select=["DV201"])
    assert [f.code for f in kept] == ["DV201"]
    clean = textwrap.dedent(DV201_SRC.format(
        pragma="  # jaxlint: disable=DV201 -- fixture"))
    kept, dropped = lint_source(clean, "mod.py", select=["DV201"])
    assert kept == []
    assert [f.code for f in dropped] == ["DV201"]


def test_dv2xx_baseline_interplay(tmp_path, capsys):
    mod = tmp_path / "mod.py"
    mod.write_text(textwrap.dedent(DV201_SRC.format(pragma="")))
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.jaxlint]
        paths = ["mod.py"]
        baseline = "baseline.json"
    """))
    pp = str(tmp_path / "pyproject.toml")
    assert lint_main(["--config", pp]) == 1
    capsys.readouterr()
    assert lint_main(["--config", pp, "--write-baseline"]) == 0
    capsys.readouterr()
    assert lint_main(["--config", pp]) == 0
    # line drift must not resurrect the accepted finding
    mod.write_text("# a new leading comment\n" + mod.read_text())
    assert lint_main(["--config", pp]) == 0


def test_dv2xx_rules_registered():
    for code in ("DV201", "DV202", "DV203", "DV204", "DV205"):
        assert code in RULES
        name, severity, check, doc = RULES[code]
        assert severity == "error" and callable(check)


def test_repo_self_lint_dist_clean(capsys):
    """The shipped tree is clean under the distributed pack — true
    positives were FIXED (platform checks routed through core/backend,
    knobs onto the registry), not baselined; the committed baseline
    stays empty. The DV201-DV205 acceptance gate."""
    rc = lint_main(["--config", str(REPO_ROOT / "pyproject.toml"),
                    "--select", "DV201,DV202,DV203,DV204,DV205",
                    "--no-cache"])
    out = capsys.readouterr().out
    assert rc == 0, f"distlint found new violations:\n{out}"
    baseline = json.loads(
        (REPO_ROOT / ".jaxlint-baseline.json").read_text())
    assert baseline["findings"] == [], \
        "the committed baseline must stay empty"


def test_dv2xx_in_json_output(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(DV201_SRC.format(pragma="")))
    rc = lint_main([str(bad), "--config",
                    str(REPO_ROOT / "pyproject.toml"),
                    "--format", "json", "--no-cache"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["summary"]["failed"] is True
    assert [f["code"] for f in doc["findings"]] == ["DV201"]


# -- the knob registry ---------------------------------------------------------

class TestKnobs:
    def test_typed_round_trips(self, monkeypatch):
        monkeypatch.setenv("DVT_FLASH_MIN_TOKENS", "256")
        assert knobs.get_int("DVT_FLASH_MIN_TOKENS") == 256
        monkeypatch.setenv("DVT_COLLECTIVE_DEADLINE_S", "12.5")
        assert knobs.get_float("DVT_COLLECTIVE_DEADLINE_S") == 12.5
        monkeypatch.setenv("DVT_LOCKSMITH", "on")
        assert knobs.get_flag("DVT_LOCKSMITH") is True
        monkeypatch.setenv("DVT_LOCKSMITH", "0")
        assert knobs.get_flag("DVT_LOCKSMITH") is False
        monkeypatch.setenv("DVT_NMS_IMPL", "pallas")
        assert knobs.get_choice("DVT_NMS_IMPL") == "pallas"
        monkeypatch.setenv("DVT_EXCACHE", "/tmp/x")
        assert knobs.get_str("DVT_EXCACHE") == "/tmp/x"

    def test_unset_and_empty_mean_default(self, monkeypatch):
        monkeypatch.delenv("DVT_FLASH_MIN_TOKENS", raising=False)
        assert knobs.get_int("DVT_FLASH_MIN_TOKENS") == 1024
        monkeypatch.setenv("DVT_FLASH_MIN_TOKENS", "   ")
        assert knobs.get_int("DVT_FLASH_MIN_TOKENS") == 1024
        # explicit default overrides the registered one
        assert knobs.get_int("DVT_FLASH_MIN_TOKENS", default=None) is None

    def test_mistype_raises_naming_the_knob(self, monkeypatch):
        monkeypatch.setenv("DVT_FLASH_MIN_TOKENS", "fast")
        with pytest.raises(knobs.KnobError, match="DVT_FLASH_MIN_TOKENS"):
            knobs.get_int("DVT_FLASH_MIN_TOKENS")
        monkeypatch.setenv("DVT_NMS_IMPL", "LAX")  # no normalization
        with pytest.raises(knobs.KnobError, match="DVT_NMS_IMPL"):
            knobs.get_choice("DVT_NMS_IMPL")
        monkeypatch.setenv("DVT_PALLAS_FUSED", "maybe")
        with pytest.raises(knobs.KnobError, match="DVT_PALLAS_FUSED"):
            knobs.get_flag("DVT_PALLAS_FUSED")

    def test_unregistered_and_wrong_kind_raise(self):
        with pytest.raises(knobs.KnobError, match="not a registered"):
            knobs.get_int("DVT_NO_SUCH_KNOB")
        with pytest.raises(knobs.KnobError, match="get_float"):
            knobs.get_int("DVT_COLLECTIVE_DEADLINE_S")

    def test_locksmith_garbage_threshold_raises(self, monkeypatch):
        """The regression that motivated the registry: HOLD_MS/WAIT_MS
        used to feed float() inside a bare try/except — garbage silently
        meant 1000ms. Now arming with garbage RAISES, naming the knob."""
        from deep_vision_tpu.obs import locksmith

        monkeypatch.setenv("DVT_LOCKSMITH", "1")
        monkeypatch.setenv("DVT_LOCKSMITH_HOLD_MS", "oops")
        with pytest.raises(knobs.KnobError, match="DVT_LOCKSMITH_HOLD_MS"):
            locksmith.arm_from_env()
        monkeypatch.setenv("DVT_LOCKSMITH_HOLD_MS", "250")
        monkeypatch.setenv("DVT_LOCKSMITH_WAIT_MS", "not-a-number")
        with pytest.raises(knobs.KnobError, match="DVT_LOCKSMITH_WAIT_MS"):
            locksmith.arm_from_env()
        monkeypatch.setenv("DVT_LOCKSMITH_WAIT_MS", "250")
        san = locksmith.arm_from_env()
        try:
            assert san is not None
        finally:
            locksmith.disarm()

    def test_knobs_import_is_stdlib_only(self):
        """rendezvous/faults read knobs before paying the jax import —
        the registry must never drag jax/flax in."""
        code = ("import sys\n"
                "from deep_vision_tpu.core import knobs\n"
                "assert 'jax' not in sys.modules, 'knobs imported jax'\n"
                "assert 'flax' not in sys.modules, 'knobs imported flax'\n"
                "assert knobs.get_int('DVT_FLASH_MIN_TOKENS') == 1024\n")
        subprocess.run([sys.executable, "-c", code], check=True,
                       cwd=str(REPO_ROOT))

    def test_readme_lists_every_knob(self):
        """The README 'Environment knobs' table cannot drift from the
        registry: every registered name appears, and the table carries
        no DVT_* name the registry does not declare."""
        readme = (REPO_ROOT / "README.md").read_text()
        section = readme.split("## Environment knobs", 1)[1]
        section = section.split("\n## ", 1)[0]
        for name in knobs.KNOBS:
            assert f"`{name}`" in section, f"README is missing {name}"
        documented = set(re.findall(r"`(DVT_[A-Z0-9_]+)`", section))
        assert documented == set(knobs.KNOBS)

    def test_cli_knob_table(self, capsys):
        assert lint_main(["--knobs"]) == 0
        out = capsys.readouterr().out
        for name in knobs.KNOBS:
            assert name in out
        assert "choice(lax|pallas)" in out


# -- the incremental lint cache ------------------------------------------------

class TestLintCache:
    SRC = "import jax\n\ndef f():\n    return jax.default_backend() == 'tpu'\n"

    def test_hit_returns_identical_verdicts(self, tmp_path):
        cache = LintCache(str(tmp_path / "c"),
                          pack_fingerprint(["DV201"], root=str(REPO_ROOT)))
        kept, dropped = lint_source(self.SRC, "m.py", select=["DV201"])
        assert cache.get("m.py", self.SRC) is None  # cold
        cache.put("m.py", self.SRC, kept, dropped)
        got = cache.get("m.py", self.SRC)
        assert got is not None and got[0] == kept and got[1] == dropped
        assert cache.hits == 1 and cache.misses == 1

    def test_content_and_pack_changes_miss(self, tmp_path):
        fp = pack_fingerprint(["DV201"], root=str(REPO_ROOT))
        cache = LintCache(str(tmp_path / "c"), fp)
        cache.put("m.py", self.SRC, [], [])
        assert cache.get("m.py", self.SRC + "# edit\n") is None
        # a different enabled-rule set is a different fingerprint
        fp2 = pack_fingerprint(["DV201", "DV202"], root=str(REPO_ROOT))
        assert fp2 != fp
        assert LintCache(str(tmp_path / "c"), fp2).get(
            "m.py", self.SRC) is None

    def test_corrupt_entry_is_a_miss_not_a_crash(self, tmp_path):
        cache = LintCache(str(tmp_path / "c"),
                          pack_fingerprint(["DV201"], root=str(REPO_ROOT)))
        cache.put("m.py", self.SRC, [], [])
        entry = next(Path(str(tmp_path / "c")).iterdir())
        entry.write_text("{not json")
        assert cache.get("m.py", self.SRC) is None

    def test_cli_cache_round_trip(self, tmp_path, capsys):
        mod = tmp_path / "mod.py"
        mod.write_text(textwrap.dedent(DV201_SRC.format(pragma="")))
        (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
            [tool.jaxlint]
            paths = ["mod.py"]
            baseline = "baseline.json"
        """))
        pp = str(tmp_path / "pyproject.toml")
        assert lint_main(["--config", pp]) == 1          # cold, cached
        capsys.readouterr()
        assert (tmp_path / "artifacts" / "lint_cache").is_dir()
        assert lint_main(["--config", pp]) == 1          # warm, same rc
        capsys.readouterr()
        # the fix invalidates the entry and the gate goes green
        mod.write_text("x = 1\n")
        assert lint_main(["--config", pp]) == 0
        capsys.readouterr()
        assert lint_main(["--config", pp, "--no-cache"]) == 0


# -- shard_check: the semantic half -------------------------------------------

@pytest.fixture(scope="module")
def shard_check():
    from deep_vision_tpu.tools import shard_check as sc

    return sc


class TestShardCheck:
    def test_all_curated_tables_pass(self, shard_check):
        for family in shard_check.FAMILIES:
            report = shard_check.check_family(family)
            assert report["ok"], report
            assert report["sharded"] >= report["min_sharded"]
            assert report["errors"] == []
            assert report["dead"] == [], report["dead"]

    def test_runs_with_zero_compiles_and_zero_device_arrays(
            self, shard_check):
        """The whole audit is abstract: eval_shape over
        ShapeDtypeStruct inputs must not trigger a single backend
        compile (the stepclock monitoring counter is the proof)."""
        from deep_vision_tpu.obs.stepclock import recompile_count

        before = recompile_count()
        report = shard_check.check_family("vit")
        assert report["ok"]
        assert recompile_count() == before

    def test_gutted_table_fails_naming_the_floor(self, shard_check):
        from deep_vision_tpu.parallel.shardmap import ShardingRules

        gutted = ShardingRules(
            name="vit",
            # jaxlint: disable=DV205 -- deliberately gutted test subject
            rules=(("*", ()),),
            min_sharded=12,
        )
        report = shard_check.check_family("vit", rules=gutted)
        assert not report["ok"] and not report["floor_ok"]
        assert report["sharded"] == 0
        rendered = shard_check.render_report(report)
        assert "FAIL" in rendered and "coverage floor" in rendered

    def test_shadowed_and_dead_rules_reported(self, shard_check):
        from deep_vision_tpu.parallel.mesh import MODEL_AXIS
        from deep_vision_tpu.parallel.shardmap import ShardingRules

        table = ShardingRules(
            name="vit",
            rules=(
                ("*.kernel", (None, MODEL_AXIS)),
                # shadowed: every Dense kernel already matched above
                ("*.Dense_*.kernel", (None, MODEL_AXIS)),
                # dead: no leaf path contains 'Conv' in a ViT
                ("*.Conv_*.kernel", (None, MODEL_AXIS)),
                ("*", ()),
            ),
            min_sharded=1,
        )
        report = shard_check.check_family("vit", rules=table)
        assert "*.Dense_*.kernel" in report["shadowed"]
        assert "*.Conv_*.kernel" in report["dead"]
        # shadow/dead are report-only; the floor holds, so the table
        # passes
        assert report["ok"]

    def test_unknown_axis_is_an_error(self, shard_check):
        from deep_vision_tpu.parallel.shardmap import ShardingRules

        report = shard_check.check_family("vit", rules=ShardingRules(
            name="vit",
            # jaxlint: disable=DV205 -- deliberately bad test subject
            rules=(("*.kernel", (None, "bogus_axis")), ("*", ())),
        ))
        assert not report["ok"]
        assert any("bogus_axis" in e for e in report["errors"])

    def test_cli_pass_and_json(self, shard_check, capsys):
        assert shard_check.main([]) == 0
        out = capsys.readouterr().out
        assert out.count("PASS") == 3 and "FAIL" not in out
        assert shard_check.main(["--family", "vit",
                                 "--format", "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["failed"] is False
        assert doc["reports"][0]["family"] == "vit"

    def test_cli_fails_on_broken_family(self, shard_check, capsys,
                                        monkeypatch):
        from deep_vision_tpu.parallel.shardmap import (
            FAMILY_RULES,
            ShardingRules,
        )

        gutted = dict(FAMILY_RULES)
        gutted["moe"] = ShardingRules(
            name="moe",
            # jaxlint: disable=DV205 -- deliberately gutted test subject
            rules=(("*", ()),),
            min_sharded=16,
        )
        monkeypatch.setattr("deep_vision_tpu.parallel.shardmap."
                            "FAMILY_RULES", gutted)
        assert shard_check.main([]) == 1
        captured = capsys.readouterr()
        assert "shard_check[moe]: FAIL" in captured.out

    def test_preflight_rung(self, shard_check, monkeypatch):
        from deep_vision_tpu.parallel.shardmap import (
            FAMILY_RULES,
            ShardingRules,
        )
        from deep_vision_tpu.tools.preflight import check_sharding_tables

        r = check_sharding_tables()
        assert r.ok and r.name == "sharding_tables"
        assert "vit" in r.detail and "resnet" in r.detail
        gutted = dict(FAMILY_RULES)
        gutted["vit"] = ShardingRules(
            name="vit",
            # jaxlint: disable=DV205 -- deliberately gutted test subject
            rules=(("*", ()),),
            min_sharded=12,
        )
        monkeypatch.setattr("deep_vision_tpu.parallel.shardmap."
                            "FAMILY_RULES", gutted)
        r = check_sharding_tables()
        assert not r.ok and "vit" in r.detail
