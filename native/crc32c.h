// crc32c (Castagnoli) — software slice-by-8 implementation.
//
// Needed by the record reader to verify TFRecord-framing checksums
// (data/records.py is the Python twin; format docs there). SSE4.2 hardware
// path when the Makefile enables it (x86_64), slice-by-8 table fallback
// otherwise.
#pragma once

#include <cstddef>
#include <cstdint>

namespace dvtpu {

// CRC-32C of buf[0..len); crc is the running value (0 for a fresh start).
uint32_t Crc32c(uint32_t crc, const void* buf, size_t len);

// TFRecord masking: rotate right 15 + magic delta.
inline uint32_t MaskedCrc32c(const void* buf, size_t len) {
  constexpr uint32_t kMaskDelta = 0xa282ead8ul;
  uint32_t crc = Crc32c(0, buf, len);
  return ((crc >> 15) | (crc << 17)) + kMaskDelta;
}

}  // namespace dvtpu
