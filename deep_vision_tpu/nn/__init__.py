from deep_vision_tpu.nn.layers import (
    ConvBN,
    DepthwiseSeparableConv,
    LocalResponseNorm,
    channel_shuffle,
    global_avg_pool,
    INITIALIZERS,
)
