"""Model summary: parameter table + totals for any registered model.

The analog of `torchsummary.summary(net, (3,224,224))` at
ResNet/pytorch/train.py:350 and `model.summary()` at
YOLO/tensorflow/train.py:297, written against flax variables directly so it
needs no extra dependency and works for every module in the zoo (including
multi-output models whose apply signature torchsummary could not handle).
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np


def count_params(tree: Any) -> int:
    """Total element count over a params (or any array) pytree."""
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


def param_bytes(tree: Any) -> int:
    return sum(
        int(np.prod(x.shape)) * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(tree)
    )


def _rows(tree: Any, prefix: Tuple[str, ...] = ()) -> Sequence[tuple]:
    """Flatten a nested variables dict to (path, shape, count) rows."""
    rows = []
    if isinstance(tree, dict):
        for key in tree:
            rows.extend(_rows(tree[key], prefix + (str(key),)))
    else:
        rows.append(("/".join(prefix), tuple(tree.shape), int(np.prod(tree.shape))))
    return rows


def model_summary(
    model,
    sample_input,
    train: bool = False,
    rng: Optional[jax.Array] = None,
    init_kwargs: Optional[dict] = None,
    max_rows: Optional[int] = None,
) -> str:
    """Build the summary table string (init runs abstractly: no FLOPs, no
    device memory — usable for ResNet-152-sized models on any host)."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    args = sample_input if isinstance(sample_input, tuple) else (sample_input,)
    kwargs = dict(init_kwargs or {})
    kwargs.setdefault("train", train)

    def init():
        try:
            return model.init({"params": rng, "dropout": rng}, *args, **kwargs)
        except TypeError as e:
            # retry ONLY for modules without a `train` kwarg (e.g. GAN
            # generators); any other TypeError is a real caller error
            if "train" not in str(e) or "train" not in kwargs:
                raise
            kwargs.pop("train", None)
            # jaxlint: disable=DV002 -- shape-only retry under jax.eval_shape: the try-arm never executed, and no randomness materializes from either key use
            return model.init({"params": rng, "dropout": rng}, *args, **kwargs)

    variables = jax.eval_shape(init)
    params = variables.get("params", {})
    batch_stats = variables.get("batch_stats", {})

    rows = _rows(params)
    name_w = max([len(r[0]) for r in rows] + [len("parameter")])
    shape_w = max([len(str(r[1])) for r in rows] + [len("shape")])
    lines = [
        f"{'parameter':<{name_w}}  {'shape':<{shape_w}}  count",
        "-" * (name_w + shape_w + 12),
    ]
    shown = rows if max_rows is None else rows[:max_rows]
    for path, shape, count in shown:
        lines.append(f"{path:<{name_w}}  {str(shape):<{shape_w}}  {count:,}")
    if max_rows is not None and len(rows) > max_rows:
        lines.append(f"... {len(rows) - max_rows} more")
    n_params = count_params(params)
    n_stats = count_params(batch_stats)
    lines += [
        "-" * (name_w + shape_w + 12),
        f"trainable params: {n_params:,} "
        f"({param_bytes(params) / 1e6:.1f} MB)",
        f"batch-norm stats: {n_stats:,}",
        f"total: {n_params + n_stats:,}",
    ]
    return "\n".join(lines)
