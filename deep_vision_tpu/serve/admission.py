"""Admission control and load shedding: overload degrades by policy.

An overloaded server without admission control fails by latency
collapse — every queue grows without bound, every request eventually
answers, and the p99 quietly becomes the timeout. The production
posture is the opposite: decide AT THE FRONT DOOR whether a request can
be served within SLO, and reject the rest immediately (reject-newest:
the requests already queued are the ones closest to their deadline, so
the newcomer is the cheapest to turn away). A shed request costs one
exception and one counter; an admitted request carries an implicit
promise that its latency tail is defensible.

Two independent budgets, both per model:

- **bounded queue**: `max_queue_depth` caps requests in flight (accepted
  but unresolved) per model across the pool. The cap is the latency
  bound in disguise: depth x batch service time ~= worst-case queue
  wait. Reason: `queue_full`.
- **token bucket**: `rate_per_s` + `burst` cap the sustained admission
  rate while allowing short bursts. Reason: `rate_limited`.

A draining pool sheds everything with reason `draining` — shutdown is
an overload of size infinity.

Every shed emits a typed `serve_shed` journal event and bumps
`serve_shed_total{model,reason}` (serve/slo.py), so the offered-vs-
admitted gap is first-class in `SLOTracker.report()` and
tools/obs_report.py — shed traffic can never silently flatter the p99.
Clients see `ShedError` synchronously from `ReplicaPool.submit` (no
Future is created for a shed request: backpressure must be cheap).
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from deep_vision_tpu.obs import locksmith
from deep_vision_tpu.serve.engine import ServeError
from deep_vision_tpu.serve.slo import SHED_REASONS


class ShedError(ServeError):
    """Request rejected by admission control; carries the shed reason."""

    def __init__(self, model: str, reason: str):
        super().__init__(f"request for {model!r} shed: {reason}")
        self.model = model
        self.reason = reason


class TokenBucket:
    """Classic token bucket: `burst` capacity, `rate_per_s` refill.

    `take()` consumes one token if available. Time is injectable so
    tests (and the seeded fleet-smoke arrival pattern) are exact.
    """

    def __init__(self, rate_per_s: float, burst: int,
                 clock: Callable[[], float] = time.monotonic):
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        if rate_per_s < 0:
            raise ValueError(f"rate_per_s must be >= 0, got {rate_per_s}")
        self.rate_per_s = float(rate_per_s)
        self.burst = int(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t = clock()

    def take(self) -> bool:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate_per_s)
        self._t = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class AdmissionController:
    """Per-model admission verdicts for a ReplicaPool front door.

    `admit(model, queue_depth)` returns None (admitted) or a shed reason
    from `slo.SHED_REASONS`. The queue bound is checked before the rate
    budget: a full queue means the pool is already beyond its latency
    promise, so spending a token on a request that would be shed anyway
    would let a burst of queue_full sheds eat the budget of the traffic
    that CAN be served.

    Thread-safe: one lock guards the per-model buckets (the pool calls
    admit from every client thread).
    """

    def __init__(self, max_queue_depth: int = 64,
                 rate_per_s: Optional[float] = None, burst: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        if max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}")
        self.max_queue_depth = int(max_queue_depth)
        self.rate_per_s = rate_per_s
        self.burst = int(burst if burst is not None
                         else max(1, int(rate_per_s or 1)))
        self._clock = clock
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = locksmith.lock("serve.admission")
        self.draining = False

    def _bucket(self, model: str) -> Optional[TokenBucket]:
        if self.rate_per_s is None:
            return None
        b = self._buckets.get(model)
        if b is None:
            b = TokenBucket(self.rate_per_s, self.burst, clock=self._clock)
            self._buckets[model] = b
        return b

    def admit(self, model: str, queue_depth: int) -> Optional[str]:
        """None = admitted; otherwise the shed reason (SHED_REASONS)."""
        with self._lock:
            if self.draining:
                return "draining"
            if queue_depth >= self.max_queue_depth:
                return "queue_full"
            bucket = self._bucket(model)
            if bucket is not None and not bucket.take():
                return "rate_limited"
            return None

    def start_draining(self) -> None:
        """Every subsequent request sheds with reason `draining`."""
        with self._lock:
            self.draining = True


assert set(SHED_REASONS) == {"queue_full", "rate_limited", "draining"}, \
    "admission reasons and slo.SHED_REASONS must stay in sync"
