"""Unified telemetry: metrics registry, run journal, step-time breakdown,
span tracing, and the training health monitor.

The observability layer every perf PR reports through (SURVEY.md §2.7
records the reference's instrumentation as one examples/sec print):

- `registry`: counters / gauges / log-scale histograms, exported as
  Prometheus text format or JSONL snapshots (`Registry`, `get_registry`).
- `journal`: append-only JSONL of typed run events — manifest, steps,
  evals, checkpoints, health, crash/exit markers (`RunJournal`,
  `read_journal`).
- `stepclock`: host data-wait vs dispatch vs device-compute breakdown
  with periodic `block_until_ready` fences, plus recompile and HBM
  tracking (`StepClock`, `recompile_count`, `hbm_bytes_in_use`).
- `trace`: Chrome trace-event spans across the data pipeline, trainers,
  and inference — *where* the time went (`Tracer`, `span`, `set_tracer`).
- `health`: NaN/Inf guard with warn/skip_step/abort policies, rolling
  z-score divergence detection, and a hang watchdog that dumps thread
  stacks — *why* the run died (`HealthMonitor`, `TrainingHealthError`).

All file writers are process-0-only under `jax.process_index()`; metric
*collection* runs on every host so counters stay meaningful if a
follower is later asked to dump state.
"""
from deep_vision_tpu.obs.health import (
    HealthMonitor,
    TrainingHealthError,
    dump_all_stacks,
)
from deep_vision_tpu.obs.journal import RunJournal, read_journal
from deep_vision_tpu.obs.trace import (
    Tracer,
    get_tracer,
    set_tracer,
    span,
    trace_event,
    traced,
)
from deep_vision_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    is_primary_host,
)
from deep_vision_tpu.obs.stepclock import (
    StepClock,
    hbm_bytes_in_use,
    recompile_count,
)

__all__ = [
    "Counter",
    "Gauge",
    "HealthMonitor",
    "Histogram",
    "Registry",
    "RunJournal",
    "StepClock",
    "Tracer",
    "TrainingHealthError",
    "dump_all_stacks",
    "get_registry",
    "get_tracer",
    "hbm_bytes_in_use",
    "is_primary_host",
    "read_journal",
    "recompile_count",
    "set_tracer",
    "span",
    "trace_event",
    "traced",
]
