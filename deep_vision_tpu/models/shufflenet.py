"""ShuffleNet V1 (Zhang 2017): group conv + channel shuffle.

The reference never implemented this — ShuffleNet/pytorch/models/shufflenet_v1.py
is a 0-byte file and its train.py lacks the config (SURVEY.md §2.9) — so this
is written from the paper (arch table 1, g=3 default, scale factor s).

ShuffleNet unit: 1x1 group conv -> channel shuffle -> 3x3 depthwise ->
1x1 group conv, with an avg-pool + concat shortcut for stride-2 units.
"""
from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from deep_vision_tpu.models import register_model
from deep_vision_tpu.nn.layers import ConvBN, channel_shuffle, global_avg_pool

# output channels per stage for each group count (paper table 1)
_STAGE_CH = {1: (144, 288, 576), 2: (200, 400, 800), 3: (240, 480, 960),
             4: (272, 544, 1088), 8: (384, 768, 1536)}
_STAGE_REPEATS = (4, 8, 4)


class ShuffleUnit(nn.Module):
    features: int
    groups: int
    stride: int = 1
    first_stage: bool = False  # no group conv on the very first 1x1 (paper sec 3.2)

    @nn.compact
    def __call__(self, x, train: bool = True):
        in_ch = x.shape[-1]
        bottleneck = self.features // 4
        out_ch = self.features - in_ch if self.stride == 2 else self.features
        g = 1 if self.first_stage else self.groups

        y = ConvBN(bottleneck, (1, 1), groups=g)(x, train)
        y = channel_shuffle(y, g) if g > 1 else y
        y = ConvBN(bottleneck, (3, 3), strides=(self.stride, self.stride),
                   groups=bottleneck, act=None)(y, train)
        y = ConvBN(out_ch, (1, 1), groups=self.groups, act=None)(y, train)
        if self.stride == 2:
            shortcut = nn.avg_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            return nn.relu(jnp.concatenate([shortcut, y], axis=-1))
        return nn.relu(x + y)


class ShuffleNetV1(nn.Module):
    num_classes: int = 1000
    groups: int = 3
    scale: float = 1.0

    @nn.compact
    def __call__(self, x, train: bool = True):
        stage_ch = [max(8, int(c * self.scale)) for c in _STAGE_CH[self.groups]]
        x = ConvBN(24, (3, 3), strides=(2, 2))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, (ch, repeats) in enumerate(zip(stage_ch, _STAGE_REPEATS)):
            x = ShuffleUnit(ch, self.groups, stride=2,
                            first_stage=(stage == 0))(x, train)
            for _ in range(repeats - 1):
                x = ShuffleUnit(ch, self.groups)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register_model("shufflenet1")
def shufflenet_v1(num_classes: int = 1000, groups: int = 3, scale: float = 1.0, **_):
    return ShuffleNetV1(num_classes=num_classes, groups=groups, scale=scale)
