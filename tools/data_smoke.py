"""Data-plane smoke: deterministic kill/resume + the shared dataset service.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/data_smoke.py \
        [--workdir artifacts/data_smoke]

The CI teeth behind the production data plane (`make data-smoke`), the
way chaos-smoke is the teeth behind resilience/ and serve-smoke behind
serve/. Two phase groups:

  1. deterministic resume (data/snapshot.py e2e): three record-backed
     LeNet CPU trains through the REAL Trainer + CheckpointManager +
     crc32c sidecar, each batch content-hashed to a file as it is
     consumed:
       A  uninterrupted reference (3 epochs);
       B1 the same run SIGKILLed mid-epoch-2 by an injected
          `data.read:crash` fault (a real kill -9, no atexit);
       B2 resume from the sidecar (`-c`-style restore through
          Trainer.resume + DataLoader.load_state_dict).
     Contracts: B1's hash prefix is byte-identical to A's (the stream
     is deterministic), B2 journals a strict-valid `data_resume`
     {verdict=restored} event, and B2's post-resume hash sequence is
     byte-identical to A's from the same offset — a kill/resume
     produces the batch stream the uninterrupted run would have, no
     silent re-visits, with the bad-record-budget spend carried over.

  2. shared service (data/service.py): one DataService worker pool,
     TWO concurrent consumers — a jitted-SGD "trainer" client and a
     jitted-forward "eval" client — sharing the stream with ZERO
     recompiles after each consumer's first step and ZERO starvation;
     an env-inherited `data.service:crash` kills a real worker process
     mid-stream (absorbed: typed data_worker_lost/recovered, stream
     uninterrupted, no client errors); an injected `data.service`
     io_error at the frame boundary drops one connection (absorbed:
     client reconnects under the retry policy, counted + journaled);
     journals pass `check_journal --strict`; `obs_report` renders the
     data-plane section.

chaos_run.py imports `phase_resume_determinism` as its
deterministic-resume phase, so the chaos gate carries these contracts
too.

Exit 0 = every contract held; 1 = broken.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
from typing import List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

SCHEMA = "data_smoke"
EPOCHS = 3
RECORDS_PER_SHARD = 80
SHARDS = 2
BATCH = 16
BPE = (RECORDS_PER_SHARD * SHARDS) // BATCH  # drop_remainder batches/epoch
# land the kill deep in epoch 2's reads: the read frontier runs ~100
# records ahead of training (prefetch + shuffle buffer + in-flight
# transforms), so a kill here interrupts TRAINING mid-epoch-2, well
# clear of epoch 1's async checkpoint commit
CRASH_AT_READ = RECORDS_PER_SHARD * SHARDS * 2 + 120


def _smoke_schema(feats):
    import numpy as np

    img = np.frombuffer(feats["image/raw"][0], np.uint8).reshape(32, 32, 1)
    return {"image": img, "label": np.int32(feats["image/class/label"][0])}


def _to_float(sample, rng):
    import numpy as np

    return {"image": sample["image"].astype(np.float32) / 255.0,
            "label": sample["label"]}


def register_schema() -> None:
    from deep_vision_tpu.data import datasets

    datasets.SCHEMAS.setdefault(SCHEMA, _smoke_schema)


def write_shards(data_dir: str) -> None:
    import numpy as np

    from deep_vision_tpu.data.example_codec import encode_example
    from deep_vision_tpu.data.records import write_records

    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.RandomState(0)
    for s in range(SHARDS):
        write_records(
            os.path.join(data_dir, f"train-{s:05d}"),
            [encode_example({
                "image/raw": [rng.randint(0, 256, size=(32, 32, 1),
                                          dtype=np.uint8).tobytes()],
                "image/class/label": [i % 10],
            }) for i in range(RECORDS_PER_SHARD)],
        )


def _build_loader(data_dir: str, dead_letter: Optional[str] = None):
    from deep_vision_tpu.data.datasets import RecordDataset
    from deep_vision_tpu.data.pipeline import DataLoader
    from deep_vision_tpu.data.records import BadRecordBudget

    register_schema()
    # the budget routes reads through the tolerant reader (where the
    # data.read fault point fires per record — the kill mechanism) and
    # proves spend carryover across the resume
    budget = BadRecordBudget(max_count=50, dead_letter_path=dead_letter)
    ds = RecordDataset(os.path.join(data_dir, "train-*"), SCHEMA,
                       shuffle_shards=True, seed=3,
                       bad_record_budget=budget)
    return DataLoader(ds, BATCH, transform=_to_float, shuffle=True,
                      shuffle_buffer=64, num_workers=2, drop_remainder=True,
                      seed=5, prefetch=2, name="train")


def _hash_batch(batch) -> str:
    import numpy as np

    h = hashlib.sha1()
    for k in sorted(batch):
        h.update(np.ascontiguousarray(batch[k]).tobytes())
    return h.hexdigest()


# -- child: one real train run, batch stream hashed ---------------------------

def child_train(argv: List[str]) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", required=True)
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--journal", required=True)
    p.add_argument("--hashes", required=True)
    p.add_argument("--epochs", type=int, default=EPOCHS)
    p.add_argument("--resume", action="store_true")
    args = p.parse_args(argv)

    import jax.numpy as jnp

    from deep_vision_tpu.core import CheckpointManager
    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.obs import RunJournal
    from deep_vision_tpu.train import Trainer, build_optimizer

    from deep_vision_tpu.obs import locksmith

    journal = RunJournal(args.journal, kind="train")
    locksmith.arm_from_env(journal=journal)  # DVT_LOCKSMITH=1 children
    journal.manifest()
    loader = _build_loader(args.data_dir)
    ckpt = CheckpointManager(args.ckpt_dir, journal=journal)
    trainer = Trainer(
        get_model("lenet5", num_classes=10),
        build_optimizer("sgd", 0.05, momentum=0.9),
        classification_loss_fn,
        sample_input=jnp.zeros((BATCH, 32, 32, 1)),
        checkpoint_manager=ckpt, journal=journal, data_loader=loader,
    )
    journal.add_closer(trainer.close)

    def hashed_batches():
        # append+fsync per line: a SIGKILL keeps the consumed prefix,
        # which the parent compares byte-for-byte against the reference
        with open(args.hashes, "a") as fh:
            for b in loader:
                fh.write(_hash_batch(b) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
                yield b

    start_epoch = trainer.resume() if args.resume else 0
    trainer.fit(hashed_batches, None, epochs=args.epochs,
                start_epoch=start_epoch)
    trainer.close()
    journal.close()
    return 0


# -- parent helpers -----------------------------------------------------------

class Failures:
    def __init__(self):
        self.errors: List[str] = []

    def check(self, ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}", flush=True)
        if not ok:
            self.errors.append(what)
        return ok


def _run_child(args: List[str], log_path: str, extra_env=None,
               timeout: float = 600.0) -> int:
    env = dict(os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu",
               DVT_LOCKSMITH="1")
    env.pop("DVT_FAULT_SPEC", None)
    env.pop("DVT_FAULT_SEED", None)
    if extra_env:
        env.update(extra_env)
    with open(log_path, "w") as log:
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"] + args,
            cwd=ROOT, env=env, stdout=log, stderr=subprocess.STDOUT,
            timeout=timeout,
        ).returncode


def _read_lines(path: str) -> List[str]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [ln.strip() for ln in f if ln.strip()]


def _read_jsonl(path: str) -> List[dict]:
    out = []
    for ln in _read_lines(path):
        try:
            out.append(json.loads(ln))
        except json.JSONDecodeError:
            pass  # torn final line: the SIGKILL signature
    return out


def _strict_ok(path: str) -> bool:
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_journal.py"),
         path, "--strict"],
        cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
    ).returncode == 0


# -- phase group 1: deterministic kill/resume ---------------------------------

def phase_resume_determinism(work: str, f: Failures) -> None:
    """SIGKILL mid-epoch, resume from the sidecar, byte-identical batch
    stream (chaos_run.py runs this as its deterministic-resume phase)."""
    data_dir = os.path.join(work, "data")
    if not os.path.isdir(data_dir):
        write_shards(data_dir)

    print("resume-determinism: reference run (uninterrupted)", flush=True)
    ha = os.path.join(work, "hashes_a.txt")
    rc = _run_child(
        ["--data-dir", data_dir, "--ckpt-dir", os.path.join(work, "ckpt_a"),
         "--journal", os.path.join(work, "journal_a.jsonl"),
         "--hashes", ha, "--epochs", str(EPOCHS)],
        os.path.join(work, "run_a.log"))
    f.check(rc == 0, f"reference run completed (rc={rc})")
    A = _read_lines(ha)
    f.check(len(A) == EPOCHS * BPE,
            f"reference consumed {len(A)} == {EPOCHS}x{BPE} batches")

    print("resume-determinism: SIGKILL mid-epoch-2 via injected "
          "data.read:crash", flush=True)
    hb = os.path.join(work, "hashes_b.txt")
    jb1 = os.path.join(work, "journal_b1.jsonl")
    ckpt_b = os.path.join(work, "ckpt_b")
    rc = _run_child(
        ["--data-dir", data_dir, "--ckpt-dir", ckpt_b,
         "--journal", jb1, "--hashes", hb, "--epochs", str(EPOCHS)],
        os.path.join(work, "run_b1.log"),
        extra_env={"DVT_FAULT_SPEC": f"data.read:crash@{CRASH_AT_READ}",
                   "DVT_FAULT_SEED": "0"})
    f.check(rc == -signal.SIGKILL,
            f"run died by the injected SIGKILL mid-epoch (rc={rc})")
    B1 = _read_lines(hb)
    f.check(2 * BPE <= len(B1) < EPOCHS * BPE,
            f"kill landed mid-epoch-2 ({len(B1)} batches consumed)")
    f.check(B1 == A[:len(B1)],
            "interrupted run's batch stream is byte-identical to the "
            "reference prefix (content hashes)")

    print("resume-determinism: resume from the sidecar", flush=True)
    jb2 = os.path.join(work, "journal_b2.jsonl")
    hb2 = os.path.join(work, "hashes_b2.txt")
    rc = _run_child(
        ["--data-dir", data_dir, "--ckpt-dir", ckpt_b,
         "--journal", jb2, "--hashes", hb2, "--epochs", str(EPOCHS),
         "--resume"],
        os.path.join(work, "run_b2.log"))
    f.check(rc == 0, f"resumed run completed (rc={rc})")
    ev = _read_jsonl(jb2)
    resumes = [e for e in ev if e.get("event") == "data_resume"]
    f.check(len(resumes) == 1
            and resumes[0].get("verdict") == "restored",
            f"typed data_resume event with verdict=restored "
            f"({resumes and resumes[0].get('verdict')})")
    f.check(_strict_ok(jb2),
            "check_journal --strict accepts the resumed journal "
            "(data_resume included)")
    if not resumes:
        return
    offset = int(resumes[0]["epoch"]) * BPE + int(resumes[0]["batches"])
    B2 = _read_lines(hb2)
    f.check(B2 == A[offset:],
            f"post-resume batch sequence is byte-identical to the "
            f"uninterrupted run from offset {offset} "
            f"({len(B2)} vs {len(A) - offset} batches)")


# -- phase group 2: the shared service ----------------------------------------

def phase_service(work: str, f: Failures) -> None:
    import time

    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.data.datasets import RecordDataset
    from deep_vision_tpu.data.service import DataService, DataServiceClient
    from deep_vision_tpu.obs import RunJournal, locksmith
    from deep_vision_tpu.obs.registry import Registry
    from deep_vision_tpu.obs.stepclock import recompile_count
    from deep_vision_tpu.resilience import faults, install_spec

    data_dir = os.path.join(work, "data")
    if not os.path.isdir(data_dir):
        write_shards(data_dir)
    register_schema()
    jpath = os.path.join(work, "journal_service.jsonl")
    journal = RunJournal(jpath, kind="data_service")
    journal.manifest()
    registry = Registry()
    san = locksmith.arm(journal=journal, registry=registry)
    base_compiles = recompile_count()  # installs the listener BEFORE the
    #                                    first jit so warmup is observed

    def make_service(name: str) -> DataService:
        ds = RecordDataset(os.path.join(data_dir, "train-*"), SCHEMA,
                           shuffle_shards=True, seed=3)
        return DataService(ds, batch_size=BATCH, num_workers=2,
                           shuffle_buffer=64, seed=7, queue_depth=16,
                           worker_poll_s=0.6, name=name, journal=journal,
                           registry=registry).start()

    def warm(svc: DataService, depth: int = 8, deadline: float = 60.0):
        t0 = time.monotonic()
        while (svc._batches.qsize() < depth
               and time.monotonic() - t0 < deadline):
            time.sleep(0.05)

    # the "trainer": a jitted SGD step over the service batches; the
    # "eval": a jitted forward pass — both must compile exactly once
    @jax.jit
    def sgd(w, batch):
        x = batch["image"].reshape(BATCH, -1)
        logits = x @ w
        onehot = jax.nn.one_hot(batch["label"], 10)
        g = x.T @ (jax.nn.softmax(logits) - onehot) / BATCH
        return w - 0.1 * g

    @jax.jit
    def fwd(w, batch):
        return jnp.argmax(batch["image"].reshape(BATCH, -1) @ w, -1)

    # -- 2a: clean shared stream — zero recompiles, zero starvation ------
    print("service: 2 concurrent consumers share one clean stream",
          flush=True)
    svc = make_service("shared")
    warm(svc)
    trainer_c = DataServiceClient(svc.address, name="trainer",
                                  journal=journal, registry=registry)
    eval_c = DataServiceClient(svc.address, name="eval",
                               journal=journal, registry=registry)
    n_each = 10
    eval_err: List[BaseException] = []
    eval_compiles = 0

    def eval_consumer():
        nonlocal eval_compiles
        try:
            we = jnp.zeros((32 * 32, 10))
            for i, b in enumerate(eval_c.batches(n_each)):
                batch = {k: jnp.asarray(v) for k, v in b.items()}
                fwd(we, batch).block_until_ready()
                if i == 0:
                    eval_compiles = recompile_count()
                time.sleep(0.02)  # a realistic consumer computes between gets
        except BaseException as e:  # surfaced to the parent check
            eval_err.append(e)

    w = jnp.zeros((32 * 32, 10))
    t = threading.Thread(target=eval_consumer, daemon=True)
    t.start()
    first_train = 0
    for i, b in enumerate(trainer_c.batches(n_each)):
        batch = {k: jnp.asarray(v) for k, v in b.items()}
        w = sgd(w, batch)
        w.block_until_ready()
        if i == 0:
            first_train = recompile_count()
        time.sleep(0.02)
    t.join(timeout=120)
    f.check(not t.is_alive() and not eval_err,
            f"both consumers streamed {n_each} batches concurrently "
            + (f"(eval error: {eval_err[0]!r})" if eval_err else ""))
    warmup = max(first_train, eval_compiles)
    total = recompile_count()
    f.check(warmup > base_compiles and total <= warmup,
            f"ZERO recompiles after each consumer's first step "
            f"({total} total vs warmup {warmup}, base {base_compiles}): "
            f"every batch keeps the one compiled shape")
    starved = registry.counter("data_service_starved_total",
                               labels={"service": "shared"}).value
    f.check(starved == 0,
            f"no starvation: both consumers always found a batch ready "
            f"({int(starved)} starved gets)")
    trainer_c.close()
    eval_c.close()
    svc.close()

    # -- 2b: env-inherited worker crash — absorbed, request-scoped -------
    print("service: injected data.service worker crash -> supervised "
          "respawn", flush=True)
    os.environ[faults.ENV_SPEC] = "data.service:crash@40"
    os.environ[faults.ENV_SEED] = "0"
    try:
        svc2 = make_service("crashy")
        c2 = DataServiceClient(svc2.address, name="crash-client",
                               journal=journal, registry=registry)
        got = list(c2.batches(15))  # 240 samples: well past the crash
        f.check(len(got) == 15,
                f"stream continued across the worker death "
                f"({len(got)}/15 batches, no client error)")
        f.check(c2.reconnects == 0,
                "worker crash absorbed SERVER-side: the client never "
                "even reconnected")
        c2.close()
        svc2.close()
    finally:
        os.environ.pop(faults.ENV_SPEC, None)
        os.environ.pop(faults.ENV_SEED, None)

    # -- 2c: io_error at the frame boundary — client reconnects ----------
    print("service: injected io_error at the frame boundary -> "
          "reconnect", flush=True)
    svc3 = make_service("flaky")
    warm(svc3, depth=4)
    c3 = DataServiceClient(svc3.address, name="flaky-client",
                           journal=journal, registry=registry)
    install_spec("data.service:io_error@3", export_env=False)
    try:
        got = list(c3.batches(4))
    finally:
        install_spec(None)
    f.check(len(got) == 4 and c3.reconnects >= 1,
            f"dropped connection absorbed by reconnect "
            f"({c3.reconnects} reconnect(s), {len(got)}/4 batches)")
    c3.close()
    svc3.close()

    f.check(not san.violations(),
            "locksmith: zero lock-order violations across the service "
            "lifecycle")
    locksmith.disarm()
    journal.close()

    ev = _read_jsonl(jpath)
    lost = [e for e in ev if e.get("event") == "data_worker_lost"]
    rec = [e for e in ev if e.get("event") == "data_worker_recovered"]
    f.check(len(lost) >= 1 and len(rec) >= 1,
            f"worker death journaled as typed lost/recovered pair(s) "
            f"({len(lost)}/{len(rec)})")
    summaries = [e for e in ev if e.get("event") == "data_service"]
    f.check({s.get("role") for s in summaries} == {"server", "client"},
            "server + client data_service summaries journaled")
    f.check(_strict_ok(jpath),
            "check_journal --strict accepts the service journal")
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_report.py"),
         jpath],
        cwd=ROOT, env=dict(os.environ, PYTHONPATH=ROOT),
        stdout=subprocess.PIPE).returncode
    f.check(rc == 0, f"obs_report renders the data-plane section (rc={rc})")


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--child":
        return child_train(argv[1:])

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/data_smoke")
    args = p.parse_args(argv)
    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work)
    f = Failures()

    print("== phase 1: deterministic kill/resume (byte-identical batch "
          "stream) ==", flush=True)
    phase_resume_determinism(work, f)

    print("== phase 2: shared dataset service (2 consumers, worker "
          "crash, reconnect) ==", flush=True)
    phase_service(work, f)

    if f.errors:
        print(f"\ndata-smoke: {len(f.errors)} contract(s) BROKEN "
              f"(artifacts in {work})")
        return 1
    print(f"\ndata-smoke: all data-plane contracts held "
          f"(artifacts in {work})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
