"""Short real-hardware convergence run; records the loss curve as an artifact.

The reference commits multi-MB training logs as convergence evidence
(ResNet/pytorch/logs/resnet50-yanjiali-010919.log; "compare with other's
losses", YOLO/tensorflow/README.md:18). This is the executable equivalent
sized for CI-on-a-chip: N optimizer steps of the flagship ResNet-50 recipe
(bf16, s2d stem, SGD+momentum exactly as configs/resnet50) on a fixed
memorizable fixture, asserting the loss collapses, and writing the full curve
+ environment to artifacts/ for humans to diff between rounds.

    python -m deep_vision_tpu.tools.convergence_run [--steps 200] [--batch 64]
"""
from __future__ import annotations

import argparse
import json
import os
import time
from typing import Optional


def run(steps: int = 200, batch: int = 64, classes: int = 64,
        model_name: str = "resnet50", out_path: Optional[str] = None) -> dict:
    out_path = out_path or f"artifacts/{model_name}_tpu_convergence.json"
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.core.train_state import create_train_state
    from deep_vision_tpu.data.transforms import space_to_depth
    from deep_vision_tpu.losses.classification import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.train.optimizers import build_optimizer

    # fixed fixture: `batch` images / `classes` labels, memorizable in O(100)
    # steps — real-data ImageNet is not present in this environment, so the
    # evidence is "the full recipe optimizes on hardware", not accuracy parity
    rng = np.random.RandomState(0)
    imgs = rng.rand(batch, 112, 112, 3).astype(np.float32)
    if model_name == "resnet50":
        model = get_model("resnet50", num_classes=classes, dtype=jnp.bfloat16,
                          stem="s2d")
        tx = build_optimizer("sgd", 0.05, momentum=0.9, weight_decay=1e-4)
        sample = jnp.ones((8, 56, 56, 12), jnp.float32)
        recipe = "resnet50 (bf16, s2d stem, SGD 0.05/0.9/1e-4)"
        images = jnp.asarray(
            np.stack([space_to_depth(i) for i in imgs]), jnp.bfloat16
        )
    else:  # the attention family: AdamW recipe on raw 112px inputs
        model = get_model(model_name, num_classes=classes,
                          dtype=jnp.bfloat16)
        tx = build_optimizer("adamw", 1e-3, weight_decay=1e-4)
        sample = jnp.ones((8, 112, 112, 3), jnp.float32)
        recipe = f"{model_name} (bf16, AdamW 1e-3/1e-4)"
        images = jnp.asarray(imgs, jnp.bfloat16)
    state = create_train_state(model, tx, sample, jax.random.PRNGKey(0))

    batch_d = {
        "image": images,
        "label": jnp.asarray(np.arange(batch) % classes, jnp.int32),
    }

    def train_step(state, batch):
        def loss_fn(params):
            variables = {"params": params}
            # NB mutable=False, not []: flax returns (y, vars) for ANY list
            mutable = False
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
                mutable = ["batch_stats"]
            out = state.apply_fn(
                variables, batch["image"], train=True,
                rngs={"dropout": jax.random.fold_in(state.rng, state.step)},
                mutable=mutable)
            out, nms = out if mutable else (out, {})
            loss, _ = classification_loss_fn(out, batch)
            return loss, nms.get("batch_stats", {})

        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params)
        new_state = state.apply_gradients(grads)
        if state.batch_stats:
            new_state = new_state.replace(batch_stats=bs)
        return new_state, loss

    step = jax.jit(train_step, donate_argnums=0)
    losses = []
    t0 = time.time()
    for i in range(steps):
        state, loss = step(state, batch_d)
        if i % 10 == 0 or i == steps - 1:
            losses.append((i, float(loss)))
    wall = time.time() - t0

    dev = jax.devices()[0]
    result = {
        "model": recipe,
        "device": f"{dev.platform}:{dev.device_kind}",
        "steps": steps,
        "batch": batch,
        "classes": classes,
        "wall_seconds": round(wall, 1),
        "loss_curve": [[i, round(l, 4)] for i, l in losses],
        "first_loss": round(losses[0][1], 4),
        "final_loss": round(losses[-1][1], 4),
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--model", default="resnet50",
                   help="resnet50 | vit_s16 | vmoe_s16")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)
    out = args.out or f"artifacts/{args.model}_tpu_convergence.json"
    r = run(args.steps, args.batch, model_name=args.model, out_path=out)
    print(f"device={r['device']} first={r['first_loss']} "
          f"final={r['final_loss']} wall={r['wall_seconds']}s -> {out}")
    ok = r["final_loss"] < 0.5 * r["first_loss"]
    print("CONVERGED" if ok else "DID NOT CONVERGE")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
