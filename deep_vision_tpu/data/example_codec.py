"""Minimal tf.train.Example protobuf wire codec (no TensorFlow dependency).

The reference's entire record tooling speaks tf.train.Example
(`Datasets/VOC2007/tfrecords.py:38-95`, `ResNet/tensorflow/train.py:150-160`);
implementing the wire format directly keeps those shard files readable and
writable from this framework without importing TF on the training hosts.

Wire schema (proto3 subset):

    Example    { 1: Features }
    Features   { 1: map<string, Feature> }   // repeated map-entry messages
    Feature    { oneof: 1: BytesList, 2: FloatList, 3: Int64List }
    BytesList  { repeated 1: bytes }
    FloatList  { repeated packed 1: float }   // also accepts unpacked
    Int64List  { repeated packed 1: varint }  // also accepts unpacked

Python-side representation is a flat dict:

    {"image/encoded": [b"..."], "image/width": [416], "bbox/xmin": [0.1, 0.4]}

bytes values -> BytesList, floats -> FloatList, ints -> Int64List.
"""
from __future__ import annotations

import numbers
import struct
from typing import Dict, List, Sequence, Union

FeatureValue = Union[Sequence[bytes], Sequence[float], Sequence[int]]

_WIRE_VARINT = 0
_WIRE_I64 = 1
_WIRE_LEN = 2
_WIRE_I32 = 5


# -- varint ------------------------------------------------------------------

def _write_varint(buf: bytearray, value: int) -> None:
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _tag(field: int, wire: int) -> int:
    return (field << 3) | wire


# -- encode ------------------------------------------------------------------

def _encode_feature(values: FeatureValue) -> bytes:
    buf = bytearray()
    if not values:
        # typeless empty feature: emit an empty Int64List
        inner = b""
        _write_varint(buf, _tag(3, _WIRE_LEN))
        _write_varint(buf, len(inner))
        return bytes(buf)
    v0 = values[0]
    if isinstance(v0, (bytes, bytearray, str)):
        inner = bytearray()
        for v in values:
            if isinstance(v, str):
                v = v.encode("utf-8")
            _write_varint(inner, _tag(1, _WIRE_LEN))
            _write_varint(inner, len(v))
            inner += v
        _write_varint(buf, _tag(1, _WIRE_LEN))
    elif all(isinstance(v, numbers.Integral) for v in values):
        # every value must be integral (not just values[0]): a mixed list
        # like [0, 0.5] belongs in FloatList. numbers ABCs (not bare
        # int/float isinstance) so numpy scalars encode consistently.
        inner = bytearray()
        packed = bytearray()
        for v in values:
            _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)  # two's complement
        _write_varint(inner, _tag(1, _WIRE_LEN))
        _write_varint(inner, len(packed))
        inner += packed
        _write_varint(buf, _tag(3, _WIRE_LEN))
    elif isinstance(v0, numbers.Real):
        inner = bytearray()
        packed = struct.pack(f"<{len(values)}f", *(float(v) for v in values))
        _write_varint(inner, _tag(1, _WIRE_LEN))
        _write_varint(inner, len(packed))
        inner += packed
        _write_varint(buf, _tag(2, _WIRE_LEN))
    else:
        raise TypeError(f"unsupported feature value type {type(v0)}")
    _write_varint(buf, len(inner))
    buf += inner
    return bytes(buf)


def encode_example(features: Dict[str, FeatureValue]) -> bytes:
    """Serialize a feature dict to tf.train.Example bytes."""
    feats = bytearray()
    for key in features:  # insertion order, deterministic
        kb = key.encode("utf-8")
        fb = _encode_feature(list(features[key]))
        entry = bytearray()
        _write_varint(entry, _tag(1, _WIRE_LEN))
        _write_varint(entry, len(kb))
        entry += kb
        _write_varint(entry, _tag(2, _WIRE_LEN))
        _write_varint(entry, len(fb))
        entry += fb
        _write_varint(feats, _tag(1, _WIRE_LEN))
        _write_varint(feats, len(entry))
        feats += entry
    out = bytearray()
    _write_varint(out, _tag(1, _WIRE_LEN))
    _write_varint(out, len(feats))
    out += feats
    return bytes(out)


# -- decode ------------------------------------------------------------------

def _skip_field(data: bytes, pos: int, wire: int) -> int:
    if wire == _WIRE_VARINT:
        _, pos = _read_varint(data, pos)
    elif wire == _WIRE_I64:
        pos += 8
    elif wire == _WIRE_LEN:
        n, pos = _read_varint(data, pos)
        pos += n
    elif wire == _WIRE_I32:
        pos += 4
    else:
        raise ValueError(f"unknown wire type {wire}")
    return pos


def _decode_list(data: bytes, kind: int) -> List:
    """kind: 1 bytes, 2 float, 3 int64."""
    values: List = []
    pos = 0
    end = len(data)
    while pos < end:
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if field != 1:
            pos = _skip_field(data, pos, wire)
            continue
        if kind == 1:
            n, pos = _read_varint(data, pos)
            values.append(data[pos:pos + n])
            pos += n
        elif kind == 2:
            if wire == _WIRE_LEN:  # packed
                n, pos = _read_varint(data, pos)
                values.extend(struct.unpack(f"<{n // 4}f", data[pos:pos + n]))
                pos += n
            else:  # unpacked fixed32
                values.append(struct.unpack("<f", data[pos:pos + 4])[0])
                pos += 4
        else:
            if wire == _WIRE_LEN:  # packed
                n, pos = _read_varint(data, pos)
                stop = pos + n
                while pos < stop:
                    v, pos = _read_varint(data, pos)
                    values.append(v - (1 << 64) if v >= 1 << 63 else v)
            else:
                v, pos = _read_varint(data, pos)
                values.append(v - (1 << 64) if v >= 1 << 63 else v)
    return values


def _decode_feature(data: bytes) -> List:
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if field in (1, 2, 3) and wire == _WIRE_LEN:
            n, pos = _read_varint(data, pos)
            return _decode_list(data[pos:pos + n], field)
        pos = _skip_field(data, pos, wire)
    return []


def decode_example(data: bytes) -> Dict[str, List]:
    """Parse tf.train.Example bytes into {feature_name: list_of_values}."""
    features: Dict[str, List] = {}
    pos = 0
    # Example wrapper: find field 1 (Features)
    feats = b""
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == _WIRE_LEN:
            n, pos = _read_varint(data, pos)
            feats = data[pos:pos + n]
            pos += n
        else:
            pos = _skip_field(data, pos, wire)
    pos = 0
    while pos < len(feats):
        tag, pos = _read_varint(feats, pos)
        field, wire = tag >> 3, tag & 7
        if field != 1 or wire != _WIRE_LEN:
            pos = _skip_field(feats, pos, wire)
            continue
        n, pos = _read_varint(feats, pos)
        entry = feats[pos:pos + n]
        pos += n
        # map entry: 1 key, 2 value
        epos = 0
        key, val = "", []
        while epos < len(entry):
            etag, epos = _read_varint(entry, epos)
            efield, ewire = etag >> 3, etag & 7
            if efield == 1 and ewire == _WIRE_LEN:
                kn, epos = _read_varint(entry, epos)
                key = entry[epos:epos + kn].decode("utf-8")
                epos += kn
            elif efield == 2 and ewire == _WIRE_LEN:
                vn, epos = _read_varint(entry, epos)
                val = _decode_feature(entry[epos:epos + vn])
                epos += vn
            else:
                epos = _skip_field(entry, epos, ewire)
        features[key] = val
    return features
