from deep_vision_tpu.ops.boxes import (
    xywh_to_xyxy,
    xyxy_to_xywh,
    broadcast_iou,
    decode_yolo_boxes,
    encode_yolo_boxes,
)
from deep_vision_tpu.ops.nms import non_maximum_suppression
from deep_vision_tpu.ops.anchors import assign_anchors_to_grid, YOLO_ANCHORS, YOLO_ANCHOR_MASKS
from deep_vision_tpu.ops.heatmaps import gaussian_heatmaps, gaussian_radius
