"""Dataset readers: MNIST idx, ImageNet folder, record-backed with schemas.

Parity targets: MnistDataset's idx parser (LeNet/pytorch/data_load.py:24-48),
ImageNet2012Dataset's flattened-folder reader with filename-prefix labels
(ResNet/pytorch/data_load.py:14-69), and the Example schemas of the
reference's converters (ImageNet: build_imagenet_tfrecord.py:184+; VOC/COCO:
Datasets/VOC2007/tfrecords.py:38-95; MPII: tfrecords_mpii.py:65-84).

A Dataset is anything with __len__ + __getitem__(i) -> sample dict (the torch
Dataset contract, kept because it composes with the threaded DataLoader), or
an iterable of sample dicts for record streams.
"""
from __future__ import annotations

import io
import os
import struct
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from deep_vision_tpu.data.example_codec import decode_example
from deep_vision_tpu.data.records import expand_shards, read_records
from deep_vision_tpu.resilience import faults


def decode_image(data: bytes, channels: int = 3) -> np.ndarray:
    """JPEG/PNG bytes -> HWC uint8 RGB numpy (cv2 fast path, BGR->RGB like
    ResNet/pytorch/data_load.py:53-54; PIL fallback)."""
    try:
        import cv2

        img = cv2.imdecode(np.frombuffer(data, np.uint8), cv2.IMREAD_COLOR)
        if img is None:
            raise ValueError("cv2.imdecode failed")
        return img[:, :, ::-1].copy()  # BGR -> RGB
    except Exception:
        from PIL import Image

        img = Image.open(io.BytesIO(data))
        img = img.convert("RGB" if channels == 3 else "L")
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr


# -- MNIST idx ---------------------------------------------------------------

class MnistDataset:
    """MNIST idx-format reader (LeNet/pytorch/data_load.py:24-48).

    Unlike the reference (whole set normalized eagerly in __init__), decoding
    is lazy per item; `pad_to_32` reproduces the 28->32 zero-pad for LeNet-5.
    """

    def __init__(self, images_path: str, labels_path: str, pad_to_32: bool = True):
        self.images = self._read_idx(images_path)
        self.labels = self._read_idx(labels_path)
        assert len(self.images) == len(self.labels)
        self.pad_to_32 = pad_to_32

    @staticmethod
    def _read_idx(path: str) -> np.ndarray:
        with open(path, "rb") as f:
            data = f.read()
        zero, dtype_code, ndim = data[0] << 8 | data[1], data[2], data[3]
        assert zero == 0, f"bad idx magic in {path}"
        dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                  0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}
        shape = struct.unpack(f">{ndim}I", data[4:4 + 4 * ndim])
        arr = np.frombuffer(data, dtypes[dtype_code], offset=4 + 4 * ndim)
        return arr.reshape(shape)

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, i: int) -> dict:
        img = self.images[i]
        if img.ndim == 2:
            img = img[:, :, None]
        if self.pad_to_32 and img.shape[0] == 28:
            img = np.pad(img, ((2, 2), (2, 2), (0, 0)))
        return {"image": img, "label": np.int32(self.labels[i])}


# -- ImageNet folder ---------------------------------------------------------

class ImageFolderDataset:
    """Flattened-folder ImageNet reader: label parsed from the filename's
    synset prefix, vocab from synsets.txt (ResNet/pytorch/data_load.py:14-69).
    """

    def __init__(
        self,
        root: str,
        synsets_path: Optional[str] = None,
        extensions: Sequence[str] = (".jpeg", ".jpg", ".png"),
    ):
        self.root = root
        self.files = sorted(
            f for f in os.listdir(root)
            if f.lower().endswith(tuple(extensions))
        )
        if synsets_path:
            with open(synsets_path) as f:
                synsets = [line.strip().split()[0] for line in f if line.strip()]
        else:
            synsets = sorted({f.split("_")[0] for f in self.files})
        self.label_of = {s: i for i, s in enumerate(synsets)}

    def __len__(self) -> int:
        return len(self.files)

    def __getitem__(self, i: int) -> dict:
        name = self.files[i]
        with open(os.path.join(self.root, name), "rb") as f:
            img = decode_image(f.read())
        synset = name.split("_")[0]
        return {"image": img, "label": np.int32(self.label_of[synset])}


# -- record-backed datasets --------------------------------------------------

def imagenet_schema(feats: Dict[str, list]) -> dict:
    """9-field ImageNet Example (_parse_function at
    ResNet/tensorflow/train.py:150-160; writer build_imagenet_tfrecord.py:184+).
    Labels there are 1-based (0 is background): shift to 0-based."""
    return {
        "image": decode_image(feats["image/encoded"][0]),
        "label": np.int32(feats["image/class/label"][0] - 1),
    }


def _box_schema(feats: Dict[str, list], class_key: str) -> dict:
    n = len(feats.get("image/object/bbox/xmin", ()))
    boxes = np.zeros((n, 4), np.float32)
    if n:
        boxes[:, 0] = feats["image/object/bbox/xmin"]
        boxes[:, 1] = feats["image/object/bbox/ymin"]
        boxes[:, 2] = feats["image/object/bbox/xmax"]
        boxes[:, 3] = feats["image/object/bbox/ymax"]
    classes = np.asarray(feats.get(class_key, [0] * n), np.int32)
    return {
        "image": decode_image(feats["image/encoded"][0]),
        "boxes": boxes,
        "classes": classes,
    }


def voc_schema(feats: Dict[str, list]) -> dict:
    """Normalized-bbox VOC Example (Datasets/VOC2007/tfrecords.py:38-95)."""
    return _box_schema(feats, "image/object/class/label")


def coco_schema(feats: Dict[str, list]) -> dict:
    """COCO Example (Datasets/MSCOCO/tfrecords.py): same bbox layout."""
    return _box_schema(feats, "image/object/class/label")


def mpii_schema(feats: Dict[str, list]) -> dict:
    """MPII keypoint Example (Datasets/MPII/tfrecords_mpii.py:65-84):
    normalized joint x/y + visibility, 16 joints."""
    x = np.asarray(feats["image/person/keypoints/x"], np.float32)
    y = np.asarray(feats["image/person/keypoints/y"], np.float32)
    v = np.asarray(feats["image/person/keypoints/visibility"], np.float32)
    out = {
        "image": decode_image(feats["image/encoded"][0]),
        "keypoints": np.stack([x, y], axis=-1),
        "visibility": v,
    }
    # MPII body height / 200, for CropRoi. ALWAYS present (0.0 = unknown,
    # CropRoi falls back to the keypoint extent): a per-record key would
    # break collate(), which stacks the first sample's keys across the batch
    scale = feats.get("image/person/scale")
    out["scale"] = float(scale[0]) if scale else 0.0
    return out


def image_only_schema(feats: Dict[str, list]) -> dict:
    """Single-image Example (CycleGAN/tensorflow/tfrecords.py)."""
    return {"image": decode_image(feats["image/encoded"][0])}


SCHEMAS: Dict[str, Callable] = {
    "imagenet": imagenet_schema,
    "voc": voc_schema,
    "coco": coco_schema,
    "mpii": mpii_schema,
    "image_only": image_only_schema,
}


class RecordDataset:
    """Iterable dataset over record shards with an Example schema.

    Streams (no random access — record files are sequential by design);
    reshuffles shard order per epoch when `shuffle_shards`.

    With `bad_record_budget` (a `records.BadRecordBudget`), corrupt records
    and failing decodes are SKIPPED under the budget's bound and
    dead-lettered with file + offset instead of killing the epoch — the
    bounded-data-loss mode production runs want against bit rot. The
    budget path uses the Python tolerant reader (the native C++ reader
    keeps strict-raise parity with `read_records`).
    """

    def __init__(
        self,
        pattern,
        schema: str | Callable = "imagenet",
        shuffle_shards: bool = False,
        seed: int = 0,
        shard_index: int = 0,
        num_shards: int = 1,
        bad_record_budget=None,
    ):
        self.files = expand_shards(pattern)[shard_index::num_shards]
        self.schema = SCHEMAS[schema] if isinstance(schema, str) else schema
        self.shuffle_shards = shuffle_shards
        self.seed = seed
        self.bad_record_budget = bad_record_budget
        self._epoch = 0
        # optional snapshot.LiveCursor: updated per record read so the
        # DataLoader snapshot can report the shard read frontier
        # (data/snapshot.py); None costs one attribute check per shard
        self.cursor = None

    def set_epoch(self, epoch: int) -> None:
        """Pin the shard-reshuffle epoch (DataLoader `num_procs` mode, where
        the parent process never iterates and so never advances it)."""
        self._epoch = epoch

    def split(self, index: int, count: int) -> "RecordDataset":
        """The index-th of `count` disjoint shard slices (for DataLoader
        `num_procs` worker processes; mirrors the per-host `shard_index`/
        `num_shards` split)."""
        out = RecordDataset.__new__(RecordDataset)
        out.files = self.files[index::count]
        out.schema = self.schema
        out.shuffle_shards = self.shuffle_shards
        out.seed = self.seed + 1000003 * index
        out.bad_record_budget = self.bad_record_budget
        out._epoch = self._epoch
        out.cursor = None  # worker slices never report the parent frontier
        return out

    def _decode(self, raw: bytes) -> dict:
        faults.fire("data.decode")
        return self.schema(decode_example(raw))

    def __iter__(self) -> Iterator[dict]:
        files = list(self.files)
        if self.shuffle_shards:
            np.random.RandomState(self.seed + self._epoch).shuffle(files)
        self._epoch += 1
        budget = self.bad_record_budget
        cur = self.cursor
        if cur is not None:
            cur.begin_epoch()
        if budget is None:
            from deep_vision_tpu.data.records import best_reader

            reader = best_reader()
            for si, path in enumerate(files):
                if cur is not None:
                    cur.begin_shard(si, path)
                for raw in reader(path):
                    sample = self._decode(raw)
                    if cur is not None:
                        cur.advance()
                    yield sample
            return
        from deep_vision_tpu.data.records import (
            BadRecordBudgetExceeded,
            read_records_tolerant,
        )

        for si, path in enumerate(files):
            if cur is not None:
                cur.begin_shard(si, path)
            for offset, raw in read_records_tolerant(path, budget):
                try:
                    sample = self._decode(raw)
                except (KeyboardInterrupt, SystemExit,
                        BadRecordBudgetExceeded):
                    raise
                except Exception as e:
                    # undecodable-but-CRC-clean records (writer bug, schema
                    # drift) burn the same budget as corrupt ones
                    budget.record_bad(
                        path, offset,
                        f"decode failed: {type(e).__name__}: {e}")
                    continue
                if cur is not None:
                    cur.advance()
                yield sample
