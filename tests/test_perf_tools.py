"""Host-side units of the round-4 perf/evidence tooling.

The chip-facing halves of these tools are exercised by their committed
artifacts; these tests pin the pure-python parts (HLO parsing, metric
conventions, procedural dataset generators) that everything downstream
trusts.
"""
import numpy as np
import pytest

from tools.hbm_breakdown import breakdown, parse_entry, shape_bytes


HLO = """\
HloModule jit_train_step

%fused_computation.1 {
  %p = bf16[8,8]{1,0} parameter(0)
  ROOT %a = bf16[8,8]{1,0} add(%p, %p)
}

ENTRY %main (p0: bf16[256,56,56,64], p1: f32[64]) -> bf16[256,56,56,64] {
  %p0 = bf16[256,56,56,64]{3,2,1,0:T(8,128)(2,1)} parameter(0)
  %p1 = f32[64]{0:T(256)} parameter(1)
  %copy.1 = bf16[256,56,56,64]{0,3,2,1:T(8,128)(2,1)} copy(%p0)
  %fusion.1 = bf16[256,56,56,64]{0,3,2,1:T(8,128)(2,1)} fusion(%copy.1, %p1), kind=kLoop, calls=%fused_computation.1
  ROOT %tuple.1 = (bf16[256,56,56,64]{0,3,2,1}) tuple(%fusion.1)
}
"""


def test_shape_bytes():
    assert shape_bytes("bf16[256,56,56,64]{3,2,1,0}") == 256 * 56 * 56 * 64 * 2
    assert shape_bytes("f32[64]{0}") == 256
    # tuple shapes sum their elements
    assert shape_bytes("(f32[2,2], bf16[4])") == 16 + 8
    assert shape_bytes("pred[8]") == 8
    assert shape_bytes("token[]") == 0


def test_parse_entry_only_entry_instructions():
    rows = list(parse_entry(HLO))
    names = [r[0] for r in rows]
    # instructions inside %fused_computation.1 must NOT appear
    assert "a" not in names and "p" not in names
    assert {"p0", "p1", "copy.1", "fusion.1", "tuple.1"} <= set(names)
    by_name = {r[0]: r for r in rows}
    assert by_name["fusion.1"][2] == "fusion"
    assert by_name["fusion.1"][3] == ["copy.1", "p1"]


def test_breakdown_accounting():
    big = 256 * 56 * 56 * 64 * 2  # one bf16 feature map
    art = breakdown(HLO)
    # copy: in big + out big; fusion: in (big + 256) + out big; parameters
    # and the tuple are plumbing with no traffic of their own
    est = art["total_estimated_gb"] * 1e3  # MB (artifact rounds to 10 MB)
    want = (2 * big + (big + 256 + big)) / 1e6
    assert est == pytest.approx(want, abs=10.0)
    rows = {r["name"]: r for r in art["top_instructions"]}
    assert rows["copy.1"]["total_mb"] == pytest.approx(2 * big / 1e6,
                                                       rel=1e-3)
    assert rows["fusion.1"]["in_mb"] == pytest.approx((big + 256) / 1e6,
                                                      rel=1e-3)
    classes = {c["class"] for c in art["by_class"]}
    assert "copy/layout" in classes


def test_aux_metric_prefix_convention():
    """'_'-prefixed aux names surface as metrics WITHOUT touching the loss;
    reserved surfaced names still raise (models/vit.py router telemetry)."""
    import jax.numpy as jnp

    from deep_vision_tpu.losses.classification import classification_loss_fn

    logits = jnp.asarray([[4.0, 0.0], [0.0, 4.0]])
    batch = {"label": jnp.asarray([0, 1])}
    base, _ = classification_loss_fn(logits, batch)
    loss, metrics = classification_loss_fn(
        (logits, {"penalty": jnp.asarray(2.0),
                  "_router_entropy": jnp.asarray(1.5)}),
        batch, penalty_weight=0.01,
    )
    assert metrics["router_entropy"] == 1.5
    # only the un-prefixed penalty moved the loss
    assert float(loss) == pytest.approx(float(base) + 0.02, abs=1e-6)
    with pytest.raises(ValueError):
        classification_loss_fn(
            (logits, {"_loss": jnp.asarray(1.0)}), batch
        )


def test_procedural_shapes_layout():
    from deep_vision_tpu.tools.convergence_run import procedural_shapes

    imgs, boxes, classes = procedural_shapes(8, size=96, seed=3)
    assert imgs.shape == (8, 96, 96, 3) and imgs.dtype == np.float32
    assert boxes.shape == (8, 3, 4) and classes.shape == (8, 3)
    valid = classes >= 0
    assert valid.any(axis=1).all()  # every image has >= 1 object
    # valid boxes are normalized, non-degenerate, in-bounds
    vb = boxes[valid]
    assert (vb[:, 2] > vb[:, 0]).all() and (vb[:, 3] > vb[:, 1]).all()
    assert (vb >= 0).all() and (vb <= 1).all()
    # padded rows are zero boxes (the DetectionEvaluator drop convention)
    assert not boxes[~valid].any()
    # deterministic per seed
    i2, b2, c2 = procedural_shapes(8, size=96, seed=3)
    np.testing.assert_array_equal(boxes, b2)
    np.testing.assert_array_equal(imgs, i2)


def test_procedural_figures_layout():
    from deep_vision_tpu.tools.convergence_run import procedural_figures

    imgs, kpts, heads = procedural_figures(6, size=64, seed=1)
    assert imgs.shape == (6, 64, 64, 3)
    assert kpts.shape == (6, 5, 2) and heads.shape == (6,)
    assert (kpts >= 0).all() and (kpts <= 1).all()
    assert (heads > 0).all() and (heads < 0.5).all()
    # the head keypoint sits inside the drawn head disc: the brightest
    # region around kpt 0 must be far above the noise floor
    for i in range(6):
        x, y = (kpts[i, 0] * 64).astype(int)
        patch = imgs[i, max(y - 2, 0):y + 3, max(x - 2, 0):x + 3]
        assert patch.max() > 0.5


def test_gratings_difficulty_knob():
    from deep_vision_tpu.tools.convergence_run import procedural_gratings

    easy, labels = procedural_gratings(4, classes=16, size=32, noise=0.05)
    hard, _ = procedural_gratings(4, classes=16, size=32, noise=0.6)
    # same class structure, different SNR: hard images have more extreme
    # clipping mass at 0/1
    clip_easy = ((easy <= 0.001) | (easy >= 0.999)).mean()
    clip_hard = ((hard <= 0.001) | (hard >= 0.999)).mean()
    assert clip_hard > clip_easy
    # 32-class variant factors 8 orientations x 4 freqs and stays in range
    imgs32, labels32 = procedural_gratings(8, classes=32, size=32)
    assert labels32.max() < 32


def test_roofline_analytic_model_matches_known_resnet50_figures():
    """The shape-math traffic/FLOP model must reproduce the published
    ResNet-50 numbers: ~8.2 GFLOP forward per image (so ~24.6 train at the
    3x convention) and a total parameter count near 25.6M."""
    from deep_vision_tpu.tools.roofline import (
        analytic_traffic,
        resnet50_conv_shapes,
    )

    a = analytic_traffic(128)
    per_img_gflop = a["train_tflops_per_step"] * 1e3 / 128
    assert 22.0 < per_img_gflop < 27.0, per_img_gflop
    params = sum(L["k"] * L["k"] * L["cin"] * L["cout"]
                 for L in resnet50_conv_shapes())
    assert 23e6 < params < 28e6, params  # conv+head (BN scales excluded)
    # the bound is a LOWER bound: far under the cost_analysis overcount
    # (~40 GB at b128) and strictly positive floors
    assert 5.0 < a["total_gb"] < 40.0
    assert a["min_step_ms_if_memory_bound"] > 0
    assert a["min_step_ms_if_compute_bound"] > 0
    # the per-layer itemization accounts for the whole total (not just the
    # top-10 excerpt that top_layers shows)
    assert abs(a["itemized_total_gb"] - a["total_gb"]) < 0.05
    assert sum(r["gb"] for r in a["top_layers"]) > 0.3 * a["total_gb"]


def test_roofline_verdict_paths():
    from deep_vision_tpu.tools.roofline import analytic_traffic, verdict

    a = analytic_traffic(128)
    assert "analytic-only" in verdict(a, None)
    # memory-bound path: device time equal to the memory floor
    v = verdict(a, {"device_step_ms": a["min_step_ms_if_memory_bound"],
                    "dma_gb_per_step": a["total_gb"]})
    assert "memory-bound" in v
    # not-bound path: device time far above both floors, low traffic
    v = verdict(a, {"device_step_ms": 10
                    * a["min_step_ms_if_memory_bound"],
                    "dma_gb_per_step": a["total_gb"]})
    assert "NOT memory-bound" in v


def test_gratings_nonfactoring_class_count_stays_in_freq_range():
    """ADVICE r4: class counts that don't factor as n_orient x n_freq must
    still map every label to a frequency inside the documented 4-13 cycles
    grid (n_freq rounds UP, never leaving labels off-grid)."""
    import math

    import numpy as np

    from deep_vision_tpu.tools.convergence_run import procedural_gratings

    for classes in (20, 30, 5):
        imgs, labels = procedural_gratings(2 * classes, classes=classes,
                                           size=32, seed=1)
        assert labels.max() < classes and np.isfinite(imgs).all()
        # the implementation's own grid: ceil'd n_freq keeps every label's
        # frequency inside [4, 13] cycles
        n_orient = 4 if classes <= 16 else 8
        n_freq = max(1, math.ceil(classes / n_orient))
        for c in range(classes):
            freq = 4.0 + (9.0 / max(1, n_freq - 1)) * (c // n_orient)
            assert 4.0 <= freq <= 13.0 + 1e-9, (classes, c, freq)
