"""Burn-rate SLO alerting: multi-window rules over the journal stream.

The serving front door (serve/transport.py) and the training loop
journal every outcome; this module turns those rows into pages. The
core rule shape is the SRE multi-window burn rate: an error budget
(say 1% of requests may fail) is "burning too fast" when the failure
ratio exceeds `budget * burn` in BOTH a fast window (seconds — catches
the incident quickly, the slow window alone would lag) and a slow
window (the guard against paging on a single unlucky blip). Training
budgets ride the same engine as single-window threshold rules:
goodput floor (obs/goodput.py `goodput_interval` rows), recompile
bursts and data starvation (`step` rows).

Determinism contract — live and offline MUST agree: the engine is a
pure state machine over **event time**. It advances only on journal-row
timestamps (`ts`), never on the wall clock, so replaying a journal
through `evaluate_journal` reproduces the exact `alert_fired`/
`alert_resolved` pairs the live tap produced while the run was up —
the fleetnet smoke asserts this literally. The price is honest: an
alert cannot resolve while no rows flow, which is also true of the
offline replay, so the two views never diverge.

Wiring:

- **live** — `AlertEngine.observe` is tap-compatible
  (`journal.add_tap(engine.observe)`); every row ingests + evaluates,
  transitions write typed `alert_fired`/`alert_resolved` events.
  `TelemetryServer.set_alerts(engine)` serves `/alertz` and fails the
  "alerts" health source while a page-severity alert is active.
- **offline** — `evaluate_journal(events, rules)` replays any journal
  (merged journals included) through a fresh engine; `pairs()` is the
  fired->resolved timeline tools/obs_report.py renders.

Every threshold is a `DVT_ALERT_*` knob (core/knobs.py registry); the
defaults keep a clean run silent — the acceptance bar is literally
"a clean run fires zero alerts".

jax-free at import.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional

from deep_vision_tpu.core import knobs
from deep_vision_tpu.obs import locksmith
from deep_vision_tpu.obs.goodput import _num

#: alert_fired / alert_resolved severity enum — mirrored in
#: tools/check_journal.py (ALERT_SEVERITIES), pinned by a drift test.
ALERT_SEVERITIES = ("page", "ticket")

#: The engine's OWN verdict rows, skipped on ingestion so the tap
#: observing its own write cannot recurse. Deliberately narrower than
#: goodput.OWN_EVENTS: goodput_interval rows are the goodput plane's
#: output but this engine's *signal* — the goodput_floor rule reads
#: them (tests/test_alerts.py pins that they are ingested).
ENGINE_OWN_EVENTS = ("alert_fired", "alert_resolved")


def _percentile(xs: List[float], q: float) -> float:
    ys = sorted(xs)
    if not ys:
        return 0.0
    idx = min(len(ys) - 1, max(0, int(round(q * (len(ys) - 1)))))
    return ys[idx]


class BurnRateRule:
    """One SRE-style multi-window burn-rate rule over a good/bad row
    classifier. Fires when the bad ratio exceeds `budget * burn` in
    both windows with at least `min_count` samples (and one bad) in
    the fast window."""

    kind = "burn_rate"

    def __init__(self, name: str, *, classify: Callable[[dict],
                                                        Optional[bool]],
                 budget: float, burn: float, fast_s: float, slow_s: float,
                 min_count: int = 4, severity: str = "page") -> None:
        assert severity in ALERT_SEVERITIES
        self.name = name
        self.severity = severity
        self.budget = float(budget)
        self.burn = float(burn)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.min_count = int(min_count)
        self._classify = classify
        self._samples: deque = deque(maxlen=65536)  # (ts, bad)

    def ingest(self, ts: float, row: dict) -> None:
        verdict = self._classify(row)
        if verdict is None:
            return
        self._samples.append((ts, bool(verdict)))

    def firing(self, now: float) -> Optional[dict]:
        """The (value, threshold) verdict dict when burning, else None."""
        while self._samples and self._samples[0][0] <= now - self.slow_s:
            self._samples.popleft()
        slow = self._samples
        if not slow:
            return None
        bad_slow = sum(1 for _, bad in slow if bad)
        fast = [(t, bad) for t, bad in slow if t > now - self.fast_s]
        bad_fast = sum(1 for _, bad in fast if bad)
        threshold = self.budget * self.burn
        if (len(fast) >= self.min_count and bad_fast >= 1
                and bad_fast / len(fast) > threshold
                and bad_slow / len(slow) > threshold):
            return {"value": round(bad_fast / len(fast), 4),
                    "threshold": round(threshold, 4),
                    "window_s": self.fast_s}
        return None

    def describe(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "severity": self.severity, "budget": self.budget,
                "burn": self.burn, "fast_s": self.fast_s,
                "slow_s": self.slow_s}


class WindowRule:
    """One single-window threshold rule: aggregate a per-row value over
    `window_s` of event time and compare against `bound`. `agg` is one
    of mean / max / p95 / delta (max - min — the shape a cumulative
    counter burst takes); `direction` "above" fires when agg > bound,
    "below" when agg < bound (goodput floor)."""

    kind = "threshold"

    def __init__(self, name: str, *, value: Callable[[dict],
                                                     Optional[float]],
                 bound: float, window_s: float, agg: str = "mean",
                 direction: str = "above", min_count: int = 2,
                 severity: str = "ticket") -> None:
        assert agg in ("mean", "max", "p95", "delta")
        assert direction in ("above", "below")
        assert severity in ALERT_SEVERITIES
        self.name = name
        self.severity = severity
        self.bound = float(bound)
        self.window_s = float(window_s)
        self.agg = agg
        self.direction = direction
        self.min_count = int(min_count)
        self._value = value
        self._samples: deque = deque(maxlen=65536)  # (ts, value)

    def ingest(self, ts: float, row: dict) -> None:
        v = self._value(row)
        if v is None:
            return
        self._samples.append((ts, float(v)))

    def firing(self, now: float) -> Optional[dict]:
        while self._samples and self._samples[0][0] <= now - self.window_s:
            self._samples.popleft()
        xs = [v for _, v in self._samples]
        if len(xs) < self.min_count:
            return None
        if self.agg == "mean":
            value = sum(xs) / len(xs)
        elif self.agg == "max":
            value = max(xs)
        elif self.agg == "p95":
            value = _percentile(xs, 0.95)
        else:  # delta
            value = max(xs) - min(xs)
        hot = (value > self.bound if self.direction == "above"
               else value < self.bound)
        if hot:
            return {"value": round(value, 4),
                    "threshold": round(self.bound, 4),
                    "window_s": self.window_s}
        return None

    def describe(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "severity": self.severity, "bound": self.bound,
                "window_s": self.window_s, "agg": self.agg,
                "direction": self.direction}


# -- the stock classifiers / value extractors ----------------------------------

def _transport_bad(row: dict) -> Optional[bool]:
    """transport_request rows: a 5xx / torn socket burns the error
    budget; sheds, deadline refusals, and client errors are policy,
    not budget burn."""
    if row.get("event") != "transport_request":
        return None
    status = _num(row, "status") or 0
    return row.get("outcome") in ("error", "torn") or status >= 500


def _transport_ok_latency(row: dict) -> Optional[float]:
    if row.get("event") != "transport_request":
        return None
    if row.get("outcome") != "ok":
        return None
    return _num(row, "latency_ms")


def _goodput_frac(row: dict) -> Optional[float]:
    if row.get("event") != "goodput_interval":
        return None
    return _num(row, "goodput_frac")


def _step_recompiles(row: dict) -> Optional[float]:
    if row.get("event") != "step":
        return None
    return _num(row, "recompiles")


def _step_starved(row: dict) -> Optional[float]:
    if row.get("event") != "step":
        return None
    wait = _num(row, "data_wait_ms")
    dispatch = _num(row, "dispatch_ms")
    if wait is None or dispatch is None:
        return None
    return 1.0 if wait > dispatch else 0.0


# -- stock rule sets (knob-tuned; a zero/negative budget disables) -------------

def default_serving_rules() -> List[object]:
    fast = knobs.get_float("DVT_ALERT_FAST_S")
    slow = knobs.get_float("DVT_ALERT_SLOW_S")
    rules: List[object] = [BurnRateRule(
        "serve_error_burn", classify=_transport_bad,
        budget=knobs.get_float("DVT_ALERT_ERROR_BUDGET"),
        burn=knobs.get_float("DVT_ALERT_BURN"),
        fast_s=fast, slow_s=slow, severity="page")]
    latency_ms = knobs.get_float("DVT_ALERT_LATENCY_BUDGET_MS")
    if latency_ms > 0:
        rules.append(WindowRule(
            "serve_latency_budget", value=_transport_ok_latency,
            bound=latency_ms, window_s=slow, agg="p95",
            direction="above", severity="ticket"))
    return rules


def default_training_rules() -> List[object]:
    slow = knobs.get_float("DVT_ALERT_SLOW_S")
    rules: List[object] = []
    floor = knobs.get_float("DVT_ALERT_GOODPUT_FLOOR")
    if floor > 0:
        rules.append(WindowRule(
            "goodput_floor", value=_goodput_frac, bound=floor,
            window_s=slow, agg="mean", direction="below",
            min_count=1, severity="ticket"))
    burst = knobs.get_int("DVT_ALERT_RECOMPILE_BURST")
    if burst > 0:
        rules.append(WindowRule(
            "recompile_burst", value=_step_recompiles, bound=float(burst),
            window_s=slow, agg="delta", direction="above",
            severity="ticket"))
    starve = knobs.get_float("DVT_ALERT_STARVATION_FRAC")
    if starve > 0:
        rules.append(WindowRule(
            "data_starvation", value=_step_starved, bound=starve,
            window_s=slow, agg="mean", direction="above",
            min_count=4, severity="ticket"))
    return rules


def default_rules() -> List[object]:
    return default_training_rules() + default_serving_rules()


class AlertEngine:
    """Evaluate a rule set over the journal stream; journal the
    transitions. `observe` is tap-compatible; all evaluation happens at
    event time (the row's ts), which is what makes the live engine and
    an offline replay bit-identical."""

    def __init__(self, rules: List[object], journal=None,
                 registry=None) -> None:
        self.journal = journal
        self._lock = locksmith.lock("obs.alerts")
        self._rules = list(rules)
        self._fired: Dict[str, dict] = {}     # name -> active verdict
        self._history: List[dict] = []        # fired->resolved pairs
        self._now: Optional[float] = None
        self._g_active = (registry.gauge("alerts_active",
                                         "alert rules currently firing")
                          if registry is not None else None)

    # -- ingestion ---------------------------------------------------------

    def observe(self, row: dict) -> None:
        """Fold one journal row in and evaluate at its timestamp.
        Tap-compatible. The engine's own output events are skipped —
        they are verdicts, not signals, and skipping them bounds the
        tap recursion a transition's write re-enters with."""
        if not isinstance(row, dict) or row.get("event") in ENGINE_OWN_EVENTS:
            return
        ts = _num(row, "ts")
        if ts is None:
            return
        with self._lock:
            self._now = ts if self._now is None else max(self._now, ts)
            for rule in self._rules:
                rule.ingest(ts, row)
            transitions = self._evaluate_locked(self._now)
        self._emit(transitions)

    def evaluate(self) -> List[dict]:
        """Re-evaluate at the last observed event time (no-op on an
        empty stream) and return the active alerts."""
        with self._lock:
            if self._now is None:
                return []
            transitions = self._evaluate_locked(self._now)
        self._emit(transitions)
        return self.active()

    def _evaluate_locked(self, now: float) -> List[dict]:
        transitions = []
        for rule in self._rules:
            verdict = rule.firing(now)
            was = self._fired.get(rule.name)
            if verdict is not None and was is None:
                active = {"rule": rule.name, "severity": rule.severity,
                          "fired_ts": now, **verdict}
                self._fired[rule.name] = active
                self._history.append(dict(active, resolved_ts=None))
                transitions.append(("alert_fired", dict(active)))
            elif verdict is None and was is not None:
                del self._fired[rule.name]
                for h in reversed(self._history):
                    if h["rule"] == rule.name and h["resolved_ts"] is None:
                        h["resolved_ts"] = now
                        break
                transitions.append(("alert_resolved", {
                    "rule": rule.name, "severity": rule.severity,
                    "dur_s": round(now - was["fired_ts"], 3)}))
        if self._g_active is not None:
            self._g_active.set(len(self._fired))
        return transitions

    def _emit(self, transitions: List[tuple]) -> None:
        if self.journal is None:
            return
        for event, fields in transitions:
            if event == "alert_fired":  # literal event types for DV204
                self.journal.write("alert_fired", **fields)
            else:
                self.journal.write("alert_resolved", **fields)

    # -- reading -----------------------------------------------------------

    def active(self) -> List[dict]:
        with self._lock:
            return [dict(v) for v in self._fired.values()]

    def has_active_page(self) -> bool:
        with self._lock:
            return any(v["severity"] == "page"
                       for v in self._fired.values())

    def pairs(self) -> List[dict]:
        """The fired->resolved timeline: one dict per firing with
        `resolved_ts` None while still active. The fleetnet smoke
        compares this list (by rule name + order) between the live
        engine and the offline replay."""
        with self._lock:
            return [dict(h) for h in self._history]

    def alertz(self) -> dict:
        """The /alertz body (obs/telemetry.py route)."""
        with self._lock:
            return {"now": self._now,
                    "active": [dict(v) for v in self._fired.values()],
                    "history": [dict(h) for h in self._history],
                    "rules": [r.describe() for r in self._rules]}


def evaluate_journal(events: List[dict],
                     rules: Optional[List[object]] = None) -> AlertEngine:
    """Offline evaluation: replay journal rows through a fresh engine
    built from the same knob-tuned rule set the live side used. Returns
    the engine; read `pairs()` / `active()` off it."""
    engine = AlertEngine(default_rules() if rules is None else rules)
    for row in events:
        if isinstance(row, dict):
            engine.observe(row)
    return engine
