"""Live-telemetry smoke: the CI teeth behind obs/telemetry.py + propagate.py.

    PYTHONPATH=. JAX_PLATFORMS=cpu python tools/live_smoke.py \
        [--workdir artifacts/live_smoke]

`make live-smoke`, a `make verify` prerequisite. Three phases:

  1. train      a REAL `train.py` subprocess with --telemetry-port 0:
                the endpoint is discovered through the run dir's
                discovery file, /metrics + /healthz + /statusz are
                scraped MID-RUN (a live step number, Prometheus text
                that parses, a 200 verdict), tools/obs_poll.py renders
                its one-line status, and after the clean exit the
                journal passes check_journal --strict with typed
                telemetry_server started/stopped events and the
                discovery file is gone.
  2. propagate  a `tools/data_service.py` subprocess (journal +
                telemetry) serving a real shard stream; one client
                `get` under an installed root trace context. The two
                journals — server-side and client-side — merge into ONE
                cross-process request timeline (root -> client hop ->
                server hop) rendered by `obs_report --merged`, and both
                pass check_journal --strict (trace ids are
                shape-validated on every event that carries them).
  3. overhead   an in-process jitted loop hammered by concurrent
                scrapers with locksmith armed: zero lock-order
                violations, ZERO recompiles caused by scraping, and the
                probed cost of a realistic 1 Hz /metrics poll stays
                under 2% of the phase-1 mean step time.

Exit status 0 = every contract held; 1 = something broke.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.smoke_util import read_jsonl  # noqa: E402


class Failures:
    def __init__(self):
        self.errors: List[str] = []

    def check(self, ok: bool, what: str) -> bool:
        print(("  ok  " if ok else "  FAIL") + f"  {what}")
        if not ok:
            self.errors.append(what)
        return ok


def _get(address: str, path: str, timeout: float = 5.0):
    """(status, body_text); HTTP error codes are returned, not raised."""
    try:
        with urllib.request.urlopen(f"http://{address}{path}",
                                    timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")
    except (OSError, urllib.error.URLError):
        return None, ""


def _get_json(address: str, path: str, timeout: float = 5.0):
    code, body = _get(address, path, timeout=timeout)
    if code is None:
        return None, None
    try:
        return code, json.loads(body)
    except ValueError:
        return code, None


def _env():
    return dict(os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu")


# -- phase 1: scrape a real training run mid-flight ---------------------------

def phase1(work: str, f: Failures) -> Optional[float]:
    from deep_vision_tpu.obs.telemetry import (
        read_discovery,
        validate_prometheus,
    )

    print("phase 1: scrape a live train.py mid-run via discovery")
    ckpt = os.path.join(work, "train_ckpt")
    jpath = os.path.join(work, "train_journal.jsonl")
    # lenet5 fake-data epochs run ~0.2 s each: 60 of them leave a
    # ~10 s stepping window to scrape mid-run after the ~3 s startup
    proc = subprocess.Popen(
        [sys.executable, "train.py", "-m", "lenet5", "--fake-data",
         "--epochs", "60", "--ckpt-dir", ckpt, "--journal", jpath,
         "--telemetry-port", "0"],
        cwd=ROOT, env=_env(), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    rec = None
    deadline = time.time() + 180
    try:
        while time.time() < deadline and proc.poll() is None and not rec:
            recs = read_discovery(ckpt)
            rec = recs[0] if recs else None
            if not rec:
                time.sleep(0.05)
        f.check(rec is not None and rec.get("role") == "train",
                "discovery file appeared under the run dir "
                f"({rec and rec['discovery_file']})")
        if rec is None:
            proc.kill()
            print(proc.communicate()[0][-2000:])
            return None
        addr = f"{rec['host']}:{rec['port']}"
        # mid-run: poll /statusz until the trainer's live step mirror
        # shows up (the run is actually training, not booting)
        live = None
        while time.time() < deadline and proc.poll() is None:
            _, row = _get_json(addr, "/statusz")
            train = ((row or {}).get("status") or {}).get("train") or {}
            if train.get("step") is not None:
                live = row
                break
            time.sleep(0.02)
        f.check(live is not None,
                "/statusz shows a live step mid-run "
                f"(step {live and live['status']['train']['step']})")
        code, text = _get(addr, "/metrics")
        problems = validate_prometheus(text) if code == 200 else ["no 200"]
        f.check(code == 200 and not problems,
                "mid-run /metrics parses as Prometheus text"
                + ("" if not problems else f" ({problems[0]})"))
        f.check("step_time_ms" in text,
                "/metrics carries the step-time histogram family")
        code, body = _get_json(addr, "/healthz")
        f.check(code == 200 and body and body.get("ok") is True,
                "mid-run /healthz answers 200")
        poll = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "obs_poll.py"),
             "--run-dir", ckpt],
            cwd=ROOT, env=_env(), stdout=subprocess.PIPE, text=True)
        f.check(poll.returncode == 0 and "train" in poll.stdout
                and "OK" in poll.stdout,
                "obs_poll renders one healthy line per process: "
                + poll.stdout.strip().splitlines()[0])
    finally:
        try:
            out = proc.communicate(timeout=max(1.0,
                                               deadline - time.time()))[0]
        except subprocess.TimeoutExpired:
            proc.kill()
            out = proc.communicate()[0]
    f.check(proc.returncode == 0,
            f"train run exited clean (rc={proc.returncode})"
            + ("" if proc.returncode == 0 else f"\n{out[-2000:]}"))
    f.check(read_discovery(ckpt) == [],
            "discovery file removed on clean exit")
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_journal.py"),
         jpath, "--strict"],
        cwd=ROOT, env=_env()).returncode
    f.check(rc == 0, "train journal passes check_journal --strict "
                     "(typed telemetry_server events included)")
    ev = read_jsonl(jpath)
    tel = [e for e in ev if e.get("event") == "telemetry_server"]
    f.check([e.get("outcome") for e in tel] == ["started", "stopped"]
            and all(e.get("port") == rec["port"] for e in tel),
            "journal carries telemetry_server started/stopped with the "
            "bound port")
    steps = [e.get("step_time_ms") for e in ev if e.get("event") == "step"
             and isinstance(e.get("step_time_ms"), (int, float))]
    return (sum(steps) / len(steps)) if steps else None


# -- phase 2: one request traced across the data-service boundary ------------

def phase2(work: str, f: Failures) -> None:
    from tools.data_smoke import SCHEMA, register_schema, write_shards

    from deep_vision_tpu.data.service import DataServiceClient
    from deep_vision_tpu.obs import RunJournal, propagate

    print("phase 2: one request, one causal timeline across processes")
    register_schema()
    data_dir = os.path.join(work, "shards")
    write_shards(data_dir)
    sj_path = os.path.join(work, "svc_journal.jsonl")
    cj_path = os.path.join(work, "client_journal.jsonl")
    boot = ("import tools.data_smoke as ds; ds.register_schema(); "
            "import tools.data_service as t; import sys; "
            "sys.exit(t.main(sys.argv[1:]))")
    proc = subprocess.Popen(
        [sys.executable, "-c", boot,
         "--pattern", os.path.join(data_dir, "train-*"),
         "--schema", SCHEMA, "--batch-size", "8", "--workers", "1",
         "--journal", sj_path, "--telemetry-port", "0"],
        cwd=ROOT, env=_env(), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        addr = tele_addr = None
        deadline = time.time() + 120
        while time.time() < deadline and proc.poll() is None:
            line = proc.stdout.readline().strip()
            if line.startswith("ready "):
                addr = line.split(" ", 1)[1]
            elif line.startswith("telemetry http://"):
                tele_addr = line.split("http://", 1)[1].split("/", 1)[0]
            if addr and tele_addr:
                break
        f.check(addr is not None and tele_addr is not None,
                f"data service up (stream {addr}, telemetry {tele_addr})")
        cj = RunJournal(cj_path, kind="train")
        cj.manifest(config={"name": "live_smoke", "task": "telemetry"})
        client = DataServiceClient(addr, name="live", journal=cj)
        # steady state first: no installed context, no per-request event
        batch = client.get()
        f.check(batch is not None, "untraced steady-state get streams")
        root = propagate.new_trace()
        with propagate.use(root):
            batch = client.get()
        f.check(batch is not None, "traced get returns a batch")
        code, body = _get_json(tele_addr, "/healthz")
        f.check(code == 200, "data-service /healthz answers 200")
        code, body = _get_json(tele_addr, "/statusz")
        served = ((body or {}).get("status") or {}).get(
            "data_service", {}).get("served")
        f.check(code == 200 and isinstance(served, int) and served >= 2,
                f"data-service /statusz shows the served ledger ({served})")
        client.close()
        cj.close()
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
    f.check(proc.returncode == 0,
            f"data service drained clean (rc={proc.returncode})")
    for path, who in ((sj_path, "service"), (cj_path, "client")):
        rc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools",
                                          "check_journal.py"),
             path, "--strict"],
            cwd=ROOT, env=_env()).returncode
        f.check(rc == 0, f"{who} journal passes check_journal --strict")
    # the causal chain: root -> client hop -> server hop, one trace id
    hops = [e for e in read_jsonl(cj_path) + read_jsonl(sj_path)
            if e.get("event") == "data_service" and e.get("op") == "get"]
    f.check(len(hops) == 2
            and len({e.get("trace_id") for e in hops}) == 1,
            "exactly the traced get journaled a hop on each side, "
            "sharing one trace id")
    client_hop = next((e for e in hops if e.get("role") == "client"), {})
    server_hop = next((e for e in hops if e.get("role") == "server"), {})
    f.check(server_hop.get("parent_span_id") == client_hop.get("span_id"),
            "server hop's parent is the client hop (causal, not merely "
            "correlated)")
    rep = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "obs_report.py"),
         cj_path, sj_path, "--merged"],
        cwd=ROOT, env=_env(), stdout=subprocess.PIPE, text=True)
    tid = client_hop.get("trace_id", "?")
    f.check(rep.returncode == 0 and "request timelines (1)" in rep.stdout
            and tid in rep.stdout and "2 process(es)" in rep.stdout,
            "obs_report --merged renders the request as ONE "
            "cross-process timeline")
    for line in rep.stdout.splitlines():
        if "trace " in line or "+" in line[:12]:
            print("   | " + line)


# -- phase 3: the overhead + safety probe -------------------------------------

def phase3(work: str, f: Failures, mean_step_ms: Optional[float]) -> None:
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.obs import RunJournal, locksmith
    from deep_vision_tpu.obs.registry import Registry
    from deep_vision_tpu.obs.stepclock import recompile_count
    from deep_vision_tpu.obs.telemetry import TelemetryServer

    print("phase 3: concurrent scrapes are free — no recompiles, no "
          "lock-order violations, <2% step-time overhead at 1 Hz")
    jpath = os.path.join(work, "probe_journal.jsonl")
    journal = RunJournal(jpath, kind="train")
    locksmith.arm(journal=journal)
    reg = Registry()
    # a realistic registry: the series a real run exports
    step_t = reg.histogram("step_time_ms", "step time")
    for name in ("excache_hits_total", "excache_misses_total",
                 "examples_total", "recompiles_total"):
        reg.counter(name, name).inc()
    for m in ("toy", "aux"):
        reg.histogram("serve_request_latency_ms", "lat",
                      labels={"model": m}).observe(1.0)
        reg.gauge("serve_queue_depth", "depth",
                  labels={"model": m}).set(0)
    loss_g = reg.gauge("loss", "loss")
    tele = TelemetryServer(port=0, role="probe", registry=reg,
                           journal=journal, discovery_dir=work)
    tele.start()
    tele.add_status("train", lambda: {"step": 0})
    tele.add_health("train", lambda: (True, {}))

    @jax.jit
    def step(x):
        return (x @ x.T).sum()

    x = jnp.ones((128, 128), jnp.float32)
    float(step(x))  # compile before the baseline
    c0 = recompile_count()
    stop = threading.Event()
    scrape_lat: List[float] = []
    failures: List[tuple] = []

    def scraper():
        while not stop.is_set():
            t0 = time.perf_counter()
            for path in ("/metrics", "/statusz", "/healthz", "/varz"):
                code, _ = _get(tele.address, path)
                if code not in (200, 503):
                    failures.append((path, code))
            scrape_lat.append((time.perf_counter() - t0) * 1e3 / 4)

    threads = [threading.Thread(target=scraper, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    hammered: List[float] = []
    for i in range(200):
        t0 = time.perf_counter()
        loss_g.set(float(step(x)))
        dt = (time.perf_counter() - t0) * 1e3
        hammered.append(dt)
        step_t.observe(dt)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    f.check(not failures, f"every scrape answered ({failures[:3]})")
    f.check(recompile_count() == c0,
            "ZERO recompiles caused by concurrent scraping")
    per_scrape_ms = (sum(scrape_lat) / len(scrape_lat)) if scrape_lat else 0
    base_ms = mean_step_ms if mean_step_ms else \
        (sum(hammered) / len(hammered))
    # a realistic poller hits /metrics ~1x/s; the step path can lose at
    # most the scrape's lock-held cost out of every 1000 ms of training
    overhead_pct = 100.0 * per_scrape_ms / 1000.0
    f.check(overhead_pct < 2.0,
            f"1 Hz scrape overhead {overhead_pct:.3f}% of step budget "
            f"(per-endpoint {per_scrape_ms:.2f} ms vs mean step "
            f"{base_ms:.2f} ms)")
    tele.close()
    report = locksmith.report()
    f.check(report["violations"] == [],
            "locksmith: zero lock-order violations under scrape load")
    locksmith.disarm()
    journal.close()
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_journal.py"),
         jpath, "--strict"],
        cwd=ROOT, env=_env()).returncode
    f.check(rc == 0, "probe journal passes check_journal --strict")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--workdir", default="artifacts/live_smoke")
    args = p.parse_args(argv)

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work)
    f = Failures()
    mean_step_ms = phase1(work, f)
    phase2(work, f)
    phase3(work, f, mean_step_ms)
    if f.errors:
        print(f"\nlive-smoke: {len(f.errors)} contract(s) BROKEN "
              f"(artifacts in {work})")
        return 1
    print(f"\nlive-smoke: the telemetry plane held every contract "
          f"(artifacts in {work})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
