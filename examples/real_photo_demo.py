"""Real photographs end-to-end: converter -> records -> DataLoader ->
fine-tune -> labeled inference overlays.

The script form of the reference's classify-a-real-photo demo
(`ResNet50.ipynb`: load a real image, run the classifier, show the label),
driven through every real subsystem instead of a notebook shortcut: the
three license-clean photographs in `tests/fixtures/real_photos/` go through
the ImageNet converter into record shards, the DataLoader decodes and
augments the actual JPEG bytes, a zoo classifier fine-tunes to the three
classes with the Trainer, and `tools/infer.py --render` restores the
checkpoint and writes `*_classified.jpg` display copies with the predicted
label drawn.

    python examples/real_photo_demo.py                # ~2-4 min on CPU
    python examples/real_photo_demo.py --model resnet50 --steps 80

Committed sample outputs: `output/demo_real_*_classified.jpg`.
"""
from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
FIXTURES = os.path.join(REPO, "tests", "fixtures", "real_photos")
PHOTOS = ("grace_hopper.jpg", "china.jpg", "flower.jpg")
SYNSETS = ("n10000001", "n10000002", "n10000003")
# model class index i = converter label i+1 mapped down by the dataset;
# index 0..2 after the records round trip
NAMES = ("Grace Hopper (US Navy portrait)",
         "pagoda (Summer Palace)",
         "orange dahlia")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="mobilenet1",
                   help="any classification config name (configs registry)")
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--out", default=os.path.join(REPO, "examples", "output"))
    p.add_argument("--workdir", default=None,
                   help="records + checkpoint dir (default: a temp dir)")
    args = p.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # this rig's site hook imports jax before the env var can take
        # effect at backend init; mirroring it into the config makes
        # `JAX_PLATFORMS=cpu python examples/real_photo_demo.py` reliable
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    import numpy as np

    from deep_vision_tpu.configs import get_config
    from deep_vision_tpu.core import CheckpointManager
    from deep_vision_tpu.data import Compose, DataLoader, RecordDataset
    from deep_vision_tpu.data import transforms as T
    from deep_vision_tpu.losses import classification_loss_fn
    from deep_vision_tpu.models import get_model
    from deep_vision_tpu.tools import infer
    from deep_vision_tpu.tools.converters import (
        build_shards,
        imagenet_annotations,
        imagenet_example,
    )
    from deep_vision_tpu.train import Trainer, build_optimizer

    cfg = get_config(args.model)
    assert cfg.task == "classification", "pick a classification config"
    work = args.workdir or tempfile.mkdtemp(prefix="real_photo_demo_")
    os.makedirs(work, exist_ok=True)

    # 1. real JPEGs -> the converter's flattened layout -> record shards
    flat = os.path.join(work, "flat")
    os.makedirs(flat, exist_ok=True)
    for synset, photo in zip(SYNSETS, PHOTOS):
        shutil.copy(os.path.join(FIXTURES, photo),
                    os.path.join(flat, f"{synset}_{photo}".replace(".jpg",
                                                                   ".JPEG")))
    synsets_txt = os.path.join(work, "synsets.txt")
    with open(synsets_txt, "w") as f:
        f.write("".join(s + "\n" for s in SYNSETS))
    records = os.path.join(work, "records")
    build_shards(imagenet_annotations(flat, synsets_txt), imagenet_example,
                 records, "train", num_shards=1)

    # 2. the real input pipeline over the records (decode + augment + batch)
    crop = cfg.eval_crop
    chain = Compose([
        T.Rescale(cfg.train_resize), T.RandomHorizontalFlip(),
        T.RandomCrop(crop), T.ToFloatNormalize(expand_gray_to_rgb=True),
    ])
    loader = DataLoader(RecordDataset(records + "/*", "imagenet"),
                        batch_size=3, transform=chain, shuffle=True,
                        drop_remainder=True)

    # 3. fine-tune to the three classes (memorization recipe: Adam, no
    # schedule — the demo's point is the path, not the recipe)
    model = get_model(cfg.model, num_classes=cfg.num_classes,
                      **cfg.model_kwargs)
    tx = build_optimizer("adam", args.lr)
    sample = jnp.ones((2, crop, crop, 3), jnp.float32)
    if cfg.model_kwargs.get("stem") == "s2d":
        sample = jnp.ones((2, crop // 2, crop // 2, 12), jnp.float32)
    ckpt_dir = os.path.join(work, "ckpt")
    trainer = Trainer(model, tx, classification_loss_fn, sample,
                      checkpoint_manager=CheckpointManager(ckpt_dir))

    def batches():
        s2d = cfg.model_kwargs.get("stem") == "s2d"
        for batch in loader:
            img = batch["image"]
            if s2d:
                from deep_vision_tpu.data.transforms import space_to_depth

                img = np.stack([space_to_depth(im) for im in img])
            yield {"image": jnp.asarray(img),
                   "label": jnp.asarray(batch["label"])}

    # one loader pass = one 3-image batch, so epochs == optimizer steps;
    # fit() checkpoints through the manager as it goes
    trainer.fit(batches, eval_data_fn=None, epochs=args.steps,
                save_every=args.steps)
    final = trainer.evaluate(batches(), epoch=args.steps)
    print(f"fine-tuned {args.model} {args.steps} steps: "
          f"loss={float(final['loss']):.4f} top1={float(final['top1']):.2f}")
    if float(final["top1"]) < 1.0:
        print("warning: did not fully memorize; overlays may be mislabeled")

    # 4. the inference CLI restores the checkpoint and renders the overlays
    names_txt = os.path.join(work, "names.txt")
    with open(names_txt, "w") as f:
        f.write("".join(n + "\n" for n in NAMES))
    os.makedirs(args.out, exist_ok=True)
    srcs = []
    for photo in PHOTOS:  # demo_real_* output names, distinct from inputs
        dst = os.path.join(work, "demo_real_" + photo)
        shutil.copy(os.path.join(FIXTURES, photo), dst)
        srcs.append(dst)
    rc = infer.main(["-m", args.model, "-c", ckpt_dir, "-o", args.out,
                     "--render", "--labels", names_txt, *srcs])
    print(f"overlays in {args.out}/demo_real_*_classified.jpg")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
