"""TensorBoard event-file writer, implemented natively (no TF dependency).

The reference logs scalars through Keras TensorBoard callbacks and
`tf.summary.scalar` (ResNet/tensorflow/train.py:268-269,
YOLO/tensorflow/train.py:159-179, 12 CycleGAN scalars at
CycleGAN/tensorflow/train.py:267-304). This writer produces the same on-disk
artifact — `events.out.tfevents.*` files TensorBoard reads — using the
record framing from `data.records` plus a hand-rolled Event/Summary proto
encoder (wire schema below), so dashboards work without TF on the host.

    Event   { 1: wall_time (double), 2: step (int64),
              3: file_version (string), 5: summary (Summary) }
    Summary { repeated 1: Value { 1: tag (string), 2: simple_value (float) } }
"""
from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

from deep_vision_tpu.data.example_codec import _tag, _write_varint
from deep_vision_tpu.data.records import RecordWriter


def _encode_event(
    wall_time: float,
    step: int = 0,
    file_version: Optional[str] = None,
    tag: Optional[str] = None,
    simple_value: Optional[float] = None,
) -> bytes:
    buf = bytearray()
    _write_varint(buf, _tag(1, 1))  # wall_time: double (wire type I64)
    buf += struct.pack("<d", wall_time)
    if step:
        _write_varint(buf, _tag(2, 0))
        _write_varint(buf, step)
    if file_version is not None:
        fv = file_version.encode()
        _write_varint(buf, _tag(3, 2))
        _write_varint(buf, len(fv))
        buf += fv
    if tag is not None:
        value = bytearray()
        tb = tag.encode()
        _write_varint(value, _tag(1, 2))
        _write_varint(value, len(tb))
        value += tb
        _write_varint(value, _tag(2, 5))  # simple_value: float (wire I32)
        value += struct.pack("<f", float(simple_value))
        summary = bytearray()
        _write_varint(summary, _tag(1, 2))
        _write_varint(summary, len(value))
        summary += value
        _write_varint(buf, _tag(5, 2))
        _write_varint(buf, len(summary))
        buf += summary
    return bytes(buf)


class SummaryWriter:
    """Minimal TensorBoard scalar writer: `scalar(tag, value, step)`.

    Satisfies the `tb_writer` interface MetricLogger consumes.
    """

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        fname = (
            f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        )
        self.path = os.path.join(logdir, fname)
        self._w = RecordWriter(self.path)
        self._w.write(_encode_event(time.time(), file_version="brain.Event:2"))
        self._w.flush()

    def scalar(self, tag: str, value: float, step: int) -> None:
        self._w.write(
            _encode_event(time.time(), step=int(step), tag=tag,
                          simple_value=float(value))
        )

    def flush(self) -> None:
        self._w.flush()

    def close(self) -> None:
        self._w.close()
