from deep_vision_tpu.losses.classification import (
    cross_entropy_loss,
    classification_loss_fn,
)
from deep_vision_tpu.losses.heatmap import (
    centernet_focal_loss,
    centernet_loss_fn,
    hourglass_loss_fn,
)
from deep_vision_tpu.losses.yolo import (
    yolo_loss_fn,
    yolo_loss_per_scale,
    yolo_train_loss_fn,
)
from deep_vision_tpu.losses import gan

__all__ = [
    "cross_entropy_loss",
    "classification_loss_fn",
    "centernet_focal_loss",
    "centernet_loss_fn",
    "hourglass_loss_fn",
    "yolo_loss_fn",
    "yolo_loss_per_scale",
    "yolo_train_loss_fn",
    "gan",
]
